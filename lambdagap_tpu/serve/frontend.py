"""Line-protocol socket front end: callers outside the process.

Until this PR every serve caller lived in-process. ``ServeFrontend``
binds a TCP socket (loopback by default) and speaks newline-delimited
JSON — one object per line, matching the ``task=serve`` loop verbs:

    {"op": "predict", "id": 1, "x": [[...]], "model": "m", "tenant": "t",
     "trace": {"id": "<trace_id>", "parent": "<span_id>"}}
    {"op": "swap",    "id": 2, "source": "model_v2.txt", "model": "m"}
    {"op": "swap_delta", "id": 8, "model": "m", "delta": {...}}
    {"op": "stats",   "id": 3, "reservoirs": true}
    {"op": "prometheus", "id": 5, "scope": "fleet"}
    {"op": "health",  "id": 4}            {"op": "models",  "id": 6}
    {"op": "signals", "id": 7}            {"op": "prefetch", "id": 9,
                                           "model": "m"}
    {"op": "artifact", "id": 10, "payload": "<b64>", "expect_hash": "..."}
    {"op": "artifact_get", "id": 11, "model": "m"}
    {"op": "shadow_on", "id": 12, "source": "cand.txt", "sample": 0.1}
    {"op": "loop_status", "id": 13}

The optional ``trace`` field carries the distributed-tracing context
(obs/trace.py): the server records frontend/serve/dispatch child spans
under the given parent, so one trace id connects the client's wall to
every hop inside the fleet. ``stats`` with ``reservoirs=true`` adds the
raw latency-reservoir states a fleet scraper merges; ``prometheus`` with
``scope="fleet"`` answers the fleet-merged exposition; ``signals`` is the
control-signal plane (router targets with a scraper attached).

Responses carry the request ``id`` back (predict responses may arrive out
of submit order — the id is the correlation key):

    {"id": 1, "ok": true, "values": [...], "generation": 0}
    {"id": 2, "ok": false, "error": "...", "kind": "SwapFailed"}

A malformed frame (bad JSON, unknown op, bad shapes) answers an
``ok=false`` frame with a null id and the connection SURVIVES — a
confused client must not take down its neighbors' streams. Numeric
fidelity: JSON floats carry Python's shortest-roundtrip repr, and
float32 -> float64 -> JSON -> float64 -> float32 is exact, so frontend
responses stay bit-identical to in-process serving (the parity test
asserts it).

``FrontendClient`` is the matching caller: ``submit`` returns a Future
resolved by a reader thread; when the socket dies, every pending future
resolves with :class:`~lambdagap_tpu.guard.ReplicaUnavailable` — never a
hang (R8 discipline) — which is exactly the signal
:class:`~lambdagap_tpu.serve.router.RemoteReplica` converts into
failover.
"""
from __future__ import annotations

import json
import socket
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from ..guard.degrade import (ReplicaUnavailable, ServeOverloaded,
                             ServeTimeout, SwapFailed, SwapRejected)
from ..infer import ArtifactMismatch
from ..obs import trace as obs_trace
from ..utils import log

# wire error kinds <-> exception classes (client re-raises the real type,
# so router/loadgen accounting is identical for local and remote replicas).
# graftlint R13 enforces that every guard/degrade.py exception class has a
# row here: an unmapped class would degrade to RuntimeError client-side
# and the router's class-dispatched failover would silently stop matching
# it (ReplicaUnavailable was exactly that gap — a replica fronting an
# all-dead fleet answered RuntimeError instead of the failover trigger)
_KINDS = {
    "ReplicaUnavailable": ReplicaUnavailable,
    "ServeOverloaded": ServeOverloaded,
    "ServeTimeout": ServeTimeout,
    "SwapFailed": SwapFailed,
    "SwapRejected": SwapRejected,
    "ArtifactMismatch": ArtifactMismatch,
    "ValueError": ValueError,
    "KeyError": KeyError,
}


def _error_frame(req_id, exc) -> dict:
    kind = type(exc).__name__
    return {"id": req_id, "ok": False, "error": str(exc),
            "kind": kind if kind in _KINDS else "RuntimeError"}


class _Conn:
    """One accepted client connection: a reader loop + a serialized
    writer. Predict responses are written from batcher worker threads
    (future callbacks), so the send side takes a per-connection mutex."""

    def __init__(self, sock: socket.socket, frontend: "ServeFrontend"
                 ) -> None:
        self.sock = sock
        self.frontend = frontend
        self._tx = threading.Lock()
        self._open = True

    def send(self, frame: dict) -> None:
        data = (json.dumps(frame) + "\n").encode()
        try:
            with self._tx:
                if self._open:
                    # graftlint: disable=R9 — deliberate: frames must not
                    # interleave, so mutual exclusion must span the whole
                    # write; frames are small, the socket is loopback-class,
                    # and the only contenders are this conn's reply callbacks.
                    # (R9 resolves _tx to a real threading.Lock identity that
                    # R5's name heuristic never sees — the old disable=R5
                    # here was inert, the R14 dead-suppression class)
                    self.sock.sendall(data)
        except OSError:
            # client went away mid-response; its futures already resolved
            # server-side, nothing to strand
            self._open = False

    def run(self) -> None:
        f = self.sock.makefile("rb")
        try:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                self.handle(raw)
        except OSError as e:
            log.debug("frontend: connection reset (%s) — normal teardown", e)
        finally:
            self._open = False
            try:
                self.sock.close()
            except OSError:
                log.debug("frontend: close raced the peer reset")
            self.frontend._forget(self)

    def handle(self, raw: bytes) -> None:
        # frame receipt time, BEFORE the decode: the frontend span of a
        # traced predict starts here, so decode cost is inside it
        self._t_in_wall = time.time()
        self._t_in = time.perf_counter()
        try:
            frame = json.loads(raw.decode())
            if not isinstance(frame, dict):
                raise ValueError("frame must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            self.send({"id": None, "ok": False,
                       "error": f"malformed frame: {e}",
                       "kind": "ValueError"})
            return
        req_id = frame.get("id")
        op = frame.get("op")
        try:
            handler = getattr(self, f"_op_{op}", None) if op else None
            if handler is None or not isinstance(op, str) \
                    or op.startswith("_"):
                raise ValueError(f"unknown op {op!r}")
            handler(req_id, frame)
        except Exception as e:           # op-level failure: answer, survive
            self.send(_error_frame(req_id, e))

    # -- ops ------------------------------------------------------------
    def _op_predict(self, req_id, frame) -> None:
        # wire trace context (docs/serving.md): {"trace": {"id", "parent"}}
        # — malformed values fall back to untraced, never to an error
        ctx = obs_trace.TraceContext.from_wire(frame.get("trace"))
        hop = ctx.child() if ctx is not None else None
        t_in_wall, t_in = self._t_in_wall, self._t_in
        x = np.asarray(frame["x"], dtype=np.float32)
        fut = self.frontend.target.submit(x, model=frame.get("model"),
                                          tenant=frame.get("tenant"),
                                          trace=hop)

        def reply(f: Future) -> None:
            exc = f.exception()
            if exc is not None:
                self.send(_error_frame(req_id, exc))
                if hop is not None:
                    obs_trace.RECORDER.record(
                        "frontend", ctx, t_in_wall,
                        time.perf_counter() - t_in,
                        span_id=hop.span_id, error=type(exc).__name__)
                return
            res = f.result()
            if hop is None:
                self.send({"id": req_id, "ok": True,
                           "values": np.asarray(res.values).tolist(),
                           "generation": int(res.generation)})
                return
            with obs_trace.RECORDER.span("encode", hop):
                self.send({"id": req_id, "ok": True,
                           "values": np.asarray(res.values).tolist(),
                           "generation": int(res.generation)})
            # the frontend span closes only after the reply hit the
            # socket: decode + serve + encode tile it (span tree
            # discipline, obs/trace.validate_tree)
            obs_trace.RECORDER.record(
                "frontend", ctx, t_in_wall, time.perf_counter() - t_in,
                span_id=hop.span_id)

        fut.add_done_callback(reply)

    def _op_swap(self, req_id, frame) -> None:
        kwargs = {}
        if frame.get("model") is not None:
            kwargs["model"] = frame["model"]
        gen = self.frontend.target.swap(frame["source"], **kwargs)
        self.send({"id": req_id, "ok": True, "generation": int(gen)})

    def _op_swap_delta(self, req_id, frame) -> None:
        # appended-trees rollout frame (serve/delta.py); a non-applying
        # delta answers SwapFailed and the old generation keeps serving
        kwargs = {}
        if frame.get("model") is not None:
            kwargs["model"] = frame["model"]
        gen = self.frontend.target.swap_delta(frame["delta"], **kwargs)
        self.send({"id": req_id, "ok": True, "generation": int(gen)})

    def _op_prefetch(self, req_id, frame) -> None:
        # placement actuation: make the model resident off the request
        # path (pays any readmission compile HERE, not on a request)
        kwargs = {}
        if frame.get("model") is not None:
            kwargs["model"] = frame["model"]
        info = self.frontend.target.prefetch(**kwargs)
        self.send({"id": req_id, "ok": True, "info": info})

    def _op_artifact(self, req_id, frame) -> None:
        # compiled-forest artifact admission (docs/serving.md "Compiled
        # forest artifacts"): the payload is the base64 of
        # ForestArtifact.to_bytes(); the content hash is verified before
        # the store mutates, so a torn/tampered frame answers
        # ArtifactMismatch and the replica compiles locally instead —
        # loudly, never serving a wrong model
        import base64
        payload = base64.b64decode(frame["payload"])
        h = self.frontend.target.admit_artifact(
            payload, expect_hash=frame.get("expect_hash"))
        self.send({"id": req_id, "ok": True, "hash": h})

    def _op_artifact_get(self, req_id, frame) -> None:
        # the publisher side: serialize a model's compiled artifact so a
        # peer (or an operator) can ship it to the rest of the fleet
        import base64
        kwargs = {}
        if frame.get("model") is not None:
            kwargs["model"] = frame["model"]
        payload = self.frontend.target.artifact_bytes(**kwargs)
        self.send({"id": req_id, "ok": True,
                   "payload": base64.b64encode(payload).decode()})

    def _op_stats(self, req_id, frame) -> None:
        # reservoirs=true adds the raw reservoir states a fleet scraper
        # merges (bounded; obs/fleet.py)
        self.send({"id": req_id, "ok": True,
                   "stats": self.frontend.target.stats_snapshot(
                       reservoirs=bool(frame.get("reservoirs")))})

    def _op_prometheus(self, req_id, frame) -> None:
        target = self.frontend.target
        if frame.get("scope") == "fleet":
            text = target.prometheus_fleet()
        else:
            text = target.prometheus()
        self.send({"id": req_id, "ok": True, "text": text})

    def _op_signals(self, req_id, frame) -> None:
        self.send({"id": req_id, "ok": True,
                   "signals": self.frontend.target.signals()})

    def _op_health(self, req_id, frame) -> None:
        health = self.frontend.target.health
        self.send({"id": req_id, "ok": True, "state": health.state(),
                   "snapshot": health.snapshot()})

    def _op_models(self, req_id, frame) -> None:
        self.send({"id": req_id, "ok": True,
                   "models": self.frontend.target.models()})

    def _op_shadow_on(self, req_id, frame) -> None:
        # arm (or with sample<=0 disarm) shadow mirroring on the fronted
        # router (docs/continuous-learning.md). Strictly off the reply
        # path: live answers are bit-identical with shadow armed.
        info = self.frontend.target.shadow_on(
            frame.get("source"), sample=float(frame.get("sample", 1.0)))
        self.send({"id": req_id, "ok": True, "shadow": info})

    def _op_loop_status(self, req_id, frame) -> None:
        # promotion state machine position (loop/controller.py): state,
        # candidate/promoted epochs, counters, live shadow window
        self.send({"id": req_id, "ok": True,
                   "status": self.frontend.target.loop_status()})


class ServeFrontend:
    """TCP front end for one serve target (a ForestServer — or anything
    with the same submit/swap/stats/health surface). ``port=0`` binds an
    ephemeral port, exposed as :attr:`port` after :meth:`start`."""

    def __init__(self, target, host: str = "127.0.0.1", port: int = 0,
                 backlog: int = 64, bind_retry_s: float = 5.0) -> None:
        self.target = target
        self.host = host
        self._port = int(port)
        self._backlog = int(backlog)
        self._bind_retry_s = max(float(bind_retry_s), 0.0)
        self._sock: Optional[socket.socket] = None
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None
        self._closed = False

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "ServeFrontend":
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # SO_REUSEADDR + a bounded EADDRINUSE retry window: a revived
        # replica re-binding its OLD fixed port must win against the dead
        # process's lingering socket (TIME_WAIT, or a SIGKILLed peer the
        # kernel has not fully reaped) instead of failing the respawn —
        # the rapid kill/revive cycle the autonomics controller drives
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        deadline = time.perf_counter() + self._bind_retry_s
        while True:
            try:
                sock.bind((self.host, self._port))
                break
            except OSError as e:
                import errno
                if (e.errno != errno.EADDRINUSE or self._port == 0
                        or time.perf_counter() >= deadline):
                    raise
                time.sleep(0.05)
        sock.listen(self._backlog)
        self._port = sock.getsockname()[1]
        self._sock = sock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"lambdagap-serve-frontend-{self._port}")
        self._accept_thread.start()
        log.info("serve frontend listening on %s:%d (newline-JSON "
                 "protocol; ops: predict/swap/swap_delta/prefetch/"
                 "artifact/artifact_get/stats/prometheus/signals/health/"
                 "models/shadow_on/loop_status)", self.host, self._port)
        return self

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                client, addr = self._sock.accept()
            except OSError:
                break                    # listener closed
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(client, self)
            with self._conn_lock:
                self._conns.add(conn)
            threading.Thread(target=conn.run, daemon=True,
                             name=f"lambdagap-serve-conn-{addr[1]}").start()

    def _forget(self, conn: _Conn) -> None:
        with self._conn_lock:
            self._conns.discard(conn)

    def close(self) -> None:
        """Stop accepting and drop connections. The target server is NOT
        closed — the frontend is a door, not the house."""
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                log.debug("frontend: listener close raced")
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                log.debug("frontend: conn shutdown raced")
        if self._accept_thread is not None:
            self._accept_thread.join(5.0)

    def __enter__(self) -> "ServeFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


class FrontendClient:
    """Async client for :class:`ServeFrontend`: one socket, one reader
    thread, futures correlated by request id."""

    def __init__(self, host: str, port: int, timeout: float = 5.0) -> None:
        from .server import ServeResult
        self._result_type = ServeResult
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(None)       # reader blocks; writes are sendall
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._tx = threading.Lock()
        self._pending_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._next_id = 0
        self.alive = True
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"lambdagap-serve-client-{port}")
        self._reader.start()

    # ------------------------------------------------------------------
    def _send(self, frame: dict) -> Future:
        fut: Future = Future()
        with self._pending_lock:
            if not self.alive:
                raise ReplicaUnavailable("frontend connection is closed")
            self._next_id += 1
            frame["id"] = self._next_id
            self._pending[self._next_id] = fut
        data = (json.dumps(frame) + "\n").encode()
        try:
            with self._tx:
                # graftlint: disable=R9 — deliberate, mirror of
                # _Conn.send: whole-frame writes must not interleave, and
                # the submit path is the only contender on this mutex
                # (R9 sees the _tx lock identity; R5's name heuristic never
                # does, so the old disable=R5 here was inert — R14 class)
                self.sock.sendall(data)
        except OSError as e:
            self._die(e)
            raise ReplicaUnavailable(
                f"frontend connection died mid-send: {e}") from e
        return fut

    def _read_loop(self) -> None:
        f = self.sock.makefile("rb")
        err: Exception = ReplicaUnavailable("frontend connection closed")
        try:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    frame = json.loads(raw.decode())
                except ValueError:
                    log.warning("frontend client: undecodable frame %r",
                                raw[:80])
                    continue
                self._resolve(frame)
        except OSError as e:
            err = ReplicaUnavailable(f"frontend connection died: {e}")
        self._die(err)

    def _resolve(self, frame: dict) -> None:
        with self._pending_lock:
            fut = self._pending.pop(frame.get("id"), None)
        if fut is None:
            return                       # stats pushed for a forgotten id
        if frame.get("ok"):
            if "values" in frame:
                fut.set_result(self._result_type(
                    np.asarray(frame["values"], dtype=np.float32),
                    int(frame.get("generation", -1))))
            else:
                fut.set_result(frame)
        else:
            exc_type = _KINDS.get(frame.get("kind"), RuntimeError)
            fut.set_exception(exc_type(frame.get("error", "remote error")))

    def _die(self, exc: Exception) -> None:
        """Terminal: resolve EVERY pending future with the transport
        error so no caller hangs on a dead socket."""
        with self._pending_lock:
            if not self.alive:
                return
            self.alive = False
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(exc)
        try:
            self.sock.close()
        except OSError:
            log.debug("frontend client: close raced the reset")

    # -- API ------------------------------------------------------------
    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> Future:
        """Async predict over the wire. ``trace`` is an incoming
        :class:`~lambdagap_tpu.obs.trace.TraceContext`; with none given,
        one is minted per the process ``serve_trace_sample`` knob — the
        client is where a fleet trace is born. The sampled context rides
        the frame's ``trace`` field and a ``client_request`` span records
        the full client-observed wall (submit -> future resolution), the
        root the server-side spans must tile."""
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        frame = {"op": "predict", "x": x.tolist()}
        if model is not None:
            frame["model"] = model
        if tenant is not None:
            frame["tenant"] = tenant
        ctx = trace if trace is not None \
            else obs_trace.RECORDER.maybe_trace()
        if ctx is None:
            return self._send(frame)
        if trace is None:                # minted here: this IS the root
            span, parent = ctx, ""
        else:
            span, parent = ctx.child(), None
        frame["trace"] = span.to_wire()
        t0_wall, t0 = time.time(), time.perf_counter()
        fut = self._send(frame)

        def _record(_f) -> None:
            obs_trace.RECORDER.record(
                "client_request", ctx, t0_wall,
                time.perf_counter() - t0, span_id=span.span_id,
                parent=parent)

        fut.add_done_callback(_record)
        return fut

    def predict(self, x, timeout: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None) -> np.ndarray:
        return self.submit(x, model=model, tenant=tenant).result(
            timeout).values

    def _call(self, op: str, timeout: Optional[float] = 30.0, **kw) -> dict:
        frame = {"op": op}
        frame.update({k: v for k, v in kw.items() if v is not None})
        return self._send(frame).result(timeout)

    def swap(self, source, model: Optional[str] = None,
             timeout: Optional[float] = 120.0) -> int:
        return int(self._call("swap", timeout=timeout, source=source,
                              model=model)["generation"])

    def swap_delta(self, delta, model: Optional[str] = None,
                   timeout: Optional[float] = 120.0) -> int:
        """Delta hot-swap over the wire: only the appended trees (plus
        header/tail) cross the socket (serve/delta.py)."""
        return int(self._call("swap_delta", timeout=timeout, delta=delta,
                              model=model)["generation"])

    def prefetch(self, model: Optional[str] = None,
                 timeout: Optional[float] = 120.0) -> dict:
        """Make a registry model resident on the remote replica now
        (placement actuation; pays any readmission off the request
        path)."""
        return self._call("prefetch", timeout=timeout, model=model)["info"]

    def push_artifact(self, payload: bytes,
                      expect_hash: Optional[str] = None,
                      timeout: Optional[float] = 120.0) -> str:
        """Ship a serialized compiled-forest artifact to the remote
        replica's store; its next matching build skips the compile
        (the fleet-wide one-compile contract). Returns the verified
        hash; a corrupt payload raises ``ArtifactMismatch``."""
        import base64
        return self._call("artifact", timeout=timeout,
                          payload=base64.b64encode(payload).decode(),
                          expect_hash=expect_hash)["hash"]

    def fetch_artifact(self, model: Optional[str] = None,
                       timeout: Optional[float] = 120.0) -> bytes:
        """The publisher side: the remote replica's serialized compiled
        artifact for ``model`` (requires predict_engine=compiled)."""
        import base64
        return base64.b64decode(
            self._call("artifact_get", timeout=timeout,
                       model=model)["payload"])

    def stats(self, timeout: Optional[float] = 30.0,
              reservoirs: bool = False) -> dict:
        return self._call("stats", timeout=timeout,
                          reservoirs=True if reservoirs else None)["stats"]

    def prometheus(self, timeout: Optional[float] = 30.0,
                   scope: Optional[str] = None) -> str:
        return self._call("prometheus", timeout=timeout,
                          scope=scope)["text"]

    def signals(self, timeout: Optional[float] = 30.0) -> dict:
        """The router-side control-signal tick (requires the remote
        frontend to front a router with a signal plane attached)."""
        return self._call("signals", timeout=timeout)["signals"]

    def health(self, timeout: Optional[float] = 30.0) -> str:
        return self._call("health", timeout=timeout)["state"]

    def models(self, timeout: Optional[float] = 30.0) -> list:
        return self._call("models", timeout=timeout)["models"]

    def shadow_on(self, source, sample: float = 1.0,
                  timeout: Optional[float] = 120.0) -> dict:
        """Arm shadow mirroring of a candidate model on the remote
        router (``sample<=0`` disarms and returns the final window).
        Shadow traffic never touches live answers — see
        docs/continuous-learning.md."""
        return self._call("shadow_on", timeout=timeout, source=source,
                          sample=sample)["shadow"]

    def loop_status(self, timeout: Optional[float] = 30.0) -> dict:
        """Where the continuous-learning state machine is: state,
        candidate/promoted epochs, counters, live shadow window."""
        return self._call("loop_status", timeout=timeout)["status"]

    def close(self) -> None:
        self._die(ReplicaUnavailable("frontend client closed"))

    def __enter__(self) -> "FrontendClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
