"""Open-loop load generation: offered load the server cannot gate.

bench_serve.py's original clients are CLOSED-loop: each keeps a bounded
window in flight, so when the server slows down the clients slow down with
it and "offered load" silently collapses to whatever the server admits —
saturation becomes unmeasurable (every closed-loop bench reports a happy
server at 100% of its own pace). The generator here is OPEN-loop: request
arrival times are fixed IN ADVANCE from an arrival rate — deterministic
(``uniform``) or Poisson (seeded ``numpy.random.Generator``; never
wall-clock random) — and submission follows that schedule regardless of
how the fleet is doing. Overload therefore shows up honestly, as shed
requests and deadline misses rather than a politely slowed client.

Metrics separate three honest numbers per round:

- **offered_rps** — the schedule, what arrived;
- **throughput_rps** — requests that completed with a value, at any
  latency;
- **goodput_rps** — requests that completed within ``deadline_ms`` of
  their SCHEDULED arrival (a late answer is as useless to a caller as no
  answer; queue time the generator spends catching up counts against the
  server, as it does in production).

Latency is measured from scheduled arrival, per request and per tenant
(bounded reservoirs). ``sweep`` walks a rate ladder to saturation and
reports the knee: the last offered rate whose goodput stays within
``good_ratio`` of offered.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..guard.degrade import (ReplicaUnavailable, ServeOverloaded,
                             ServeTimeout)
from ..obs.reservoir import Reservoir


def arrival_times(rate_rps: float, n: int, kind: str = "poisson",
                  seed: int = 0) -> np.ndarray:
    """``n`` arrival offsets (seconds from start) at ``rate_rps``.
    ``uniform`` = deterministic 1/rate spacing; ``poisson`` = exponential
    inter-arrivals from a seeded generator (reproducible across runs)."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be > 0")
    if kind == "uniform":
        return (np.arange(n, dtype=np.float64) + 1.0) / rate_rps
    if kind == "poisson":
        rng = np.random.default_rng(seed)
        return rng.exponential(1.0 / rate_rps, size=n).cumsum()
    raise ValueError(f"unknown arrival kind {kind!r} (uniform/poisson)")


def run_open_loop(submit: Callable, X: np.ndarray, rate_rps: float,
                  n_requests: int, deadline_ms: float = 50.0,
                  tenants: Optional[Dict[str, float]] = None,
                  models: Optional[Sequence[str]] = None,
                  arrival: str = "poisson", seed: int = 0,
                  settle_timeout_s: float = 30.0) -> dict:
    """One open-loop round: ``n_requests`` single-row requests offered at
    ``rate_rps`` against ``submit(x, model=, tenant=)``. Tenants (name ->
    weight) and models are drawn per-request from the seeded generator, so
    a (seed, rate, n) triple is a fully reproducible workload."""
    tenants = tenants or {"t0": 1.0}
    names = sorted(tenants)
    rng = np.random.default_rng(seed + 1)
    probs = np.asarray([tenants[t] for t in names], np.float64)
    probs /= probs.sum()
    t_assign = rng.choice(len(names), size=n_requests, p=probs)
    m_assign = (rng.integers(0, len(models), size=n_requests)
                if models else None)
    row_assign = rng.integers(0, len(X), size=n_requests)
    sched = arrival_times(rate_rps, n_requests, kind=arrival, seed=seed)
    deadline_s = deadline_ms / 1e3

    lat_all = Reservoir(8192, seed=11)
    lat_tenant = {t: Reservoir(4096, seed=13 + i)
                  for i, t in enumerate(names)}
    counts = {"ok": 0, "good": 0, "late": 0, "rejected": 0, "timeout": 0,
              "transport": 0, "error": 0}
    per_tenant = {t: {"offered": 0, "ok": 0, "good": 0, "shed": 0}
                  for t in names}
    pending = []

    t0 = time.perf_counter()
    for i in range(n_requests):
        target = t0 + sched[i]
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        tenant = names[t_assign[i]]
        per_tenant[tenant]["offered"] += 1
        model = models[m_assign[i]] if models else None
        try:
            fut = submit(X[row_assign[i]][None, :], model=model,
                         tenant=tenant)
        except (ServeOverloaded, ReplicaUnavailable):
            counts["rejected"] += 1
            per_tenant[tenant]["shed"] += 1
            continue
        # stamp the COMPLETION time in the resolving thread — settling
        # below happens much later, and late bookkeeping must not smear
        # into the latency a caller actually saw
        stamp = [0.0]
        fut.add_done_callback(
            lambda f, s=stamp: s.__setitem__(0, time.perf_counter()))
        pending.append((fut, target, tenant, stamp))
    t_offered = time.perf_counter() - t0

    settle_by = time.perf_counter() + settle_timeout_s
    for fut, target, tenant, stamp in pending:
        try:
            fut.result(timeout=max(settle_by - time.perf_counter(), 0.01))
        except ServeTimeout:
            counts["timeout"] += 1
            per_tenant[tenant]["shed"] += 1
            continue
        except (ServeOverloaded, ReplicaUnavailable):
            counts["transport"] += 1
            per_tenant[tenant]["shed"] += 1
            continue
        except Exception:
            counts["error"] += 1
            continue
        # the callback races result() by microseconds at worst; fall back
        # to now if this thread won
        done = stamp[0] or time.perf_counter()
        lat = done - target              # from SCHEDULED arrival
        counts["ok"] += 1
        per_tenant[tenant]["ok"] += 1
        lat_all.add(lat)
        lat_tenant[tenant].add(lat)
        if lat <= deadline_s:
            counts["good"] += 1
            per_tenant[tenant]["good"] += 1
        else:
            counts["late"] += 1
    elapsed = max(time.perf_counter() - t0, 1e-9)
    span = max(t_offered, 1e-9)

    def _ms(d):
        return {k: v * 1e3 for k, v in d.items()}

    return {
        "offered_rps": rate_rps,
        "achieved_offer_rps": n_requests / span,
        "arrival": arrival,
        "seed": seed,
        "n_requests": n_requests,
        "deadline_ms": deadline_ms,
        "elapsed_s": elapsed,
        "counts": counts,
        "throughput_rps": counts["ok"] / span,
        "goodput_rps": counts["good"] / span,
        "goodput_ratio": counts["good"] / n_requests,
        "latency_ms": _ms(lat_all.percentiles()),
        "per_tenant": {
            t: {**per_tenant[t],
                "latency_ms": _ms(lat_tenant[t].percentiles())}
            for t in names
        },
    }


def sweep(submit: Callable, X: np.ndarray, rates: Sequence[float],
          n_requests: int = 500, deadline_ms: float = 50.0,
          tenants: Optional[Dict[str, float]] = None,
          models: Optional[Sequence[str]] = None,
          arrival: str = "poisson", seed: int = 0,
          good_ratio: float = 0.9) -> dict:
    """Walk ``rates`` (ascending offered load) and report the saturation
    knee: the last rate whose goodput holds ``good_ratio`` of offered.
    Each round reuses the seeded workload generator, so two sweeps of the
    same config measure the same request stream."""
    rounds: List[dict] = []
    saturation = None
    for rate in rates:
        r = run_open_loop(submit, X, rate, n_requests,
                          deadline_ms=deadline_ms, tenants=tenants,
                          models=models, arrival=arrival, seed=seed)
        rounds.append(r)
        if r["goodput_ratio"] >= good_ratio:
            saturation = rate
    return {
        "rates": list(rates),
        "deadline_ms": deadline_ms,
        "good_ratio": good_ratio,
        "saturation_rps": saturation,
        "rounds": rounds,
    }
