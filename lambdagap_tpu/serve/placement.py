"""HBM-aware model placement: bin-pack models onto replicas by design.

BENCH_serve priced what an LRU accident costs: a request landing on a
replica that evicted its model pays a 174-214x p50 readmission cliff.
With every replica admitting every model under its own
``serve_hbm_budget_mb``, WHICH model is resident WHERE is decided by
arrival order — the one thing production traffic does not control. This
module decides it deliberately:

- :func:`plan_placement` — a deterministic greedy bin-pack: models in
  descending traffic order (hottest first — the model whose readmission
  would hurt most gets first pick of the budget), each assigned
  ``spread`` preferred replicas, chosen to fit the per-replica byte
  budget while balancing assigned traffic. A model too big for any
  remaining budget still gets the emptiest replica: the registry admits
  over-budget models anyway (one model is the floor), so the plan
  mirrors that reality instead of leaving the model homeless.
- :func:`plan_from_fleet` — the adapter from the fleet metric plane
  (obs/fleet.py merged snapshot: per-model requests as traffic,
  registry hbm bytes per copy) to the planner's inputs.

The plan is actuated in two places (serve/autonomics.py): the router
routes a model's traffic to its preferred replicas
(``Router.set_placement`` — requests land where the forest lives) and
the controller ``prefetch``-es newly preferred models so the readmission
compile happens off the request path. Placement is a PREFERENCE, not a
partition: failover still reaches every live replica, and a replica
asked for a non-resident model still serves it (paying the cliff the
plan exists to avoid).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence


def plan_placement(models: Dict[str, Dict], replicas: Sequence[str],
                   budget_bytes: int = 0, spread: int = 1
                   ) -> Dict[str, List[str]]:
    """model -> preferred replica names.

    ``models``: name -> ``{"bytes": per-copy device bytes,
    "traffic": request weight}`` (missing keys read as 0).
    ``budget_bytes`` is the PER-REPLICA residency budget (0 = unlimited:
    pure traffic balancing). ``spread`` preferred replicas per model
    (capped at the replica count). Deterministic: ties break on name.
    """
    names = [str(r) for r in replicas]
    if not names or not models:
        return {}
    spread = max(1, min(int(spread), len(names)))
    remaining = {r: float(budget_bytes) for r in names}
    traffic_load = {r: 0.0 for r in names}
    order = sorted(models,
                   key=lambda m: (-float(models[m].get("traffic", 0)),
                                  -float(models[m].get("bytes", 0)), m))
    plan: Dict[str, List[str]] = {}
    for model in order:
        need = float(models[model].get("bytes", 0))
        share = float(models[model].get("traffic", 0)) / spread
        chosen: List[str] = []
        for _ in range(spread):
            fits = [r for r in names
                    if r not in chosen
                    and (budget_bytes <= 0 or remaining[r] >= need)]
            pool = fits or [r for r in names if r not in chosen]
            if not pool:
                break
            # least assigned traffic wins; budget headroom then name
            # break ties — hot models spread across cold replicas
            pick = min(pool, key=lambda r: (traffic_load[r],
                                            -remaining[r], r))
            chosen.append(pick)
            traffic_load[pick] += share
            if budget_bytes > 0:
                remaining[pick] -= need
        plan[model] = chosen
    return plan


def plan_from_fleet(fleet_snap: Dict, replicas: Sequence[str],
                    budget_bytes: int = 0, spread: int = 1
                    ) -> Dict[str, List[str]]:
    """The planner fed from a fleet snapshot (obs/fleet.py): traffic is
    each model's merged request count, per-copy bytes come from the
    merged registry (summed resident bytes / resident replica count; a
    model evicted everywhere reports 0 bytes and simply packs last among
    equals — its first placement pays one compile, after which real
    bytes flow back through the next scrape)."""
    merged = (fleet_snap or {}).get("merged") or {}
    registry = merged.get("registry") or {}
    per_model = merged.get("per_model") or {}
    models: Dict[str, Dict] = {}
    for name, m in (registry.get("models") or {}).items():
        copies = max(int(m.get("resident_replicas", 0)), 1)
        models[name] = {
            "bytes": float(m.get("hbm_bytes", 0)) / copies,
            "traffic": float((per_model.get(name) or {}).get("requests", 0)),
        }
    # a model evicted EVERYWHERE at scrape time reports 0 bytes; packing
    # it as free would co-locate cold models with the hot one (the exact
    # churn placement exists to stop). Estimate unknowns at the fleet's
    # mean per-copy size — forests in one fleet are similar, and one
    # over-reservation beats an oscillating plan.
    known = [m["bytes"] for m in models.values() if m["bytes"] > 0]
    if known:
        est = sum(known) / len(known)
        for m in models.values():
            if m["bytes"] <= 0:
                m["bytes"] = est
    return plan_placement(models, replicas, budget_bytes=budget_bytes,
                          spread=spread)


def plan_changes(old: Optional[Dict[str, List[str]]],
                 new: Dict[str, List[str]]) -> Dict[str, List[str]]:
    """model -> replicas NEWLY preferred by ``new`` (the prefetch
    work-list; models whose preference set only shrank need no
    actuation — eviction happens lazily under the budget)."""
    old = old or {}
    out: Dict[str, List[str]] = {}
    for model, names in new.items():
        fresh = [r for r in names if r not in (old.get(model) or ())]
        if fresh:
            out[model] = fresh
    return out
