"""Multi-model registry: N compiled forests resident under an HBM budget.

PR 1's serve stack owned exactly one model: ``ForestServer`` held one
:class:`~lambdagap_tpu.serve.cache.CompiledForestCache` behind one swap
pointer. A fleet serves many models from one chip, so ownership moves
here: the registry owns every compiled forest, its padding buckets, its
generation pointer, and its hot-swap — the server keeps only policy
(batching, shedding, health).

Residency is governed by an explicit byte budget (``serve_hbm_budget_mb``):
each compiled forest charges its device-array footprint
(:attr:`CompiledForestCache.hbm_bytes`), and admitting a forest past the
budget evicts least-recently-used models first. Eviction frees the device
forest and its compiled executables but RETAINS the host-side model and
the generation pointer, so a later request re-admits it with exactly one
recompile and an unchanged generation — evictions and re-admissions are
counted in :class:`~lambdagap_tpu.serve.stats.ServeStats` because every
one of them is a latency cliff an operator must see.

Lock discipline (graftlint R5): the registry lock guards only the name
map, LRU metadata, and pointer flips — forest loads and compiles happen
OUTSIDE it. Concurrent first-uses of an evicted model single-flight
through a per-entry pending event (waiters park on the event, not on a
lock held across the compile); concurrent swaps of one model serialize on
that entry's writer lock exactly like the PR 1 ``SwapController`` did.

Generation semantics are per model: every model's generations count up
from 0 independently, every response carries the generation that produced
it, and a swap pre-warms before the pointer flip — in-flight batches
finish on the forest they started with.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, List, Optional

from ..guard.degrade import CircuitBreaker, SwapFailed, SwapRejected
from ..utils import log
from .swap import load_booster

DEFAULT_MODEL = "default"


class ModelEntry:
    """One registered model: host booster + (maybe) its compiled forest.

    ``cache`` is the residency pointer — ``None`` means evicted. It is
    read lock-free by the dispatch path (an atomic reference under the
    GIL); all writes happen under the registry lock. ``breaker`` guards
    this model's hot-swaps. ``active`` aliases ``cache`` for
    compatibility with the PR 1 single-model swap-controller surface.
    """

    __slots__ = ("name", "gbdt", "generation", "cache", "bytes", "width",
                 "engine", "buckets", "builds", "last_used", "breaker",
                 "pending", "swap_lock")

    def __init__(self, name: str, breaker: CircuitBreaker) -> None:
        self.name = name
        self.gbdt = None
        self.generation = -1             # no generation admitted yet
        self.cache = None                # CompiledForestCache or None
        self.bytes = 0
        self.width = 1
        self.engine = "tensor"
        self.buckets: tuple = ()
        self.builds = 0                  # compiles: install + swaps + readmits
        self.last_used = 0
        self.breaker = breaker
        self.pending: Optional[threading.Event] = None   # single-flight
        self.swap_lock = threading.Lock()                # writers only

    @property
    def active(self):
        return self.cache

    @property
    def resident(self) -> bool:
        return self.cache is not None


class ModelRegistry:
    """Name -> :class:`ModelEntry` map with LRU eviction under a byte
    budget.

    ``build_cache(gbdt, generation) -> CompiledForestCache`` is supplied
    by the server (it closes over the bucket/engine/warmup policy); the
    registry decides *when* to call it — install, swap, re-admission —
    and what to evict to make the result fit.
    """

    def __init__(self, build_cache: Callable, stats=None,
                 hbm_budget_bytes: int = 0,
                 breaker_threshold: int = 3,
                 artifact_store=None) -> None:
        self._build = build_cache
        self._stats = stats
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self._breaker_threshold = int(breaker_threshold)
        # shared infer.ArtifactStore (compiled engine): builds consult it
        # by source key before compiling, and admit_artifact() feeds it
        # peer-shipped compiles so the whole fleet pays for ONE lowering
        self.artifacts = artifact_store
        self._lock = threading.Lock()    # name map + LRU metadata + flips
        self._entries: Dict[str, ModelEntry] = {}
        self._seq = itertools.count(1)

    # -- introspection --------------------------------------------------
    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def entry(self, name: str) -> ModelEntry:
        with self._lock:
            e = self._entries.get(name)
        if e is None:
            raise KeyError(f"unknown serve model {name!r} "
                           f"(registered: {self.names() or 'none'})")
        return e

    def generation(self, name: str = DEFAULT_MODEL) -> int:
        return self.entry(name).generation

    # -- admission ------------------------------------------------------
    def install(self, name: str, source, params=None) -> int:
        """Register a new model under ``name`` and compile it (generation
        0). Duplicate names are an error — use :meth:`swap` to replace a
        registered model's forest."""
        breaker = CircuitBreaker(threshold=self._breaker_threshold)
        with self._lock:
            if name in self._entries:
                raise ValueError(f"serve model {name!r} is already "
                                 "registered; swap() replaces it")
            e = self._entries[name] = ModelEntry(name, breaker)
            # a get() racing the install parks on this event instead of
            # finding a half-built entry
            e.pending = threading.Event()
        try:
            gbdt = load_booster(source, params)
            cache = self._build(gbdt, 0)
            self._admit(e, gbdt, cache)
        except Exception:
            with self._lock:             # failed install leaves no entry
                self._entries.pop(name, None)
            raise
        finally:
            with self._lock:
                ev, e.pending = e.pending, None
            ev.set()
        log.info("serve registry: installed model %r (%d bytes resident, "
                 "%d models registered)", name, e.bytes, len(self._entries))
        return 0

    def get(self, name: str = DEFAULT_MODEL,
            info: Optional[Dict] = None):
        """The resident compiled forest for ``name`` — touching LRU, and
        re-admitting (ONE recompile, generation preserved) if the model
        was evicted. Concurrent callers of an evicted model single-flight
        the rebuild; the losers park on an event, never on a lock held
        across the compile.

        ``info`` (optional dict) is filled with what the resolve cost:
        ``readmitted=True`` + ``build_s`` when THIS call paid the
        recompile, ``waited=True`` when it parked behind another caller's
        rebuild — the per-request visibility of the readmission cliff
        that request tracing records as the ``registry_get`` span."""
        while True:
            with self._lock:
                e = self._entries.get(name)
                if e is None:
                    raise KeyError(f"unknown serve model {name!r} "
                                   f"(registered: "
                                   f"{sorted(self._entries) or 'none'})")
                e.last_used = next(self._seq)
                cache = e.cache
                if cache is not None:
                    return cache
                if e.pending is None:
                    e.pending = threading.Event()
                    waiter = None
                else:
                    waiter = e.pending
                gbdt, gen = e.gbdt, e.generation
            if waiter is not None:
                if info is not None:
                    info["waited"] = True
                waiter.wait(60.0)
                continue
            try:
                t0 = time.perf_counter()
                cache = self._build(gbdt, gen)   # outside every lock
                if info is not None:
                    info["readmitted"] = True
                    info["build_s"] = time.perf_counter() - t0
                    ah = getattr(cache, "artifact_hash", None)
                    if ah:                       # compiled engine: which
                        info["artifact_hash"] = ah  # artifact was rebuilt
                admitted = self._admit(e, gbdt, cache, readmission=True,
                                       expect_generation=gen)
            finally:
                with self._lock:
                    ev, e.pending = e.pending, None
                ev.set()
            if not admitted:
                # a concurrent swap published a newer generation while we
                # rebuilt the old one: drop the stale build and re-resolve
                continue
            log.info("serve registry: re-admitted evicted model %r "
                     "(generation %d preserved, %d bytes)", name,
                     e.generation, e.bytes)
            return cache

    def swap(self, name: str, source, params=None,
             background: bool = False):
        """Replace model ``name``'s forest (path / model text / Booster /
        GBDT): load + compile + pre-warm OFF the serving path, then flip
        the entry's residency pointer. A failed load/compile raises
        :class:`SwapFailed` without touching the old forest (structural
        rollback) and feeds this model's circuit breaker; an open circuit
        rejects up front with :class:`SwapRejected`. Works on evicted
        entries too — the swap admits the NEW forest, so the old one is
        never recompiled just to be replaced."""
        e = self.entry(name)

        def work() -> int:
            if not e.breaker.allow():
                raise SwapRejected(
                    f"swap circuit for model {name!r} open after "
                    f"{e.breaker.consecutive_failures} consecutive "
                    f"failures; serving continues on generation "
                    f"{e.generation} (cooldown {e.breaker.cooldown_s:g}s)")
            try:
                gbdt = load_booster(source, params)
                with e.swap_lock:
                    gen = e.generation + 1
                    # graftlint: disable=R5 — deliberate, the PR 1
                    # SwapController discipline: swap_lock serializes
                    # WRITERS of one entry only (concurrent swaps apply in
                    # call order); the dispatch path reads entry.cache
                    # lock-free, so the build convoys no request
                    cache = self._build(gbdt, gen)
                    self._admit(e, gbdt, cache)
            except Exception as exc:
                e.breaker.record_failure()
                if self._stats is not None:
                    self._stats.record_swap_failure()
                log.warning("serve registry: swap of model %r failed (%s); "
                            "generation %d keeps serving (breaker: %s)",
                            name, exc, e.generation, e.breaker.state())
                raise SwapFailed(
                    f"swap of model {name!r} failed ({exc}); serving "
                    f"continues on generation {e.generation}") from exc
            e.breaker.record_success()
            if self._stats is not None:
                self._stats.record_swap()
            log.info("serve registry: swapped model %r to generation %d "
                     "(%s engine, pre-warmed before the flip)", name, gen,
                     cache.engine)
            return gen

        if background:
            t = threading.Thread(target=work, daemon=True,
                                 name=f"lambdagap-serve-swap-{name}")
            t.start()
            return t
        return work()

    def swap_delta(self, name: str, delta, faults=None):
        """Delta hot-swap (serve/delta.py): reconstruct the new model
        text from this entry's RESIDENT host model + the appended-trees
        frame, then take the normal :meth:`swap` path — compile,
        pre-warm, pointer flip, circuit breaker. A delta that does not
        apply (stale base, wrong hash, torn frame) raises
        :class:`SwapFailed` through the same breaker-fed rollback the
        full swap uses: the active generation keeps serving."""
        from .delta import apply_delta, model_text_of
        e = self.entry(name)
        try:
            if faults is not None:
                faults.delta_swap_fault()
            base_text = model_text_of(e.gbdt)
            new_text = apply_delta(base_text, delta)
        except Exception as exc:
            e.breaker.record_failure()
            if self._stats is not None:
                self._stats.record_swap_failure()
            log.warning("serve registry: delta swap of model %r failed to "
                        "apply (%s); generation %d keeps serving "
                        "(breaker: %s)", name, exc, e.generation,
                        e.breaker.state())
            raise SwapFailed(
                f"delta swap of model {name!r} failed to apply ({exc}); "
                f"serving continues on generation {e.generation}") from exc
        return self.swap(name, new_text)

    def admit_artifact(self, payload: bytes,
                       expect_hash: Optional[str] = None) -> str:
        """Admit a peer-shipped compiled-forest artifact into this
        replica's :class:`~lambdagap_tpu.infer.ArtifactStore` (content
        hash verified BEFORE the store mutates — a torn or tampered frame
        raises :class:`~lambdagap_tpu.infer.ArtifactMismatch` and the
        next build falls back loudly to a local compile, never to a
        wrong-model serve). Returns the verified hash; later builds whose
        source key matches skip the compiler entirely
        (``compile_shared_total``)."""
        if self.artifacts is None:
            from ..infer import ArtifactStore
            self.artifacts = ArtifactStore()
        art = self.artifacts.admit_bytes(payload, expect_hash=expect_hash)
        log.info("serve registry: admitted compiled artifact %s "
                 "(%d trees, %d bytes) by hash — local compile skipped on "
                 "next matching build", art.hash[:12], art.num_trees,
                 art.nbytes)
        return art.hash

    def artifact_bytes(self, name: str = DEFAULT_MODEL) -> bytes:
        """Serialized compiled artifact of model ``name`` — what a
        publisher ships to peers over the delta plane so N replicas
        share ONE compile. Requires the compiled engine (the artifact is
        attached at cache build time)."""
        cache = self.get(name)
        art = getattr(cache, "artifact", None)
        if art is None:
            raise ValueError(
                f"serve model {name!r} has no compiled artifact (engine "
                f"{cache.engine!r}; artifact sharing needs "
                f"predict_engine=compiled)")
        return art.to_bytes()

    def model_text(self, name: str = DEFAULT_MODEL) -> str:
        """The resident host model's full text — the base a delta
        publisher diffs against (host models survive eviction, so this
        never recompiles anything)."""
        from .delta import model_text_of
        return model_text_of(self.entry(name).gbdt)

    def remove(self, name: str) -> None:
        """Forget a model entirely (device AND host side). In-flight
        batches that already hold its compiled forest finish normally."""
        with self._lock:
            e = self._entries.pop(name, None)
        if e is None:
            raise KeyError(f"unknown serve model {name!r}")
        log.info("serve registry: removed model %r", name)

    # -- residency ------------------------------------------------------
    def _admit(self, e: ModelEntry, gbdt, cache, readmission: bool = False,
               expect_generation: Optional[int] = None) -> bool:
        """Flip ``e`` to the freshly built ``cache``, evicting LRU models
        first when the budget demands it. The build already happened —
        admission is pointer work under the registry lock. With
        ``expect_generation`` set (re-admission), the flip is abandoned if
        a concurrent swap already published a newer generation — a stale
        rebuild must never roll a model back."""
        need = cache.hbm_bytes
        evicted: List[str] = []
        with self._lock:
            if (expect_generation is not None
                    and e.generation != expect_generation):
                return False
            if self.hbm_budget_bytes > 0:
                resident = sorted(
                    (o for o in self._entries.values()
                     if o is not e and o.cache is not None),
                    key=lambda o: o.last_used)
                used = sum(o.bytes for o in resident) + (
                    e.bytes if e.cache is not None else 0)
                for victim in resident:
                    if used + need <= self.hbm_budget_bytes:
                        break
                    victim.cache = None          # atomic un-publish
                    used -= victim.bytes
                    evicted.append(victim.name)
                if used + need > self.hbm_budget_bytes:
                    log.warning(
                        "serve registry: model %r alone (%d bytes) exceeds "
                        "serve_hbm_budget_mb (%d bytes); admitting anyway "
                        "— the budget bounds the fleet, one model is the "
                        "floor", e.name, need, self.hbm_budget_bytes)
            e.gbdt = gbdt
            e.generation = cache.generation
            e.cache = cache
            e.bytes = need
            e.width = cache.width
            e.engine = cache.engine
            e.buckets = tuple(cache.buckets)
            e.builds += 1
            e.last_used = next(self._seq)
        for name in evicted:
            if self._stats is not None:
                self._stats.record_eviction(model=name)
            log.info("serve registry: evicted model %r under the HBM "
                     "budget (host model retained; next use recompiles)",
                     name)
        if readmission and self._stats is not None:
            self._stats.record_readmission(model=e.name)
        return True

    # -- reporting ------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            models = {}
            resident_bytes = 0
            for name, e in sorted(self._entries.items()):
                models[name] = {
                    "resident": e.cache is not None,
                    "generation": e.generation,
                    "hbm_bytes": e.bytes if e.cache is not None else 0,
                    "builds": e.builds,
                    "width": e.width,
                    "engine": e.engine,
                }
                if e.cache is not None:
                    resident_bytes += e.bytes
                    ah = getattr(e.cache, "artifact_hash", None)
                    if ah:
                        models[name]["artifact_hash"] = ah
            return {
                "models": models,
                "resident_models": sum(1 for m in models.values()
                                       if m["resident"]),
                "registered_models": len(models),
                "hbm_bytes_resident": resident_bytes,
                "hbm_budget_bytes": self.hbm_budget_bytes,
            }
