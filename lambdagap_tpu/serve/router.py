"""Replica router: health-aware dispatch over shared-nothing serve workers.

A fleet is several :class:`~lambdagap_tpu.serve.server.ForestServer`
replicas — in-process (:class:`LocalReplica`) or behind a socket front end
(:class:`RemoteReplica`, serve/frontend.py) — that share NOTHING: each has
its own registry, batcher, and device executables. The router owns only
dispatch policy:

- **health-aware placement**: replicas reporting ``ok`` are preferred;
  ``degraded`` replicas serve only when no ok replica exists; ``draining``
  and dead replicas never take new work. Among candidates the least
  outstanding-requests replica wins (join-shortest-queue).
- **failover, never stranding** (graftlint R8 discipline): a request whose
  replica dies mid-flight — transport error, closed server, injected
  dispatch fault — is resubmitted once per remaining live replica; only
  when every replica has been tried (or none exists) does the caller see
  :class:`~lambdagap_tpu.guard.ReplicaUnavailable`. Every future the
  router hands out therefore terminates: result, per-request error
  (shape/timeout/overload), or an explicit no-replica rejection.
- **overload spill**: a replica rejecting at admission
  (:class:`ServeOverloaded`) is treated as momentarily full, and the
  request spills to the next candidate; only an all-full fleet surfaces
  the rejection.

Request-level failures (``ServeTimeout``, shape errors, unknown model) are
NOT failed over: the request itself is at fault, and replaying it
elsewhere would double latency for a deterministic error.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

from ..guard.degrade import (DEGRADED, DRAINING, OK, ReplicaUnavailable,
                             ServeOverloaded)
from ..guard.faults import InjectedFault
from ..obs import trace as obs_trace
from ..utils import log

# exceptions that indict the REPLICA, not the request: these trigger
# failover to another replica (transport failures additionally mark the
# replica dead until the router is rebuilt)
FAILOVER_EXCEPTIONS = (ReplicaUnavailable, ConnectionError, OSError,
                       InjectedFault)
_DEAD_MARKING = (ReplicaUnavailable, ConnectionError, OSError)


class LocalReplica:
    """An in-process ForestServer as a routable replica."""

    def __init__(self, name: str, server) -> None:
        self.name = name
        self.server = server

    def respawn(self) -> "LocalReplica":
        """A fresh in-process server under the same name, warmed from
        the dead server's registry-retained HOST models (eviction and
        close never drop those) — the local revival primitive
        (serve/autonomics.py). Generations restart at 0 on the new
        server; model identity is the host model text, not the counter."""
        from .registry import DEFAULT_MODEL
        from .server import ForestServer
        reg = self.server.registry
        server = ForestServer(reg.entry(DEFAULT_MODEL).gbdt,
                              buckets=self.server._buckets,
                              raw_score=self.server.raw_score,
                              start_iteration=self.server._si,
                              num_iteration=self.server._ni)
        for name in reg.names():
            if name != DEFAULT_MODEL:
                server.add_model(name, reg.entry(name).gbdt)
        return LocalReplica(self.name, server)

    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> Future:
        try:
            return self.server.submit(x, model=model, tenant=tenant,
                                      trace=trace)
        except RuntimeError as e:
            if "closed" in str(e):       # a closed server is a dead replica
                raise ReplicaUnavailable(
                    f"replica {self.name!r} is closed") from e
            raise

    def health(self) -> str:
        return self.server.health.state()

    def close(self) -> None:
        self.server.close()


class RemoteReplica:
    """A serve worker behind a socket frontend (serve/frontend.py) as a
    routable replica. Health is polled over the wire and cached for
    ``health_ttl_s`` so the dispatch path never blocks on a health RPC; a
    transport failure reports the replica dead immediately."""

    def __init__(self, name: str, host: str, port: int,
                 health_ttl_s: float = 0.5, connect_timeout: float = 5.0
                 ) -> None:
        from .frontend import FrontendClient
        self.name = name
        # the address survives on the replica object so a revival can
        # reconnect the SAME endpoint (serve/autonomics.py)
        self.host = host
        self.port = int(port)
        self._connect_timeout = float(connect_timeout)
        self.client = FrontendClient(host, port, timeout=connect_timeout)
        self._ttl = float(health_ttl_s)
        self._health = OK
        self._health_at = 0.0
        self._health_lock = threading.Lock()

    def reconnect(self) -> "RemoteReplica":
        """A FRESH replica object for the same name/address — the remote
        revival primitive. Raises (ConnectionError/OSError) while the
        endpoint is still down; the revival backoff absorbs that."""
        return RemoteReplica(self.name, self.host, self.port,
                             health_ttl_s=self._ttl,
                             connect_timeout=self._connect_timeout)

    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> Future:
        return self.client.submit(x, model=model, tenant=tenant,
                                  trace=trace)

    def health(self) -> str:
        import time
        if not self.client.alive:
            return "dead"
        now = time.perf_counter()
        with self._health_lock:
            fresh = now - self._health_at < self._ttl
            if fresh:
                return self._health
            self._health_at = now        # one prober per TTL window
        try:
            state = self.client.health(timeout=self._ttl)
        except Exception:                # transport failed: replica is dead
            state = "dead"
        with self._health_lock:
            self._health = state
        return state

    def close(self) -> None:
        self.client.close()


class Router:
    """Health-aware dispatch + failover over a replica group.

    ``replicas`` can mix :class:`LocalReplica` and :class:`RemoteReplica`.
    ``own_replicas=True`` makes :meth:`close` close them too.
    """

    def __init__(self, replicas: Sequence, own_replicas: bool = False
                 ) -> None:
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self._replicas = list(replicas)
        self._own = bool(own_replicas)
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {r.name: 0 for r in replicas}
        self._routed: Dict[str, int] = {r.name: 0 for r in replicas}
        self._dead: Dict[str, bool] = {r.name: False for r in replicas}
        # probation: a revived replica serves in the DEGRADED tier until
        # the autonomics controller promotes it (docs/robustness.md);
        # placement: model -> preferred replica names holding it resident
        # (serve/placement.py). Both empty unless a controller is active,
        # so knob-off router snapshots stay byte-identical to pre-PR.
        self._probation: Dict[str, bool] = {}
        self._placement: Dict[str, tuple] = {}
        self._failovers = 0
        self._rejected_no_replica = 0
        self._closed = False
        self._scraper = None             # obs.fleet.FleetScraper, attached
        self._autonomics = None          # serve.autonomics.Autonomics
        self._shadow = None              # serve.shadow.ShadowMirror, armed
        self._loop = None                # loop.controller.PromotionController

    # -- dispatch -------------------------------------------------------
    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> "Future":
        """Route one request; returns a Future of ``ServeResult``. The
        future ALWAYS terminates: a dead replica's in-flight requests are
        failed over to the remaining live replicas, and only a fleet with
        no live replica rejects (:class:`ReplicaUnavailable`). A sampled
        ``trace`` context gets a ``route`` span covering pick + failover
        until the future resolves (attrs: the replica that answered, the
        failover count paid)."""
        if self._closed:
            raise RuntimeError("router closed")
        outer: Future = Future()
        ctx = trace if trace is not None \
            else obs_trace.RECORDER.maybe_trace()
        hop = None
        if ctx is not None:
            hop = ctx.child()            # the route span's own context
            t0_wall, t0 = time.time(), time.perf_counter()
            route_state = {"replica": None, "failovers": 0}

            def _record(_f) -> None:
                obs_trace.RECORDER.record(
                    "route", ctx, t0_wall, time.perf_counter() - t0,
                    span_id=hop.span_id,
                    replica=route_state["replica"],
                    failovers=route_state["failovers"])

            outer.add_done_callback(_record)
            self._attempt(outer, x, model, tenant, tried=set(),
                          trace=hop, route_state=route_state)
        else:
            self._attempt(outer, x, model, tenant, tried=set())
        # shadow mirroring rides AFTER the live dispatch is in flight and
        # owns no stake in ``outer``: a coin flip + worker handoff, so a
        # dead/slow shadow cannot move a live answer (serve/shadow.py)
        mirror = self._shadow
        if mirror is not None:
            mirror.maybe_mirror(x, model, tenant, outer, ctx)
        return outer

    def predict(self, x, timeout: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None):
        return self.submit(x, model=model, tenant=tenant).result(
            timeout).values

    def _pick(self, tried: set, model: Optional[str] = None):
        """Least-loaded replica among the healthiest available tier.
        Probation replicas (freshly revived) are demoted to the DEGRADED
        tier regardless of reported health; when a placement plan names
        replicas holding ``model`` resident, those are preferred within
        the winning tier — model traffic stays where the forest already
        lives, so readmission cliffs are paid by placement decisions,
        never by routing accidents."""
        with self._lock:
            candidates = [r for r in self._replicas
                          if r.name not in tried and not self._dead[r.name]]
            resident = self._placement.get(model, ()) if model else ()
            probation = dict(self._probation)
        by_state: Dict[str, List] = {}
        for r in candidates:
            try:
                state = r.health()
            except Exception:            # pragma: no cover — health probe
                state = "dead"           # died under us: skip it
            if state in (DRAINING, "dead"):
                if state == "dead":
                    self._mark_dead(r)
                continue
            if state == OK and probation.get(r.name):
                state = DEGRADED         # revived: serves, never preferred
            by_state.setdefault(state, []).append(r)
        tier = by_state.get(OK) or by_state.get(DEGRADED) or []
        if not tier:
            return None
        if resident:
            preferred = [r for r in tier if r.name in resident]
            if preferred:
                tier = preferred
        with self._lock:
            return min(tier, key=lambda r: self._inflight[r.name])

    def _attempt(self, outer: Future, x, model, tenant, tried: set,
                 trace=None, route_state: Optional[Dict] = None) -> None:
        while True:
            replica = self._pick(tried, model=model)
            if replica is None:
                with self._lock:
                    self._rejected_no_replica += 1
                outer.set_exception(ReplicaUnavailable(
                    "no live replica can take the request "
                    f"(tried: {sorted(tried) or 'none'})"))
                return
            tried.add(replica.name)
            try:
                inner = replica.submit(x, model=model, tenant=tenant,
                                       trace=trace)
            # graftlint: disable=R8 — the continue re-enters the pick
            # loop, every exit of which terminates the future: a
            # successful submit chains resolution to on_done, and an
            # exhausted fleet set_exception()s ReplicaUnavailable above
            except FAILOVER_EXCEPTIONS as e:
                self._note_failure(replica, e)
                if route_state is not None:
                    route_state["failovers"] += 1
                continue                 # submit-time failover
            # graftlint: disable=R8 — same loop contract as above: spill
            # to a peer, or the empty-pick branch resolves the future
            except ServeOverloaded:
                with self._lock:
                    self._failovers += 1
                if route_state is not None:
                    route_state["failovers"] += 1
                continue                 # overload spill: try a peer
            except Exception as e:
                outer.set_exception(e)   # request-level error: no replay
                return
            break
        with self._lock:
            self._inflight[replica.name] += 1
            self._routed[replica.name] += 1
        if route_state is not None:
            route_state["replica"] = replica.name

        def on_done(f: Future) -> None:
            with self._lock:
                self._inflight[replica.name] -= 1
            exc = f.exception()
            if exc is None:
                outer.set_result(f.result())
            elif isinstance(exc, FAILOVER_EXCEPTIONS):
                # in-flight failover: the replica died under the request
                self._note_failure(replica, exc)
                if route_state is not None:
                    route_state["failovers"] += 1
                self._attempt(outer, x, model, tenant, tried,
                              trace=trace, route_state=route_state)
            else:
                outer.set_exception(exc)

        inner.add_done_callback(on_done)

    def _mark_dead(self, replica) -> None:
        with self._lock:
            already = self._dead[replica.name]
            self._dead[replica.name] = True
        if not already:
            log.warning("router: replica %r reports dead health; removed "
                        "from dispatch", replica.name)

    def _note_failure(self, replica, exc) -> None:
        with self._lock:
            self._failovers += 1
            if isinstance(exc, _DEAD_MARKING):
                self._dead[replica.name] = True
        log.warning("router: replica %r failed (%s); failing over%s",
                    replica.name, exc,
                    " and marking it dead"
                    if isinstance(exc, _DEAD_MARKING) else "")

    # -- replica lifecycle (the autonomics actuation surface; every
    # -- method takes the lock only around pointer/metadata flips — the
    # -- reconnect/respawn/compile work happens in the CALLER, outside
    # -- any router lock, which graftlint R9 enforces) ------------------
    def add_replica(self, replica, probation: bool = False) -> None:
        """Join a new replica to the rotation (scale-out). Name must be
        fresh; ``probation=True`` starts it in the degraded tier."""
        with self._lock:
            if any(r.name == replica.name for r in self._replicas):
                raise ValueError(f"replica name {replica.name!r} is "
                                 "already registered; use replace_replica")
            self._replicas.append(replica)
            self._inflight[replica.name] = 0
            self._routed.setdefault(replica.name, 0)
            self._dead[replica.name] = False
            if probation:
                self._probation[replica.name] = True
        log.info("router: replica %r joined the rotation%s", replica.name,
                 " (probation)" if probation else "")

    def replace_replica(self, name: str, replica,
                        probation: bool = True) -> None:
        """Swap a (typically dead) replica object for a freshly
        reconnected/respawned one under the SAME name — the revival
        flip. The new replica re-enters at probation (degraded tier)
        until the controller's probe window clears it. The old replica
        object is closed best-effort outside the lock."""
        if replica.name != name:
            raise ValueError(f"replacement replica is named "
                             f"{replica.name!r}, not {name!r}")
        with self._lock:
            idx = next((i for i, r in enumerate(self._replicas)
                        if r.name == name), None)
            if idx is None:
                raise KeyError(f"unknown replica {name!r}")
            old = self._replicas[idx]
            self._replicas[idx] = replica
            self._inflight[name] = 0
            self._dead[name] = False
            if probation:
                self._probation[name] = True
        if old is not replica:
            try:
                old.close()
            except Exception as e:       # a dead replica may fail to close
                log.debug("router: closing replaced replica %r failed: %s",
                          name, e)
        log.info("router: replica %r revived and re-entered rotation%s",
                 name, " at probation (degraded tier)" if probation else "")

    def remove_replica(self, name: str, close: bool = True) -> None:
        """Retire a replica from the rotation (scale-in). The replica is
        removed from dispatch first, then — outside the router lock —
        closed, which drains its queued requests (``ForestServer.close``
        flushes before stopping; a remote close resolves its pending
        futures)."""
        with self._lock:
            idx = next((i for i, r in enumerate(self._replicas)
                        if r.name == name), None)
            if idx is None:
                raise KeyError(f"unknown replica {name!r}")
            if len(self._replicas) == 1:
                raise ValueError("cannot remove the last replica")
            replica = self._replicas.pop(idx)
            self._inflight.pop(name, None)
            self._routed.pop(name, None)
            self._dead.pop(name, None)
            self._probation.pop(name, None)
            for model, names in list(self._placement.items()):
                if name in names:
                    self._placement[model] = tuple(n for n in names
                                                   if n != name)
        if close:
            try:
                replica.close()
            except Exception as e:
                log.warning("router: closing retired replica %r failed: %s",
                            name, e)
        log.info("router: replica %r retired from the rotation", name)

    def set_probation(self, name: str, probation: bool) -> None:
        """Enter/clear the probation (degraded-tier) state of a replica."""
        with self._lock:
            if not any(r.name == name for r in self._replicas):
                raise KeyError(f"unknown replica {name!r}")
            if probation:
                self._probation[name] = True
            else:
                self._probation.pop(name, None)

    def set_placement(self, plan: Dict[str, Sequence]) -> None:
        """Install a model -> preferred-replica-names plan
        (serve/placement.py); ``{}`` clears placement-aware routing."""
        with self._lock:
            self._placement = {str(m): tuple(names)
                               for m, names in (plan or {}).items()}

    def replica_names(self, live_only: bool = True) -> List[str]:
        with self._lock:
            return [r.name for r in self._replicas
                    if not (live_only and self._dead[r.name])]

    def replica(self, name: str):
        with self._lock:
            for r in self._replicas:
                if r.name == name:
                    return r
        raise KeyError(f"unknown replica {name!r}")

    def prefetch(self, model: Optional[str] = None,
                 replica: Optional[str] = None) -> dict:
        """Make a model resident (placement actuation; the compile
        happens on the replica, no router lock held). ``replica=None``
        prefetches on EVERY live replica — the ForestServer-compatible
        shape the frontend's ``prefetch`` op uses on a router target —
        and returns per-replica info keyed by name."""
        names = [replica] if replica is not None \
            else self.replica_names(live_only=True)
        out = {}
        for name in names:
            r = self.replica(name)
            if hasattr(r, "server"):
                out[name] = r.server.prefetch(**(
                    {} if model is None else {"model": model}))
            else:
                out[name] = r.client.prefetch(model=model)
        return out[replica] if replica is not None else out

    # -- fleet-wide operations (ForestServer-compatible surface, so a
    # -- ServeFrontend can front a whole replica group) -----------------
    def swap(self, source, params=None, model: Optional[str] = None,
             background: bool = False):
        """Fleet-wide model rollout: swap on EVERY live replica, in name
        order. Returns the last replica's new generation. A replica whose
        swap fails keeps its old forest (per-replica rollback) and the
        failure propagates after the remaining replicas were still
        attempted — a partial rollout is visible, not silent."""
        last = None
        first_exc = None
        for r in sorted(self._replicas, key=lambda r: r.name):
            with self._lock:
                if self._dead[r.name]:
                    continue
            kwargs = {} if model is None else {"model": model}
            try:
                if hasattr(r, "server"):
                    last = r.server.swap(source, params=params, **kwargs)
                else:
                    last = r.client.swap(source, **kwargs)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
                log.warning("router: swap on replica %r failed: %s",
                            r.name, e)
        if first_exc is not None:
            raise first_exc
        return last

    def swap_delta(self, delta, model: Optional[str] = None):
        """Fleet-wide delta swap with :meth:`swap` semantics: attempt
        every live replica in name order, per-replica rollback on
        failure, first exception propagates AFTER the rest were
        attempted (a partial rollout is visible, not silent). The
        all-or-nothing rollout protocol — roll committed replicas back —
        is ``Autonomics.rollout_delta``, which holds the base text this
        method does not."""
        last = None
        first_exc = None
        for r in sorted(self._replicas, key=lambda r: r.name):
            with self._lock:
                if self._dead[r.name]:
                    continue
            kwargs = {} if model is None else {"model": model}
            try:
                if hasattr(r, "server"):
                    last = r.server.swap_delta(delta, **kwargs)
                else:
                    last = r.client.swap_delta(delta, **kwargs)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
                log.warning("router: delta swap on replica %r failed: %s",
                            r.name, e)
        if first_exc is not None:
            raise first_exc
        return last

    def push_artifact(self, payload: bytes,
                      expect_hash: Optional[str] = None,
                      replica: Optional[str] = None) -> dict:
        """Ship one compiled-forest artifact to every live replica's
        store (``replica=<name>`` targets one), so the whole fleet pays
        exactly ONE compile for a model its members later place
        (docs/serving.md "Compiled forest artifacts"). Returns the
        verified hash per replica; a replica that rejects the payload
        (``ArtifactMismatch``) reports its error string instead and will
        fall back — loudly — to a local compile, never to a wrong-model
        serve. First failure propagates AFTER every replica was
        attempted, matching the swap rollout semantics."""
        names = [replica] if replica is not None \
            else self.replica_names(live_only=True)
        out = {}
        first_exc = None
        for name in names:
            r = self.replica(name)
            try:
                if hasattr(r, "server"):
                    out[name] = r.server.admit_artifact(
                        payload, expect_hash=expect_hash)
                else:
                    out[name] = r.client.push_artifact(
                        payload, expect_hash=expect_hash)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
                out[name] = f"error: {e}"
                log.warning("router: artifact push to replica %r failed: "
                            "%s", name, e)
        if first_exc is not None:
            raise first_exc
        return out

    def swap_on(self, name: str, source, model: Optional[str] = None):
        """Full swap on ONE replica (the rollback half of a delta
        rollout; serve/autonomics.py)."""
        r = self.replica(name)
        kwargs = {} if model is None else {"model": model}
        if hasattr(r, "server"):
            return r.server.swap(source, **kwargs)
        return r.client.swap(source, **kwargs)

    def swap_delta_on(self, name: str, delta,
                      model: Optional[str] = None):
        """Delta swap on ONE replica; the fleet-atomic rollout protocol
        (apply everywhere or roll back everywhere) lives in
        ``Autonomics.rollout_delta``."""
        r = self.replica(name)
        kwargs = {} if model is None else {"model": model}
        if hasattr(r, "server"):
            return r.server.swap_delta(delta, **kwargs)
        return r.client.swap_delta(delta, **kwargs)

    def models(self) -> List[str]:
        """The first live replica's registry listing (replicas of one
        fleet serve the same model set by construction)."""
        for r in self._replicas:
            with self._lock:
                if self._dead[r.name]:
                    continue
            try:
                if hasattr(r, "server"):
                    return r.server.models()
                return r.client.models()
            except Exception:            # pragma: no cover — probe only
                continue
        return []

    @property
    def health(self) -> "_FleetHealth":
        return _FleetHealth(self)

    def stats_snapshot(self, reservoirs: bool = False,
                       timeout_s: Optional[float] = None) -> dict:
        """Router snapshot + every live replica's own stats, keyed by
        replica name — the fleet-level analog of
        ``ForestServer.stats_snapshot``. ``reservoirs=True`` asks each
        replica for its raw reservoir states so the fleet plane can merge
        latency distributions, not just counters. Replica fetches happen
        OUTSIDE the router lock (a blocking stats RPC under the dispatch
        lock would convoy every request; graftlint R9 enforces this)."""
        out = {"router": self.snapshot(), "replicas": {}}
        for r in self._replicas:
            with self._lock:
                if self._dead[r.name]:
                    continue
            try:
                if hasattr(r, "server"):
                    out["replicas"][r.name] = r.server.stats_snapshot(
                        reservoirs=reservoirs)
                else:
                    out["replicas"][r.name] = r.client.stats(
                        timeout=timeout_s if timeout_s else 30.0,
                        reservoirs=reservoirs)
            except Exception as e:
                out["replicas"][r.name] = {"unreachable": str(e)}
        return out

    # -- fleet metric plane (obs/fleet.py; docs/observability.md) -------
    def fleet_snapshot(self) -> dict:
        """Scrape + merge every live replica's stats into ONE snapshot
        (counter sums exact, reservoir-merged quantiles); prefers the
        attached scraper's cached snapshot when one is running so the
        request path never waits on a scrape."""
        if self._scraper is not None:
            return self._scraper.latest()
        from ..obs import fleet
        return fleet.fleet_snapshot(self.stats_snapshot(reservoirs=True))

    def prometheus_fleet(self) -> str:
        """The ``prometheus fleet`` verb: one exposition for the whole
        fleet — merged serve metrics + fleet gauges + per-replica
        routing/health labels (docs/serving.md)."""
        from ..obs import prom
        snap = self.fleet_snapshot()
        return prom.render_fleet(snap["merged"], router=self.snapshot())

    def attach_scraper(self, scraper) -> None:
        """Adopt a running :class:`~lambdagap_tpu.obs.fleet.FleetScraper`
        (and through it, its signal plane): ``fleet_snapshot`` reads its
        cache, ``signals`` answers from its plane, ``close`` stops it."""
        self._scraper = scraper

    def attach_autonomics(self, controller) -> None:
        """Adopt a running :class:`~lambdagap_tpu.serve.autonomics.
        Autonomics` controller: ``close`` stops its loop, and the
        ``autonomics`` block joins :meth:`snapshot` (only then — with
        the knob off, snapshots stay byte-identical to pre-autonomics
        behavior)."""
        self._autonomics = controller

    def attach_loop(self, controller) -> None:
        """Adopt a running :class:`~lambdagap_tpu.loop.controller.
        PromotionController`: ``close`` stops it, :meth:`loop_status`
        answers from it, and the ``loop`` block joins :meth:`snapshot`
        (only then — same knob-off byte-identity rule as autonomics)."""
        self._loop = controller

    def arm_shadow(self, mirror) -> None:
        """Install a built :class:`~lambdagap_tpu.serve.shadow.
        ShadowMirror` (construct it — replica spawn, warmup — OUTSIDE any
        lock; this is only the pointer flip). An already-armed mirror is
        disarmed first."""
        with self._lock:
            old, self._shadow = self._shadow, mirror
        if old is not None:
            old.close()

    def disarm_shadow(self) -> Optional[dict]:
        """Stop mirroring; returns the final shadow window snapshot (or
        None when nothing was armed)."""
        with self._lock:
            mirror, self._shadow = self._shadow, None
        if mirror is None:
            return None
        final = mirror.snapshot()
        mirror.close()
        return final

    def shadow_snapshot(self) -> Optional[dict]:
        """The armed shadow window's counters/deltas, or None."""
        mirror = self._shadow
        return mirror.snapshot() if mirror is not None else None

    def shadow_on(self, source, sample: float = 1.0) -> dict:
        """Operator entry point (wire op ``shadow_on``): build a shadow
        replica from a model ``source`` (path or model text) and arm it
        at ``sample``; ``sample<=0`` disarms instead and returns the
        final window. The replica build runs before the pointer flip, so
        the reply path never waits on it."""
        if sample <= 0.0:
            final = self.disarm_shadow()
            return {"armed": False, "final": final}
        from ..loop.controller import default_make_shadow
        from .shadow import ShadowMirror
        text = source
        if isinstance(source, str) and "\n" not in source:
            with open(source, "r") as f:
                text = f.read()
        mirror = ShadowMirror(default_make_shadow(text),
                              sample=float(sample))
        self.arm_shadow(mirror)
        return {"armed": True, "sample": float(sample)}

    def loop_status(self) -> dict:
        """The promotion state machine's position (docs/continuous-
        learning.md) — ``{"state": "off"}`` when no controller is
        attached."""
        loop = self._loop
        if loop is None:
            return {"state": "off"}
        return loop.status()

    def signals(self) -> dict:
        """The current control-signal tick (obs/signals.py). Requires an
        attached scraper with a signal plane — the CLI wires one when
        ``fleet_scrape_interval_s > 0``."""
        if self._scraper is None or self._scraper.signals is None:
            raise ValueError(
                "no signal plane attached (set fleet_scrape_interval_s > 0 "
                "or Router.attach_scraper(FleetScraper(..., "
                "signals=SignalPlane())))")
        return self._scraper.signals.snapshot()

    def prometheus(self) -> str:
        from ..obs import prom
        return prom.render_router(self.snapshot())

    # -- reporting / lifecycle -----------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            replicas = {
                r.name: {
                    "inflight": self._inflight[r.name],
                    "routed": self._routed[r.name],
                    "dead": self._dead[r.name],
                } for r in self._replicas
            }
            # probation/placement/autonomics keys appear ONLY when the
            # control loop put them there: knob-off snapshots must stay
            # byte-identical to the pre-autonomics schema (acceptance
            # criterion of ISSUE 13)
            for name in self._probation:
                if name in replicas:
                    replicas[name]["probation"] = True
            out = {
                "replicas": replicas,
                "failovers": self._failovers,
                "rejected_no_replica": self._rejected_no_replica,
            }
            if self._placement:
                out["placement"] = {m: list(names)
                                    for m, names in
                                    sorted(self._placement.items())}
            autonomics = self._autonomics
            shadow = self._shadow
            loop = self._loop
        if autonomics is not None:
            out["autonomics"] = autonomics.snapshot()
        # shadow/loop keys appear ONLY while armed/attached — same
        # knob-off byte-identity contract as the autonomics block
        if shadow is not None:
            out["shadow"] = shadow.snapshot()
        if loop is not None:
            out["loop"] = loop.status()
        for r in self._replicas:         # health probes outside the lock
            try:
                replicas[r.name]["health"] = (
                    "dead" if out["replicas"][r.name]["dead"]
                    else r.health())
            except Exception:            # pragma: no cover
                replicas[r.name]["health"] = "dead"
        return out

    def close(self) -> None:
        self._closed = True
        if self._loop is not None:
            self._loop.close()
        self.disarm_shadow()
        if self._autonomics is not None:
            self._autonomics.close()
        if self._scraper is not None:
            self._scraper.close()
        if self._own:
            for r in self._replicas:
                try:
                    r.close()
                except Exception as e:   # a dead replica may fail to close
                    log.warning("router: closing replica %r failed: %s",
                                r.name, e)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _FleetHealth:
    """Aggregate health view over a router's replicas: ``ok`` while any
    replica is ok, ``degraded`` while only degraded replicas remain, and
    ``draining`` when nothing can take a request — the same three honest
    answers a single server gives, lifted to the fleet."""

    def __init__(self, router: Router) -> None:
        self._router = router

    def state(self) -> str:
        states = [info["health"]
                  for info in self._router.snapshot()["replicas"].values()]
        if OK in states:
            return OK
        if DEGRADED in states:
            return DEGRADED
        return DRAINING

    def snapshot(self) -> dict:
        snap = self._router.snapshot()
        return {"state": self.state(),
                "replicas": {name: info["health"]
                             for name, info in snap["replicas"].items()}}
