"""Replica router: health-aware dispatch over shared-nothing serve workers.

A fleet is several :class:`~lambdagap_tpu.serve.server.ForestServer`
replicas — in-process (:class:`LocalReplica`) or behind a socket front end
(:class:`RemoteReplica`, serve/frontend.py) — that share NOTHING: each has
its own registry, batcher, and device executables. The router owns only
dispatch policy:

- **health-aware placement**: replicas reporting ``ok`` are preferred;
  ``degraded`` replicas serve only when no ok replica exists; ``draining``
  and dead replicas never take new work. Among candidates the least
  outstanding-requests replica wins (join-shortest-queue).
- **failover, never stranding** (graftlint R8 discipline): a request whose
  replica dies mid-flight — transport error, closed server, injected
  dispatch fault — is resubmitted once per remaining live replica; only
  when every replica has been tried (or none exists) does the caller see
  :class:`~lambdagap_tpu.guard.ReplicaUnavailable`. Every future the
  router hands out therefore terminates: result, per-request error
  (shape/timeout/overload), or an explicit no-replica rejection.
- **overload spill**: a replica rejecting at admission
  (:class:`ServeOverloaded`) is treated as momentarily full, and the
  request spills to the next candidate; only an all-full fleet surfaces
  the rejection.

Request-level failures (``ServeTimeout``, shape errors, unknown model) are
NOT failed over: the request itself is at fault, and replaying it
elsewhere would double latency for a deterministic error.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

from ..guard.degrade import (DEGRADED, DRAINING, OK, ReplicaUnavailable,
                             ServeOverloaded)
from ..guard.faults import InjectedFault
from ..obs import trace as obs_trace
from ..utils import log

# exceptions that indict the REPLICA, not the request: these trigger
# failover to another replica (transport failures additionally mark the
# replica dead until the router is rebuilt)
FAILOVER_EXCEPTIONS = (ReplicaUnavailable, ConnectionError, OSError,
                       InjectedFault)
_DEAD_MARKING = (ReplicaUnavailable, ConnectionError, OSError)


class LocalReplica:
    """An in-process ForestServer as a routable replica."""

    def __init__(self, name: str, server) -> None:
        self.name = name
        self.server = server

    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> Future:
        try:
            return self.server.submit(x, model=model, tenant=tenant,
                                      trace=trace)
        except RuntimeError as e:
            if "closed" in str(e):       # a closed server is a dead replica
                raise ReplicaUnavailable(
                    f"replica {self.name!r} is closed") from e
            raise

    def health(self) -> str:
        return self.server.health.state()

    def close(self) -> None:
        self.server.close()


class RemoteReplica:
    """A serve worker behind a socket frontend (serve/frontend.py) as a
    routable replica. Health is polled over the wire and cached for
    ``health_ttl_s`` so the dispatch path never blocks on a health RPC; a
    transport failure reports the replica dead immediately."""

    def __init__(self, name: str, host: str, port: int,
                 health_ttl_s: float = 0.5, connect_timeout: float = 5.0
                 ) -> None:
        from .frontend import FrontendClient
        self.name = name
        self.client = FrontendClient(host, port, timeout=connect_timeout)
        self._ttl = float(health_ttl_s)
        self._health = OK
        self._health_at = 0.0
        self._health_lock = threading.Lock()

    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> Future:
        return self.client.submit(x, model=model, tenant=tenant,
                                  trace=trace)

    def health(self) -> str:
        import time
        if not self.client.alive:
            return "dead"
        now = time.perf_counter()
        with self._health_lock:
            fresh = now - self._health_at < self._ttl
            if fresh:
                return self._health
            self._health_at = now        # one prober per TTL window
        try:
            state = self.client.health(timeout=self._ttl)
        except Exception:                # transport failed: replica is dead
            state = "dead"
        with self._health_lock:
            self._health = state
        return state

    def close(self) -> None:
        self.client.close()


class Router:
    """Health-aware dispatch + failover over a replica group.

    ``replicas`` can mix :class:`LocalReplica` and :class:`RemoteReplica`.
    ``own_replicas=True`` makes :meth:`close` close them too.
    """

    def __init__(self, replicas: Sequence, own_replicas: bool = False
                 ) -> None:
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self._replicas = list(replicas)
        self._own = bool(own_replicas)
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {r.name: 0 for r in replicas}
        self._routed: Dict[str, int] = {r.name: 0 for r in replicas}
        self._dead: Dict[str, bool] = {r.name: False for r in replicas}
        self._failovers = 0
        self._rejected_no_replica = 0
        self._closed = False
        self._scraper = None             # obs.fleet.FleetScraper, attached

    # -- dispatch -------------------------------------------------------
    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> "Future":
        """Route one request; returns a Future of ``ServeResult``. The
        future ALWAYS terminates: a dead replica's in-flight requests are
        failed over to the remaining live replicas, and only a fleet with
        no live replica rejects (:class:`ReplicaUnavailable`). A sampled
        ``trace`` context gets a ``route`` span covering pick + failover
        until the future resolves (attrs: the replica that answered, the
        failover count paid)."""
        if self._closed:
            raise RuntimeError("router closed")
        outer: Future = Future()
        ctx = trace if trace is not None \
            else obs_trace.RECORDER.maybe_trace()
        hop = None
        if ctx is not None:
            hop = ctx.child()            # the route span's own context
            t0_wall, t0 = time.time(), time.perf_counter()
            route_state = {"replica": None, "failovers": 0}

            def _record(_f) -> None:
                obs_trace.RECORDER.record(
                    "route", ctx, t0_wall, time.perf_counter() - t0,
                    span_id=hop.span_id,
                    replica=route_state["replica"],
                    failovers=route_state["failovers"])

            outer.add_done_callback(_record)
            self._attempt(outer, x, model, tenant, tried=set(),
                          trace=hop, route_state=route_state)
        else:
            self._attempt(outer, x, model, tenant, tried=set())
        return outer

    def predict(self, x, timeout: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None):
        return self.submit(x, model=model, tenant=tenant).result(
            timeout).values

    def _pick(self, tried: set):
        """Least-loaded replica among the healthiest available tier."""
        with self._lock:
            candidates = [r for r in self._replicas
                          if r.name not in tried and not self._dead[r.name]]
        by_state: Dict[str, List] = {}
        for r in candidates:
            try:
                state = r.health()
            except Exception:            # pragma: no cover — health probe
                state = "dead"           # died under us: skip it
            if state in (DRAINING, "dead"):
                if state == "dead":
                    self._mark_dead(r)
                continue
            by_state.setdefault(state, []).append(r)
        tier = by_state.get(OK) or by_state.get(DEGRADED) or []
        if not tier:
            return None
        with self._lock:
            return min(tier, key=lambda r: self._inflight[r.name])

    def _attempt(self, outer: Future, x, model, tenant, tried: set,
                 trace=None, route_state: Optional[Dict] = None) -> None:
        while True:
            replica = self._pick(tried)
            if replica is None:
                with self._lock:
                    self._rejected_no_replica += 1
                outer.set_exception(ReplicaUnavailable(
                    "no live replica can take the request "
                    f"(tried: {sorted(tried) or 'none'})"))
                return
            tried.add(replica.name)
            try:
                inner = replica.submit(x, model=model, tenant=tenant,
                                       trace=trace)
            # graftlint: disable=R8 — the continue re-enters the pick
            # loop, every exit of which terminates the future: a
            # successful submit chains resolution to on_done, and an
            # exhausted fleet set_exception()s ReplicaUnavailable above
            except FAILOVER_EXCEPTIONS as e:
                self._note_failure(replica, e)
                if route_state is not None:
                    route_state["failovers"] += 1
                continue                 # submit-time failover
            # graftlint: disable=R8 — same loop contract as above: spill
            # to a peer, or the empty-pick branch resolves the future
            except ServeOverloaded:
                with self._lock:
                    self._failovers += 1
                if route_state is not None:
                    route_state["failovers"] += 1
                continue                 # overload spill: try a peer
            except Exception as e:
                outer.set_exception(e)   # request-level error: no replay
                return
            break
        with self._lock:
            self._inflight[replica.name] += 1
            self._routed[replica.name] += 1
        if route_state is not None:
            route_state["replica"] = replica.name

        def on_done(f: Future) -> None:
            with self._lock:
                self._inflight[replica.name] -= 1
            exc = f.exception()
            if exc is None:
                outer.set_result(f.result())
            elif isinstance(exc, FAILOVER_EXCEPTIONS):
                # in-flight failover: the replica died under the request
                self._note_failure(replica, exc)
                if route_state is not None:
                    route_state["failovers"] += 1
                self._attempt(outer, x, model, tenant, tried,
                              trace=trace, route_state=route_state)
            else:
                outer.set_exception(exc)

        inner.add_done_callback(on_done)

    def _mark_dead(self, replica) -> None:
        with self._lock:
            already = self._dead[replica.name]
            self._dead[replica.name] = True
        if not already:
            log.warning("router: replica %r reports dead health; removed "
                        "from dispatch", replica.name)

    def _note_failure(self, replica, exc) -> None:
        with self._lock:
            self._failovers += 1
            if isinstance(exc, _DEAD_MARKING):
                self._dead[replica.name] = True
        log.warning("router: replica %r failed (%s); failing over%s",
                    replica.name, exc,
                    " and marking it dead"
                    if isinstance(exc, _DEAD_MARKING) else "")

    # -- fleet-wide operations (ForestServer-compatible surface, so a
    # -- ServeFrontend can front a whole replica group) -----------------
    def swap(self, source, params=None, model: Optional[str] = None,
             background: bool = False):
        """Fleet-wide model rollout: swap on EVERY live replica, in name
        order. Returns the last replica's new generation. A replica whose
        swap fails keeps its old forest (per-replica rollback) and the
        failure propagates after the remaining replicas were still
        attempted — a partial rollout is visible, not silent."""
        last = None
        first_exc = None
        for r in sorted(self._replicas, key=lambda r: r.name):
            with self._lock:
                if self._dead[r.name]:
                    continue
            kwargs = {} if model is None else {"model": model}
            try:
                if hasattr(r, "server"):
                    last = r.server.swap(source, params=params, **kwargs)
                else:
                    last = r.client.swap(source, **kwargs)
            except Exception as e:
                if first_exc is None:
                    first_exc = e
                log.warning("router: swap on replica %r failed: %s",
                            r.name, e)
        if first_exc is not None:
            raise first_exc
        return last

    def models(self) -> List[str]:
        """The first live replica's registry listing (replicas of one
        fleet serve the same model set by construction)."""
        for r in self._replicas:
            with self._lock:
                if self._dead[r.name]:
                    continue
            try:
                if hasattr(r, "server"):
                    return r.server.models()
                return r.client.models()
            except Exception:            # pragma: no cover — probe only
                continue
        return []

    @property
    def health(self) -> "_FleetHealth":
        return _FleetHealth(self)

    def stats_snapshot(self, reservoirs: bool = False,
                       timeout_s: Optional[float] = None) -> dict:
        """Router snapshot + every live replica's own stats, keyed by
        replica name — the fleet-level analog of
        ``ForestServer.stats_snapshot``. ``reservoirs=True`` asks each
        replica for its raw reservoir states so the fleet plane can merge
        latency distributions, not just counters. Replica fetches happen
        OUTSIDE the router lock (a blocking stats RPC under the dispatch
        lock would convoy every request; graftlint R9 enforces this)."""
        out = {"router": self.snapshot(), "replicas": {}}
        for r in self._replicas:
            with self._lock:
                if self._dead[r.name]:
                    continue
            try:
                if hasattr(r, "server"):
                    out["replicas"][r.name] = r.server.stats_snapshot(
                        reservoirs=reservoirs)
                else:
                    out["replicas"][r.name] = r.client.stats(
                        timeout=timeout_s if timeout_s else 30.0,
                        reservoirs=reservoirs)
            except Exception as e:
                out["replicas"][r.name] = {"unreachable": str(e)}
        return out

    # -- fleet metric plane (obs/fleet.py; docs/observability.md) -------
    def fleet_snapshot(self) -> dict:
        """Scrape + merge every live replica's stats into ONE snapshot
        (counter sums exact, reservoir-merged quantiles); prefers the
        attached scraper's cached snapshot when one is running so the
        request path never waits on a scrape."""
        if self._scraper is not None:
            return self._scraper.latest()
        from ..obs import fleet
        return fleet.fleet_snapshot(self.stats_snapshot(reservoirs=True))

    def prometheus_fleet(self) -> str:
        """The ``prometheus fleet`` verb: one exposition for the whole
        fleet — merged serve metrics + fleet gauges + per-replica
        routing/health labels (docs/serving.md)."""
        from ..obs import prom
        snap = self.fleet_snapshot()
        return prom.render_fleet(snap["merged"], router=self.snapshot())

    def attach_scraper(self, scraper) -> None:
        """Adopt a running :class:`~lambdagap_tpu.obs.fleet.FleetScraper`
        (and through it, its signal plane): ``fleet_snapshot`` reads its
        cache, ``signals`` answers from its plane, ``close`` stops it."""
        self._scraper = scraper

    def signals(self) -> dict:
        """The current control-signal tick (obs/signals.py). Requires an
        attached scraper with a signal plane — the CLI wires one when
        ``fleet_scrape_interval_s > 0``."""
        if self._scraper is None or self._scraper.signals is None:
            raise ValueError(
                "no signal plane attached (set fleet_scrape_interval_s > 0 "
                "or Router.attach_scraper(FleetScraper(..., "
                "signals=SignalPlane())))")
        return self._scraper.signals.snapshot()

    def prometheus(self) -> str:
        from ..obs import prom
        return prom.render_router(self.snapshot())

    # -- reporting / lifecycle -----------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            replicas = {
                r.name: {
                    "inflight": self._inflight[r.name],
                    "routed": self._routed[r.name],
                    "dead": self._dead[r.name],
                } for r in self._replicas
            }
            out = {
                "replicas": replicas,
                "failovers": self._failovers,
                "rejected_no_replica": self._rejected_no_replica,
            }
        for r in self._replicas:         # health probes outside the lock
            try:
                replicas[r.name]["health"] = (
                    "dead" if out["replicas"][r.name]["dead"]
                    else r.health())
            except Exception:            # pragma: no cover
                replicas[r.name]["health"] = "dead"
        return out

    def close(self) -> None:
        self._closed = True
        if self._scraper is not None:
            self._scraper.close()
        if self._own:
            for r in self._replicas:
                try:
                    r.close()
                except Exception as e:   # a dead replica may fail to close
                    log.warning("router: closing replica %r failed: %s",
                                r.name, e)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _FleetHealth:
    """Aggregate health view over a router's replicas: ``ok`` while any
    replica is ok, ``degraded`` while only degraded replicas remain, and
    ``draining`` when nothing can take a request — the same three honest
    answers a single server gives, lifted to the fleet."""

    def __init__(self, router: Router) -> None:
        self._router = router

    def state(self) -> str:
        states = [info["health"]
                  for info in self._router.snapshot()["replicas"].values()]
        if OK in states:
            return OK
        if DEGRADED in states:
            return DEGRADED
        return DRAINING

    def snapshot(self) -> dict:
        snap = self._router.snapshot()
        return {"state": self.state(),
                "replicas": {name: info["health"]
                             for name, info in snap["replicas"].items()}}
