"""ForestServer: the serving front door.

Composes the serving pieces — :class:`ModelRegistry` (N compiled forests
under an HBM budget, per-model generations + hot-swap),
:class:`MicroBatcher` (request coalescing with weighted tenant fairness)
and the guard degradation layer — behind a two-call API::

    server = booster.as_server()          # or ForestServer(booster)
    y = server.predict(x_row)             # blocking, batched under the hood
    fut = server.submit(rows)             # async: Future[ServeResult]
    server.add_model("b", "model_b.txt")  # multi-model registry
    y_b = server.predict(x_row, model="b")
    server.swap("model_v2.txt")           # zero-downtime model replace
    print(server.stats_json())
    server.close()

Every response is a :class:`ServeResult` carrying the generation that
produced it, which is what makes hot-swap correctness testable: under a
concurrent stream, each result matches exactly one generation's forest.

The server owns POLICY (batching windows, shedding, tenant quotas,
health); the registry owns MECHANISM (which forests are resident, their
buckets, their generations) — the split ROADMAP item 2 prescribes, and
what lets several replica servers share nothing behind a router
(serve/router.py) while each runs its own registry.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from ..guard.degrade import HealthMonitor
from ..guard.faults import plan_for
from ..obs import trace as obs_trace
from ..utils import log
from .batcher import MicroBatcher, Request
from .cache import DEFAULT_BUCKETS, CompiledForestCache, ModelPack
from .registry import DEFAULT_MODEL, ModelRegistry
from .stats import ServeStats


class ServeResult(NamedTuple):
    """One request's predictions + the model generation that served it."""
    values: np.ndarray
    generation: int


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """``"tenant:weight,tenant2:weight2"`` -> dict (unlisted tenants weigh
    1.0 in the fair queue)."""
    out: Dict[str, float] = {}
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if ":" not in tok:
            raise ValueError(f"serve_tenant_weights token {tok!r} is not "
                             "'tenant:weight'")
        name, w = tok.rsplit(":", 1)
        out[name.strip()] = float(w)
    return out


class ForestServer:
    """Batched, hot-swappable, multi-model TPU inference server.

    Accepts a ``basic.Booster`` or a ``models.gbdt.GBDT`` as the initial
    (``"default"``) model. Defaults for the batching/bucket/registry knobs
    come from the booster's config (``serve_*`` parameters); keyword
    arguments override.
    """

    def __init__(self, model, buckets: Optional[Sequence[int]] = None,
                 max_batch: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 workers: Optional[int] = None,
                 warmup: Optional[bool] = None,
                 raw_score: bool = False,
                 start_iteration: int = 0, num_iteration: int = -1,
                 stats: Optional[ServeStats] = None,
                 max_queue: Optional[int] = None,
                 backpressure: Optional[str] = None,
                 timeout_ms: Optional[float] = None,
                 swap_breaker: Optional[int] = None,
                 hbm_budget_bytes: Optional[int] = None,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 tenant_max_share: Optional[float] = None) -> None:
        gbdt = model._booster if hasattr(model, "_booster") else model
        cfg = gbdt.config
        self.raw_score = bool(raw_score)
        self._buckets = tuple(buckets if buckets is not None
                              else (cfg.serve_buckets or DEFAULT_BUCKETS))
        self._warmup = bool(cfg.serve_warmup if warmup is None else warmup)
        self._si = int(start_iteration)
        self._ni = int(num_iteration)
        self.stats = stats if stats is not None else ServeStats()
        self._closed = False
        self._faults = plan_for(cfg)
        if hbm_budget_bytes is None:
            hbm_budget_bytes = int(cfg.serve_hbm_budget_mb * (1 << 20))
        # the replica-wide compiled-artifact store: builds consult it by
        # source key before lowering (peers ship artifacts over the wire,
        # push_artifact), so N replicas placing one model pay ONE compile
        from ..infer import ArtifactStore
        self.artifacts = ArtifactStore()
        # cross-model packing (serve_pack_models): resident compiled
        # models fuse into ONE executable so a mixed FairQueue batch
        # dispatches once; rebuilt lazily on membership/generation change
        self._pack_models = bool(cfg.serve_pack_models)
        self._pack: Optional[ModelPack] = None
        self._pack_lock = threading.Lock()
        self.registry = ModelRegistry(
            self._build_cache, stats=self.stats,
            hbm_budget_bytes=hbm_budget_bytes,
            breaker_threshold=int(cfg.serve_swap_breaker
                                  if swap_breaker is None else swap_breaker),
            artifact_store=self.artifacts)
        self.registry.install(DEFAULT_MODEL, gbdt)
        self.health = HealthMonitor(
            breaker=self.registry.entry(DEFAULT_MODEL).breaker)
        nw = int(cfg.serve_workers if workers is None else workers)
        if nw <= 0:                      # auto: overlap dispatches, bounded
            import os
            nw = max(1, min(4, (os.cpu_count() or 1) // 2))
        if tenant_weights is None:
            tenant_weights = parse_tenant_weights(cfg.serve_tenant_weights)
        self._batcher = MicroBatcher(
            self._run_batch,
            max_batch=int(cfg.serve_max_batch if max_batch is None
                          else max_batch),
            max_delay_ms=float(cfg.serve_max_delay_ms if max_delay_ms is None
                               else max_delay_ms),
            workers=nw,
            stats=self.stats,
            max_queue=int(cfg.serve_max_queue if max_queue is None
                          else max_queue),
            backpressure=(cfg.serve_backpressure if backpressure is None
                          else backpressure),
            timeout_ms=float(cfg.serve_timeout_ms if timeout_ms is None
                             else timeout_ms),
            health=self.health,
            tenant_weights=tenant_weights,
            tenant_max_share=float(cfg.serve_tenant_max_share
                                   if tenant_max_share is None
                                   else tenant_max_share))
        # serve-side profiler window keyed to the submitted-request count
        # (profile_serve_start_req/profile_serve_n_req): the inference
        # analog of profile_start_iter (docs/observability.md)
        from ..obs.profile import ProfileWindow
        self._profile = ProfileWindow(
            start_iter=int(getattr(cfg, "profile_serve_start_req", -1)),
            n_iters=int(getattr(cfg, "profile_serve_n_req", 1)),
            out_dir=getattr(cfg, "profile_dir", ""), unit="serve_request")

    # ------------------------------------------------------------------
    def _build_cache(self, gbdt, generation: int) -> CompiledForestCache:
        cache = CompiledForestCache(
            gbdt, buckets=self._buckets, start_iteration=self._si,
            num_iteration=self._ni, generation=generation, stats=self.stats,
            artifact_store=self.artifacts)
        if self._warmup:
            cache.warm()
        return cache

    @property
    def generation(self) -> int:
        return self.registry.generation(DEFAULT_MODEL)

    @property
    def num_features(self) -> int:
        """Width the active compiled forest consumes (1 + max split
        feature); narrower requests error unless
        predict_disable_shape_check pads them with NaN."""
        return self.registry.entry(DEFAULT_MODEL).width

    @property
    def _swap(self):
        """PR 1 compatibility shim: the default model's registry entry
        exposes the old SwapController surface (``.active``,
        ``.breaker``)."""
        return self.registry.entry(DEFAULT_MODEL)

    # -- model management ----------------------------------------------
    def add_model(self, name: str, source, params=None) -> int:
        """Register an additional model (path, model text, Booster or
        GBDT) under ``name``; it compiles (and warms) now, off the request
        path, subject to the registry's HBM budget."""
        return self.registry.install(name, source, params=params)

    def models(self) -> List[str]:
        return self.registry.names()

    def admit_artifact(self, payload: bytes,
                       expect_hash: Optional[str] = None) -> str:
        """Admit a peer replica's serialized compiled-forest artifact by
        content hash (docs/serving.md "Compiled forest artifacts"). The
        next compiled-engine build whose source key matches serves the
        admitted artifact instead of compiling — a mismatched or torn
        payload raises ``ArtifactMismatch`` and compiles locally instead,
        never serving the wrong model. Returns the verified hash."""
        return self.registry.admit_artifact(payload, expect_hash=expect_hash)

    def artifact_bytes(self, model: str = DEFAULT_MODEL) -> bytes:
        """Serialize ``model``'s compiled artifact for shipping to peers
        (requires predict_engine=compiled)."""
        return self.registry.artifact_bytes(model)

    # -- request path ---------------------------------------------------
    def submit(self, x, model: Optional[str] = None,
               tenant: Optional[str] = None,
               trace=None) -> "Future[ServeResult]":
        """Async predict: enqueue rows, return a Future of
        :class:`ServeResult`. ``x`` is one row [D] or a matrix [n, D];
        ``model`` routes to a registry model (default: the initial one);
        ``tenant`` bills the request to a fairness/accounting lane;
        ``trace`` is an incoming :class:`~lambdagap_tpu.obs.trace.
        TraceContext` (None = mint one per ``serve_trace_sample``, which
        defaults to never)."""
        if self._closed:
            raise RuntimeError("ForestServer is closed")
        name = model if model is not None else DEFAULT_MODEL
        if not self.registry.has(name):
            raise ValueError(f"unknown serve model {name!r} "
                             f"(registered: {self.models()})")
        x = np.asarray(x, dtype=np.float32)
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2:
            raise ValueError(f"serve requests are rows [n, D], got {x.shape}")
        if self._profile.enabled:        # request-count profiler window
            self._profile.tick()
        ctx = trace if trace is not None \
            else obs_trace.RECORDER.maybe_trace()
        if ctx is None:                  # the untraced fast path
            return self._batcher.submit(x, model=name, tenant=tenant)
        # the serve_request span covers submit -> future resolution; its
        # context rides the Request so queue/registry/dispatch spans nest
        # under it (recorded after the fact — span ids are pre-minted)
        child = ctx.child()
        t0_wall, t0 = time.time(), time.perf_counter()
        fut = self._batcher.submit(x, model=name, tenant=tenant,
                                   trace=child)
        attrs = {"model": name}
        if tenant is not None:
            attrs["tenant"] = tenant

        def _record(_f) -> None:
            obs_trace.RECORDER.record(
                "serve_request", ctx, t0_wall,
                time.perf_counter() - t0, span_id=child.span_id, **attrs)

        fut.add_done_callback(_record)
        return fut

    def predict(self, x, timeout: Optional[float] = None,
                model: Optional[str] = None,
                tenant: Optional[str] = None) -> np.ndarray:
        """Blocking predict with ``Booster.predict`` output semantics:
        [n] for single-class models, [n, K] for multiclass."""
        return self.submit(x, model=model, tenant=tenant).result(
            timeout).values

    # -- hot swap -------------------------------------------------------
    def swap(self, source, params=None, background: bool = False,
             model: str = DEFAULT_MODEL):
        """Atomically replace a served model (path, model text, Booster
        or GBDT). The new forest is compiled and pre-warmed BEFORE the
        generation pointer flips; in-flight requests finish on the old
        forest. Returns the new generation (or the worker thread when
        ``background=True``)."""
        return self.registry.swap(model, source, params=params,
                                  background=background)

    def swap_delta(self, delta, model: str = DEFAULT_MODEL) -> int:
        """Delta hot-swap: apply an appended-trees frame
        (serve/delta.py) against the resident host model, then compile /
        pre-warm / flip exactly like :meth:`swap`. Returns the new
        generation; a non-applying delta raises ``SwapFailed`` with the
        old generation untouched."""
        return self.registry.swap_delta(model, delta, faults=self._faults)

    def model_text(self, model: str = DEFAULT_MODEL) -> str:
        """The resident host model's full text (delta-swap base)."""
        return self.registry.model_text(model)

    def prefetch(self, model: str = DEFAULT_MODEL) -> Dict:
        """Make ``model`` resident NOW (re-admitting it if evicted) and
        report what that cost — the placement loop's actuation verb, so
        the readmission cliff is paid off the request path, by design
        (docs/serving.md "Model placement")."""
        info: Dict = {}
        self.registry.get(model, info=info)
        info.setdefault("readmitted", False)
        info["resident"] = True
        return info

    # -- metrics / lifecycle -------------------------------------------
    def stats_snapshot(self, reservoirs: bool = False,
                       timeout_s: Optional[float] = None) -> dict:
        """The serving metrics dict; ``reservoirs=True`` adds the raw
        reservoir states the fleet scraper merges (obs/fleet.py).
        ``timeout_s`` exists for scrape-surface uniformity with the
        router (an in-process snapshot cannot block on a peer)."""
        entry = self.registry.entry(DEFAULT_MODEL)
        snap = self.stats.snapshot(reservoirs=reservoirs)
        snap["generation"] = entry.generation
        snap["buckets"] = list(entry.buckets)
        snap["engine"] = entry.engine
        snap["health"] = self.health.snapshot()
        snap["registry"] = self.registry.snapshot()
        return snap

    def stats_json(self, **kwargs) -> str:
        import json
        kwargs.setdefault("indent", 2)
        return json.dumps(self.stats_snapshot(), **kwargs)

    def prometheus(self) -> str:
        """Prometheus text exposition of the serving metrics (the
        ``stats`` line of the task=serve loop; metric names in
        docs/observability.md)."""
        from ..obs import prom
        return prom.render_serve(self.stats_snapshot())

    def prometheus_fleet(self) -> str:
        """The ``prometheus fleet`` verb on a single server: a fleet of
        one, rendered through the same merge path the router uses — so
        scrape configs are identical whether a frontend fronts one
        replica or a router (docs/serving.md)."""
        from ..obs import fleet, prom
        merged = fleet.merge_snapshots(
            [self.stats_snapshot(reservoirs=True)])
        return prom.render_fleet(merged)

    def close(self, timeout: float = 30.0) -> None:
        """Flush queued requests and stop the batcher thread. Health
        reports DRAINING from the first close() call onward."""
        if not self._closed:
            self._closed = True
            self.health.set_draining()
            self._batcher.close(timeout)
            self._profile.close()

    def __enter__(self) -> "ForestServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    def _run_batch(self, batch: List[Request]) -> None:
        """Worker-thread batch execution: group the coalesced batch by
        registry model, snapshot each model's compiled forest once, run
        ONE padded dispatch per model, scatter results back to futures. A
        model that fails to resolve (removed, or its re-admission compile
        failed) fails only ITS requests; the other groups still serve."""
        self._faults.dispatch_fault()    # inert unless a fault plan is armed
        groups: Dict[str, List[Request]] = {}
        for r in batch:
            groups.setdefault(r.model or DEFAULT_MODEL, []).append(r)
        resolved: List[tuple] = []
        for name, reqs in sorted(groups.items()):
            info: Dict = {}
            t_reg_wall, t_reg = time.time(), time.perf_counter()
            try:
                slot = self.registry.get(name, info=info)  # LRU; may readmit
            except Exception as e:
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(e)
                self.stats.record_error()
                continue
            reg_dur = time.perf_counter() - t_reg
            rec = obs_trace.RECORDER
            for r in reqs:
                if r.trace is None:
                    continue
                # queue_wait ends where the registry resolve begins, so
                # the three children (queue_wait, registry_get, dispatch)
                # TILE the serve_request span instead of double-counting
                rec.record("queue_wait", r.trace, r.t_wall,
                           t_reg - r.t_submit)
                # the registry resolve, per sampled request: a readmitted
                # group makes the 174x cliff visible on every trace that
                # paid it (registry_readmit nests the compile share; the
                # artifact_hash tag says WHICH compiled artifact was
                # rebuilt, so fleet traces join on the shared-compile key)
                sid = rec.record("registry_get", r.trace, t_reg_wall,
                                 reg_dur, model=name, **info)
                if info.get("readmitted"):
                    rec.record("registry_readmit", r.trace, t_reg_wall,
                               info.get("build_s", reg_dur), parent=sid,
                               model=name,
                               **({"artifact_hash": info["artifact_hash"]}
                                  if info.get("artifact_hash") else {}))
            resolved.append((name, slot, reqs))
        pack = self._model_pack() if (self._pack_models and resolved) else None
        if pack is not None:
            self._dispatch_packed(pack, resolved)
            return
        for name, slot, reqs in resolved:
            self._dispatch_group(name, slot, reqs)

    def _model_pack(self) -> Optional[ModelPack]:
        """The cross-model pack covering every registered model, rebuilt
        lazily whenever membership or any member's generation changes (the
        pack key is the (name, cache key) set). Resolving every member
        forces fleet-wide residency — packing implies the operator WANTS
        all tenants resident; the HBM budget still applies and an evicted
        member re-admits through the normal single-flight path. Returns
        None (per-model dispatch fallback) when any member cannot pack
        (non-compiled engine, early stop, or a failed build)."""
        try:
            slots: Dict[str, CompiledForestCache] = {}
            for name in self.registry.names():
                slot = self.registry.get(name)
                if slot._compiled is None or slot._es_freq:
                    return None
                slots[name] = slot
        except Exception as e:
            log.warning("serve: cross-model pack unavailable (%s); "
                        "dispatching per model", e)
            return None
        key = frozenset((n, c.key) for n, c in slots.items())
        with self._pack_lock:
            pack = self._pack
            if pack is None or pack.key != key:
                pack = ModelPack(slots, buckets=self._buckets,
                                 stats=self.stats)
                self._pack = pack
                log.info("serve: packed %d models into one executable "
                         "(%d trees, width %d, %d bytes)", len(slots),
                         pack.packed.num_trees, pack.width, pack.hbm_bytes)
            return pack

    def _gather_rows(self, name: str, slot,
                     reqs: List[Request]) -> tuple:
        """Shape-check one model's requests against its compiled width:
        returns (rows, good requests); violators fail their own future."""
        W = slot.width
        disable_check = slot.gbdt.config.predict_disable_shape_check
        rows: List[np.ndarray] = []
        good: List[Request] = []
        for r in reqs:
            x = r.x
            if x.shape[1] < W:
                if not disable_check:
                    r.future.set_exception(ValueError(
                        f"request has {x.shape[1]} features but model "
                        f"{name!r} needs {W}; set "
                        "predict_disable_shape_check=true to pad missing "
                        "features with NaN"))
                    self.stats.record_error()
                    continue
                x = np.concatenate(
                    [x, np.full((x.shape[0], W - x.shape[1]), np.nan,
                                np.float32)], axis=1)
            rows.append(np.ascontiguousarray(x[:, :W]))
            good.append(r)
        return rows, good

    def _dispatch_packed(self, pack: ModelPack, resolved: List[tuple]) -> None:
        """A mixed multi-model batch through ONE packed executable: every
        model's rows concatenate into shared cross-model padding buckets,
        the traversal dispatches once per bucket, and each request's slice
        comes back bit-identical to its member cache serving it alone."""
        t0_wall, t0 = time.time(), time.perf_counter()
        parts: List[tuple] = []
        for name, slot, reqs in resolved:
            rows, good = self._gather_rows(name, slot, reqs)
            if good:
                parts.append((name, slot, good, rows))
        if not parts:
            return
        mixed = [(name, rows[0] if len(rows) == 1
                  else np.concatenate(rows, axis=0), self.raw_score)
                 for name, _slot, _good, rows in parts]
        outs = pack.predict_mixed(mixed)
        t1 = time.perf_counter()
        total_rows = sum(x.shape[0] for _n, x, _r in mixed)
        self.stats.record_dispatch(rows=total_rows, device_s=t1 - t0)
        self.stats.record_packed_dispatch(models=len(parts), rows=total_rows)
        rec = obs_trace.RECORDER
        for (name, slot, good, rows), out in zip(parts, outs):
            lo = 0
            for r, x in zip(good, rows):
                n = x.shape[0]
                if r.trace is not None:
                    rec.record("dispatch", r.trace, t0_wall, t1 - t0,
                               rows=n, batch_rows=total_rows, model=name,
                               packed_models=len(parts))
                r.future.set_result(ServeResult(out[lo:lo + n],
                                                slot.generation))
                lo += n
                self.stats.record_request(
                    queue_wait=t0 - r.t_submit, device=t1 - t0,
                    total=time.perf_counter() - r.t_submit,
                    rows=n, model=name, tenant=r.tenant)

    def _dispatch_group(self, name: str, slot, reqs: List[Request]) -> None:
        """One model's share of a batch through one padded dispatch."""
        t0 = time.perf_counter()
        t0_wall = time.time()
        rows, good = self._gather_rows(name, slot, reqs)
        if not good:
            return
        X = rows[0] if len(rows) == 1 else np.concatenate(rows, axis=0)
        out = slot.predict(X, raw_score=self.raw_score)
        t1 = time.perf_counter()
        self.stats.record_dispatch(rows=X.shape[0], device_s=t1 - t0)
        lo = 0
        rec = obs_trace.RECORDER
        for r, x in zip(good, rows):
            n = x.shape[0]
            if r.trace is not None:
                # queue_wait + registry_get were recorded by _run_batch;
                # the dispatch span reuses the timestamps the stats
                # already take, so tracing adds no clock reads here
                rec.record("dispatch", r.trace, t0_wall, t1 - t0,
                           rows=n, batch_rows=X.shape[0], model=name)
            r.future.set_result(ServeResult(out[lo:lo + n],
                                            slot.generation))
            lo += n
            self.stats.record_request(queue_wait=t0 - r.t_submit,
                                      device=t1 - t0,
                                      total=time.perf_counter() - r.t_submit,
                                      rows=n, model=name, tenant=r.tenant)


def serve_loop(server: ForestServer, lines, out_stream,
               on_swap=None, stats_stream=None) -> int:
    """Drive a server from an iterable of text request lines (the CLI's
    ``task=serve`` loop; factored here so tests can drive it without a
    process). Line protocol (docs/serving.md):

    - one feature row per line (TSV or CSV) — a predict request;
    - ``swap=<model>`` — atomic hot-swap (``swap=name:<model>`` for a
      non-default registry model);
    - ``model=<name>`` — route subsequent predict lines to that registry
      model (``model=`` resets to the default);
    - ``stats`` — print the Prometheus exposition of the live serving
      metrics to ``stats_stream`` (default: stderr);
    - ``stats json`` — the ``ServeStats.snapshot()`` JSON instead;
    - ``prometheus fleet`` — the fleet-merged exposition (a single
      server renders as a fleet of one, same metric names as a router);
    - ``health`` — one-line health state to ``stats_stream``;
    - ``#``-prefixed lines and blanks are ignored.

    Returns the number of served requests."""
    import sys as _sys
    if stats_stream is None:
        stats_stream = _sys.stderr
    futures = []
    active_model = None
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if line == "stats" or line == "stats prometheus":
            stats_stream.write(server.prometheus())
            stats_stream.flush()
            continue
        if line == "prometheus fleet":
            stats_stream.write(server.prometheus_fleet())
            stats_stream.flush()
            continue
        if line == "stats json":
            stats_stream.write(server.stats_json() + "\n")
            stats_stream.flush()
            continue
        if line == "health":
            stats_stream.write(server.health.state() + "\n")
            stats_stream.flush()
            continue
        if line.startswith("model="):
            name = line.split("=", 1)[1].strip()
            active_model = name or None
            continue
        if line.startswith("swap="):
            from ..guard.degrade import SwapFailed, SwapRejected
            target = line.split("=", 1)[1].strip()
            model = DEFAULT_MODEL
            if ":" in target:
                head, rest = target.split(":", 1)
                # "name:path" routes the swap; bare paths (which may
                # contain ':' on exotic systems) keep working because a
                # registered model name wins only when it exists
                if server.registry.has(head):
                    model, target = head, rest
            try:
                gen = server.swap(target, model=model)
            except (SwapFailed, SwapRejected) as e:
                # degraded, not dead: the active generation keeps serving
                # (stats carry swap_failures + the breaker state)
                log.warning("serve loop: %s", e)
                continue
            if on_swap is not None:
                on_swap(target, gen)
            continue
        delim = "\t" if "\t" in line else ","
        row = np.array([_parse_cell(tok) for tok in line.split(delim)],
                       dtype=np.float32)
        futures.append(server.submit(row, model=active_model))
    for f in futures:
        vals = np.atleast_1d(np.asarray(f.result().values)).reshape(-1)
        out_stream.write("\t".join(f"{v:.10g}" for v in vals) + "\n")
    return len(futures)


def _parse_cell(tok: str) -> float:
    try:
        return float(tok)
    except ValueError:
        return float("nan")
