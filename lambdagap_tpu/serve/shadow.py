"""Shadow evaluation: mirror live traffic to a candidate, off the reply path.

The promotion half of graftloop (docs/continuous-learning.md) needs to
know how a candidate model would answer REAL traffic before any client
sees it. :class:`ShadowMirror` rides the router's submit path: a sampled
slice of requests (``serve_shadow_sample``, same coin-flip shape as the
trace sampler) is handed to the mirror's own worker pool, re-scored on
the shadow replica, and compared against the live answer — per-request
absolute prediction deltas accumulate in a :class:`Reservoir` window the
promotion controller reads.

The contract that makes shadowing safe to arm in production:

- **never on the reply path**: the live future is returned to the caller
  before the mirror sees the request; comparison waits on it from the
  mirror's worker thread. A shadow replica that is slow, overloaded, or
  dead cannot move a live answer by a single byte (tests/test_shadow.py
  asserts bit-identity with the shadow hard-down).
- **overload sheds silently and is counted**: a full mirror queue drops
  the request (``shed``), a dead shadow marks the window ``dead`` —
  nothing propagates, the counters tell the story.
- **the mirror cost is measurable**: each comparison lands a
  ``shadow_predict`` span parented into the request's trace tree, so the
  trace plane attributes exactly what shadowing costs.

Lock discipline (graftlint R9): ``_lock`` guards counters, the sampler
RNG, and the pending gauge only — dispatch, result waits, and comparison
all happen on the worker pool, never under the lock.
"""
from __future__ import annotations

import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..guard.degrade import ReplicaUnavailable
from ..guard.faults import FaultPlan
from ..obs import trace as obs_trace
from ..obs.reservoir import Reservoir
from ..utils import log

# transport-shaped failures that mark the shadow replica dead (the same
# indictment set the router uses for live replicas)
_DEAD_MARKING = (ReplicaUnavailable, ConnectionError, OSError)


class ShadowMirror:
    """One armed shadow window over one candidate replica."""

    def __init__(self, replica, sample: float = 1.0, faults=None,
                 seed: int = 0, max_pending: int = 64,
                 wait_s: float = 10.0, own_replica: bool = True) -> None:
        self.replica = replica
        self.sample = float(sample)
        self._faults = faults if faults is not None else FaultPlan("")
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._pending = 0
        self._max_pending = int(max_pending)
        self._wait_s = float(wait_s)
        self._own = own_replica
        self._closed = False
        self.dead = False
        self.deltas = Reservoir()
        self.counters = {"mirrored": 0, "compared": 0, "shed": 0,
                         "errors": 0}
        self._pool = ThreadPoolExecutor(max_workers=2,
                                        thread_name_prefix="shadow")

    # -- submit-path hook (must stay cheap: coin flip + handoff) --------
    def maybe_mirror(self, x, model, tenant, live_future, ctx) -> None:
        """Called by the router AFTER the live dispatch is in flight; the
        live future is already owned by the caller, so nothing here can
        delay or change the answer."""
        if self._closed:
            return
        with self._lock:
            if self.dead:
                self.counters["shed"] += 1
                return
            if self.sample < 1.0 and self._rng.random() >= self.sample:
                return
            if self._pending >= self._max_pending:
                self.counters["shed"] += 1   # overload sheds silently
                return
            self._pending += 1
            self.counters["mirrored"] += 1
        try:
            self._pool.submit(self._mirror_one, x, model, tenant,
                              live_future, ctx)
        except RuntimeError:                 # pool shut down mid-handoff
            with self._lock:
                self._pending -= 1
                self.counters["shed"] += 1

    # -- worker side ----------------------------------------------------
    def _mirror_one(self, x, model, tenant, live_future, ctx) -> None:
        t0_wall, t0 = time.time(), time.perf_counter()
        outcome, delta = "compared", None
        try:
            self._faults.shadow_fault()
            sx = np.array(x, copy=True)      # caller may reuse its buffer
            sf = self.replica.submit(sx, model=model, tenant=tenant)
            shadow_vals = np.asarray(sf.result(self._wait_s).values)
            live_vals = np.asarray(live_future.result(self._wait_s).values)
            delta = float(np.max(np.abs(shadow_vals - live_vals)))
            with self._lock:
                self.counters["compared"] += 1
                self.deltas.add(delta)
        except Exception as e:               # NOTHING escapes the mirror
            outcome = "shed"
            with self._lock:
                self.counters["shed"] += 1
                self.counters["errors"] += 1
                if isinstance(e, _DEAD_MARKING):
                    self.dead = True
            if isinstance(e, _DEAD_MARKING):
                log.warning("shadow replica down; window marked dead (%s)",
                            e)
        finally:
            with self._lock:
                self._pending -= 1
        if ctx is not None:
            hop = ctx.child()
            obs_trace.RECORDER.record(
                "shadow_predict", ctx, t0_wall, time.perf_counter() - t0,
                span_id=hop.span_id, outcome=outcome, delta=delta)

    # -- control/observability ------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            snap = {"sample": self.sample, "dead": bool(self.dead),
                    "pending": int(self._pending)}
            snap.update({k: int(v) for k, v in self.counters.items()})
            # the delta reservoir is guarded by the same lock as the
            # counters (pure in-memory sort, no blocking work)
            snap["delta"] = (self.deltas.percentiles()
                             if self.counters["compared"] else {})
        return snap

    def close(self) -> None:
        self._closed = True
        # never block a disarm on a wedged shadow RPC: drop queued work,
        # let in-flight worker calls finish on their own bounded waits
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._own:
            try:
                self.replica.close()
            except Exception as e:
                log.warning("closing shadow replica failed: %s", e)
