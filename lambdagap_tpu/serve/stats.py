"""Serving metrics: latency distributions, throughput, cache accounting.

The serving half of ``lambdagap_tpu.obs`` — every number a production
operator needs to size a fleet (the reference ships none of this; the
schema follows what TF-Serving/Triton-style batchers expose: per-request
queue wait, device time, end-to-end percentiles, batch occupancy, cache
hit rates, swap counts). All methods are thread-safe; ``snapshot`` is cheap
enough to poll, and ``obs.prom.render_serve`` turns it into Prometheus
text (the ``stats`` line of the task=serve loop, docs/serving.md).
"""
from __future__ import annotations

import json
import threading
import time
import zlib
from typing import Dict, Optional

from ..obs.reservoir import Reservoir as _Reservoir


class ServeStats:
    """Thread-safe serving counters + latency reservoirs.

    Times are recorded in seconds and reported in milliseconds. Schema of
    :meth:`snapshot` is documented in docs/serving.md and is the JSON the
    ``task=serve`` CLI and ``bench_serve.py`` emit.
    """

    def __init__(self, max_samples: int = 100_000) -> None:
        self._lock = threading.Lock()
        self.t_start = time.perf_counter()
        self.n_requests = 0
        self.n_rows = 0
        self.n_batches = 0
        self.n_batch_rows = 0
        self.n_dispatch_rows = 0
        self.dispatch_device_s = 0.0
        self.n_errors = 0
        self.n_timeouts = 0
        self.n_rejected = 0
        self.n_swap_failures = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.per_bucket: Dict[int, Dict[str, int]] = {}
        self.forest_builds = 0
        self.bucket_compiles = 0
        self.compiles_local = 0
        self.compiles_shared = 0
        self.packed_dispatches = 0
        self.swaps = 0
        self.evictions = 0
        self.readmissions = 0
        self._lat = _Reservoir(max_samples, seed=1)
        self._queue_wait = _Reservoir(max_samples, seed=2)
        self._device = _Reservoir(max_samples, seed=3)
        # per-model / per-tenant breakdowns (docs/serving.md): bounded
        # reservoirs per key so a many-tenant deployment stays O(keys)
        self._models: Dict[str, Dict] = {}
        self._tenants: Dict[str, Dict] = {}

    def _group(self, table: Dict[str, Dict], key: str) -> Dict:
        g = table.get(key)
        if g is None:
            g = table[key] = {"requests": 0, "rows": 0, "shed": 0,
                              "rejected": 0, "evictions": 0,
                              "readmissions": 0,
                              "lat": _Reservoir(
                                  4096,
                                  seed=zlib.crc32(key.encode()) & 0xffff)}
        return g

    # -- recording ------------------------------------------------------
    def record_request(self, queue_wait: float, device: float, total: float,
                       rows: int = 1, model: Optional[str] = None,
                       tenant: Optional[str] = None) -> None:
        with self._lock:
            self.n_requests += 1
            self.n_rows += rows
            self._lat.add(total)
            self._queue_wait.add(queue_wait)
            self._device.add(device)
            for table, key in ((self._models, model),
                               (self._tenants, tenant)):
                if key is not None:
                    g = self._group(table, key)
                    g["requests"] += 1
                    g["rows"] += rows
                    g["lat"].add(total)

    def record_batch(self, n_requests: int, rows: int) -> None:
        with self._lock:
            self.n_batches += 1
            self.n_batch_rows += rows

    def record_dispatch(self, rows: int, device_s: float) -> None:
        """One device dispatch: ``rows`` real rows in ``device_s`` seconds
        of wall-clock. Unlike the per-request reservoirs (whose rows share
        the batch's device time), this sums exactly once per dispatch, so
        ``device_us_per_row`` in the snapshot is the true per-row cost of
        the active traversal engine — the number the predict-roofline
        benches compare against the naive and native baselines."""
        with self._lock:
            self.n_dispatch_rows += rows
            self.dispatch_device_s += device_s

    def record_error(self) -> None:
        with self._lock:
            self.n_errors += 1

    def record_timeout(self, model: Optional[str] = None,
                       tenant: Optional[str] = None) -> None:
        """A request shed before dispatch (deadline expired in queue)."""
        with self._lock:
            self.n_timeouts += 1
            for table, key in ((self._models, model),
                               (self._tenants, tenant)):
                if key is not None:
                    self._group(table, key)["shed"] += 1

    def record_rejected(self, tenant: Optional[str] = None) -> None:
        """A submit refused by full-queue backpressure (reject policy or a
        per-tenant admission quota)."""
        with self._lock:
            self.n_rejected += 1
            if tenant is not None:
                self._group(self._tenants, tenant)["rejected"] += 1

    def record_eviction(self, model: Optional[str] = None) -> None:
        """A registry forest evicted under the HBM budget (its compiled
        executables freed; the host-side model is retained)."""
        with self._lock:
            self.evictions += 1
            if model is not None:
                self._group(self._models, model)["evictions"] += 1

    def record_readmission(self, model: Optional[str] = None) -> None:
        """An evicted model recompiled on first use after eviction."""
        with self._lock:
            self.readmissions += 1
            if model is not None:
                self._group(self._models, model)["readmissions"] += 1

    def record_swap_failure(self) -> None:
        """A hot-swap that failed to build/compile; the previous
        generation kept serving (rollback)."""
        with self._lock:
            self.n_swap_failures += 1

    def record_cache(self, hit: bool, bucket: Optional[int] = None) -> None:
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_misses += 1
            if bucket is not None:
                b = self.per_bucket.setdefault(int(bucket),
                                               {"hits": 0, "misses": 0})
                b["hits" if hit else "misses"] += 1

    def record_forest_build(self) -> None:
        with self._lock:
            self.forest_builds += 1

    def record_bucket_compile(self, bucket: int) -> None:
        with self._lock:
            self.bucket_compiles += 1

    def record_compile_local(self) -> None:
        """A forest lowered by the infer compiler ON this replica (no
        fleet peer had shipped the artifact first)."""
        with self._lock:
            self.compiles_local += 1

    def record_compile_shared(self) -> None:
        """A compiled-forest build satisfied from the artifact store — a
        peer's sha256-addressed compile admitted instead of re-lowering
        (the fleet-wide one-compile contract, docs/serving.md)."""
        with self._lock:
            self.compiles_shared += 1

    def record_packed_dispatch(self, models: int, rows: int) -> None:
        """One cross-model pack dispatch covering ``models`` tenants'
        rows in a single executable (serve_pack_models)."""
        del models, rows
        with self._lock:
            self.packed_dispatches += 1

    def record_swap(self) -> None:
        with self._lock:
            self.swaps += 1

    # -- reporting ------------------------------------------------------
    @staticmethod
    def _ms(d: Dict[str, float]) -> Dict[str, float]:
        return {k: v * 1e3 for k, v in d.items()}

    @staticmethod
    def _group_block(table: Dict[str, Dict],
                     reservoirs: bool = False) -> Dict[str, Dict]:
        out = {}
        for key, g in sorted(table.items()):
            out[key] = {
                "requests": g["requests"], "rows": g["rows"],
                "shed": g["shed"], "rejected": g["rejected"],
                "evictions": g["evictions"],
                "readmissions": g["readmissions"],
                "latency_ms": {k: v * 1e3
                               for k, v in g["lat"].percentiles().items()},
            }
            if reservoirs:
                out[key]["latency_state"] = g["lat"].state(scale=1e3)
        return out

    def snapshot(self, reservoirs: bool = False) -> Dict:
        """The metrics dict of docs/serving.md. ``reservoirs=True`` adds
        the raw reservoir states (``obs.reservoir.Reservoir.state``, ms
        units, bounded) that the fleet plane merges — the lifted
        aggregate a scraper needs to sum distributions, not just
        counters."""
        with self._lock:
            elapsed = max(time.perf_counter() - self.t_start, 1e-9)
            total = self.cache_hits + self.cache_misses
            out = {
                "requests": self.n_requests,
                "rows": self.n_rows,
                "errors": self.n_errors,
                "timeouts": self.n_timeouts,
                "rejected": self.n_rejected,
                "swap_failures": self.n_swap_failures,
                "elapsed_s": elapsed,
                "throughput_rps": self.n_requests / elapsed,
                "throughput_rows_per_s": self.n_rows / elapsed,
                "latency_ms": self._ms(self._lat.percentiles()),
                "queue_wait_ms": self._ms(self._queue_wait.percentiles()),
                "device_ms": self._ms(self._device.percentiles()),
                "batches": {
                    "count": self.n_batches,
                    "mean_rows": (self.n_batch_rows / self.n_batches
                                  if self.n_batches else 0.0),
                },
                "device_us_per_row": (
                    1e6 * self.dispatch_device_s / self.n_dispatch_rows
                    if self.n_dispatch_rows else 0.0),
                "cache": {
                    "hits": self.cache_hits,
                    "misses": self.cache_misses,
                    "hit_rate": (self.cache_hits / total) if total else 0.0,
                    "forest_builds": self.forest_builds,
                    "bucket_compiles": self.bucket_compiles,
                    "compiles_local": self.compiles_local,
                    "compiles_shared": self.compiles_shared,
                    "packed_dispatches": self.packed_dispatches,
                    "per_bucket": {str(k): dict(v)
                                   for k, v in self.per_bucket.items()},
                },
                "swaps": self.swaps,
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "per_model": self._group_block(self._models, reservoirs),
                "per_tenant": self._group_block(self._tenants, reservoirs),
            }
            if reservoirs:
                out["reservoirs"] = {
                    "latency_ms": self._lat.state(scale=1e3),
                    "queue_wait_ms": self._queue_wait.state(scale=1e3),
                    "device_ms": self._device.state(scale=1e3),
                }
            return out

    def to_json(self, **kwargs) -> str:
        kwargs.setdefault("indent", 2)
        return json.dumps(self.snapshot(), **kwargs)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")
