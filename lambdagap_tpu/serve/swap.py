"""Atomic model hot-swap: load, pre-warm, flip a generation pointer.

Production serving replaces models without draining traffic. The protocol
here is the standard read-copy-update shape:

1. the new model text loads and compiles into a fresh
   :class:`~lambdagap_tpu.serve.cache.CompiledForestCache` off the serving
   path (its padding buckets are pre-warmed, so post-swap requests pay no
   compile);
2. the controller flips ONE reference (``self.active``) — an atomic store
   under the GIL;
3. readers (the batcher worker) snapshot ``active`` once per batch and use
   that snapshot for the whole dispatch.

In-flight batches therefore finish on the forest they started with and new
batches see the new one: no request is ever dropped, and none can observe
a torn mix of generations — every response carries exactly one
generation's predictions.

Failure semantics (lambdagap_tpu.guard, docs/robustness.md): a swap whose
load/compile raises never touches ``active`` — rollback is structural, the
old generation simply keeps serving — and the failure feeds a
consecutive-failure circuit breaker. With the circuit open, further swaps
are rejected fast (:class:`~lambdagap_tpu.guard.SwapRejected`) until the
cooldown admits a probe, so a flapping model publisher cannot convoy the
serving path behind repeated doomed compiles.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from ..guard.degrade import CircuitBreaker, SwapFailed, SwapRejected


def load_booster(source, params=None, config=None):
    """Resolve a swap source into a GBDT: an in-memory ``Booster``/``GBDT``
    passes through; anything else is a model file path or model text
    (``models.model_text.read_model_source``)."""
    from ..config import Config
    from ..models.gbdt import GBDT
    from ..models.model_text import read_model_source
    if hasattr(source, "_booster"):          # basic.Booster
        return source._booster
    if isinstance(source, GBDT):
        return source
    text = read_model_source(source)
    return GBDT.from_model_string(text,
                                  config or Config.from_params(params or {}))


class SwapController:
    """Holds the active compiled forest and serializes generation flips.

    ``active`` is read lock-free by the serving path; ``_swap_lock`` only
    serializes writers (concurrent swaps apply in call order).
    """

    def __init__(self, build_cache: Callable, stats=None,
                 breaker: Optional[CircuitBreaker] = None) -> None:
        self._build = build_cache        # (gbdt, generation) -> cache
        self._stats = stats
        self.breaker = breaker if breaker is not None else CircuitBreaker(0)
        self._swap_lock = threading.Lock()
        self.active = None               # CompiledForestCache

    def install(self, gbdt) -> int:
        """Initial model (generation 0) — or a swap of an already-loaded
        booster object."""
        with self._swap_lock:
            gen = 0 if self.active is None else self.active.generation + 1
            # graftlint: disable=R5 — deliberate: _swap_lock serializes
            # WRITERS only (concurrent swaps apply in call order); readers
            # snapshot `active` lock-free, so the build convoys no request
            cache = self._build(gbdt, gen)
            self.active = cache          # atomic flip
            if gen > 0 and self._stats is not None:
                self._stats.record_swap()
        return gen

    def swap(self, source, params=None, background: bool = False):
        """Swap to a new model (path / model text / Booster / GBDT).

        Synchronous by default: returns the new generation once the flip
        happened. ``background=True`` runs load+warm+flip on a daemon
        thread and returns it immediately (serving continues on the old
        generation until the flip).

        A failed load/compile raises :class:`SwapFailed` WITHOUT touching
        the active generation (rollback by construction) and trips the
        circuit breaker; an open circuit rejects the swap up front with
        :class:`SwapRejected`."""

        def work() -> int:
            from ..utils import log
            if not self.breaker.allow():
                raise SwapRejected(
                    "swap circuit open after "
                    f"{self.breaker.consecutive_failures} consecutive "
                    "failures; serving continues on generation "
                    f"{self.active.generation} (cooldown "
                    f"{self.breaker.cooldown_s:g}s)")
            try:
                gbdt = load_booster(source, params)
                with self._swap_lock:
                    gen = self.active.generation + 1
                    # graftlint: disable=R5 — deliberate, same as install():
                    # writer-only lock; the serving path never contends on it
                    cache = self._build(gbdt, gen)
                    self.active = cache      # atomic flip
            except Exception as e:
                self._swap_failed(e)
                raise SwapFailed(f"swap failed ({e}); serving continues on "
                                 f"generation {self.active.generation}") from e
            self.breaker.record_success()
            if self._stats is not None:
                self._stats.record_swap()
            log.info("serve: swapped to generation %d (%s engine, "
                     "pre-warmed before the flip)", gen,
                     getattr(cache, "engine", "?"))
            return gen

        if background:
            t = threading.Thread(target=work, daemon=True,
                                 name="lambdagap-serve-swap")
            t.start()
            return t
        return work()

    def _swap_failed(self, exc) -> None:
        from ..utils import log
        self.breaker.record_failure()
        if self._stats is not None:
            self._stats.record_swap_failure()
        log.warning("serve: model swap failed (%s); the active generation %d "
                  "keeps serving (breaker: %s)", exc,
                  self.active.generation if self.active is not None else -1,
                  self.breaker.state())
