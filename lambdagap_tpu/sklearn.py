"""scikit-learn estimator wrappers.

(reference: python-package/lightgbm/sklearn.py — LGBMModel, LGBMClassifier,
LGBMRegressor, LGBMRanker.) Names keep the LGBM prefix so reference users can
switch imports without code changes.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .engine import train as train_fn
from .utils import log


class LGBMModel:
    """Base sklearn-style estimator (reference: sklearn.py LGBMModel)."""

    _objective_default = "regression"

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs: Any) -> None:
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features: Optional[int] = None
        self._classes: Optional[np.ndarray] = None
        self.best_iteration_: int = -1

    # -- sklearn protocol ------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {k: getattr(self, k) for k in (
            "boosting_type", "num_leaves", "max_depth", "learning_rate",
            "n_estimators", "subsample_for_bin", "objective", "class_weight",
            "min_split_gain", "min_child_weight", "min_child_samples",
            "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
            "reg_lambda", "random_state", "n_jobs", "importance_type")}
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self._other_params[k] = v
        return self

    def _train_params(self) -> Dict[str, Any]:
        p = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "num_iterations": self.n_estimators,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "objective": self.objective or self._objective_default,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbose": -1,
        }
        if self.random_state is not None:
            p["seed"] = int(self.random_state)
        p.update(self._other_params)
        return p

    def _sample_weight(self, y, sample_weight):
        if self.class_weight is not None and self._classes is not None:
            if self.class_weight == "balanced":
                counts = np.bincount(y.astype(int), minlength=len(self._classes))
                w_per_class = len(y) / np.maximum(
                    counts * len(self._classes), 1)
            else:
                w_per_class = np.asarray(
                    [self.class_weight.get(c, 1.0) for c in self._classes])
            cw = w_per_class[y.astype(int)]
            sample_weight = cw if sample_weight is None else sample_weight * cw
        return sample_weight

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMModel":
        params = self._train_params()
        if eval_metric is not None:
            params["metric"] = eval_metric
        y = np.asarray(y)
        sample_weight = self._sample_weight(y, sample_weight)
        ds = Dataset(X, label=y, weight=sample_weight, init_score=init_score,
                     group=group, feature_name=feature_name,
                     categorical_feature=categorical_feature)
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            for i, (Xe, ye) in enumerate(eval_set):
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                valid_sets.append(ds.create_valid(Xe, label=np.asarray(ye),
                                                  weight=vw, group=vg))
        self._Booster = train_fn(params, ds,
                                 num_boost_round=self.n_estimators,
                                 valid_sets=valid_sets,
                                 valid_names=eval_names,
                                 init_model=init_model,
                                 callbacks=callbacks)
        self.best_iteration_ = self._Booster.best_iteration
        self._n_features = ds.num_feature()
        return self

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs) -> np.ndarray:
        self._check_fitted()
        ni = -1 if num_iteration is None else num_iteration
        return self._Booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=ni, pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    def _check_fitted(self) -> None:
        if self._Booster is None:
            raise RuntimeError("Estimator not fitted; call fit() first")

    # -- sklearn attributes ----------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(self.importance_type)

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self.n_features_

    @property
    def n_estimators_(self) -> int:
        self._check_fitted()
        return self._Booster.num_trees() // max(
            self._Booster.num_model_per_iteration(), 1)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()


class LGBMRegressor(LGBMModel):
    _objective_default = "regression"

    def _more_tags(self):
        return {"estimator_type": "regressor"}


class LGBMClassifier(LGBMModel):
    _objective_default = "binary"

    def fit(self, X, y, **kwargs) -> "LGBMClassifier":
        y = np.asarray(y)
        self._classes = np.unique(y)
        n_classes = len(self._classes)
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        if n_classes > 2:
            if self.objective is None:
                self.objective = "multiclass"
            self._other_params.setdefault("num_class", n_classes)
        elif self.objective is None:
            self.objective = "binary"
        return super().fit(X, y_enc, **kwargs)

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        return len(self._classes)

    def predict_proba(self, X, **kwargs) -> np.ndarray:
        p = super().predict(X, **kwargs)
        if p.ndim == 1:
            return np.column_stack([1.0 - p, p])
        return p

    def predict(self, X, raw_score: bool = False, **kwargs) -> np.ndarray:
        p = super().predict(X, raw_score=raw_score, **kwargs)
        if raw_score or kwargs.get("pred_leaf") or kwargs.get("pred_contrib"):
            return p
        if p.ndim == 1:
            idx = (p > 0.5).astype(int)
        else:
            idx = np.argmax(p, axis=1)
        return self._classes[idx]


class LGBMRanker(LGBMModel):
    _objective_default = "lambdarank"

    def fit(self, X, y, group=None, **kwargs) -> "LGBMRanker":
        if group is None and "eval_group" not in kwargs:
            log.fatal("LGBMRanker.fit requires the `group` argument")
        return super().fit(X, y, group=group, **kwargs)
