"""Logging for lambdagap_tpu.

TPU-native analog of the reference's ``Log`` class with levels and a pluggable
callback (reference: include/LightGBM/utils/log.h:43-60, used by the Python
package's ``register_logger``).
"""
from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

_logger = logging.getLogger("lambdagap_tpu")
if not _logger.handlers:
    _handler = logging.StreamHandler(sys.stdout)
    _handler.setFormatter(logging.Formatter("[LambdaGapTPU] [%(levelname)s] %(message)s"))
    _logger.addHandler(_handler)
    _logger.setLevel(logging.INFO)

_custom_callback: Optional[Callable[[str], None]] = None


def register_logger(logger: logging.Logger) -> None:
    """Replace the package logger (mirrors lightgbm.register_logger)."""
    global _logger
    _logger = logger


def set_verbosity(verbosity: int) -> None:
    """Map LightGBM-style verbosity int to logging level.

    <0: fatal only, 0: warning, 1: info, >1: debug
    (reference: include/LightGBM/config.h ``verbosity`` semantics).
    """
    if verbosity < 0:
        _logger.setLevel(logging.CRITICAL)
    elif verbosity == 0:
        _logger.setLevel(logging.WARNING)
    elif verbosity == 1:
        _logger.setLevel(logging.INFO)
    else:
        _logger.setLevel(logging.DEBUG)


def debug(msg: str, *args) -> None:
    _logger.debug(msg, *args)


def debug_enabled() -> bool:
    return _logger.isEnabledFor(logging.DEBUG)


def info(msg: str, *args) -> None:
    _logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    _logger.warning(msg, *args)


def fatal(msg: str, *args) -> None:
    """Log and raise — analog of Log::Fatal (reference: utils/log.h)."""
    text = msg % args if args else msg
    _logger.critical(text)
    raise RuntimeError(text)
