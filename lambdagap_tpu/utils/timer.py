"""Coarse per-phase wall-clock timers (DEPRECATED shim).

TPU-native analog of the reference's ``Common::Timer global_timer`` +
``FunctionTimer`` RAII (reference: include/LightGBM/utils/common.h:984-1068,
compiled in with USE_TIMETAG). Superseded by ``lambdagap_tpu.obs``
(docs/observability.md): when telemetry is active, ``TrainTelemetry`` feeds
its phase spans into ``global_timer`` under the historical scope names, so
the end-of-train report keeps working — but new code should read
``booster._booster.telemetry`` instead.

Enablement is evaluated at USE time (``timer_enabled()``), not snapshotted
at import: flipping ``LAMBDAGAP_TIMETAG`` (or monkeypatching ``_ENABLED``)
after import now takes effect, and the ``telemetry`` config knob enables
the same accounting without the env var.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

# import-time snapshot kept ONLY as a monkeypatch/back-compat override;
# timer_enabled() re-reads the environment on every call
_ENABLED = os.environ.get("LAMBDAGAP_TIMETAG", "0") not in ("0", "", "false")


def timer_enabled() -> bool:
    """Legacy-timer enablement, evaluated now (env var or the
    ``_ENABLED`` override)."""
    return _ENABLED or os.environ.get(
        "LAMBDAGAP_TIMETAG", "0") not in ("0", "", "false")


class Timer:
    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def scope(self, name: str) -> Iterator[None]:
        if not timer_enabled():
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        lines = ["LambdaGapTPU timers:"]
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            lines.append(f"  {name}: {self.totals[name]:.4f}s x{self.counts[name]}")
        return "\n".join(lines)

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


global_timer = Timer()
