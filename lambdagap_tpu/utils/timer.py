"""Coarse per-phase wall-clock timers.

TPU-native analog of the reference's ``Common::Timer global_timer`` +
``FunctionTimer`` RAII (reference: include/LightGBM/utils/common.h:984-1068,
compiled in with USE_TIMETAG). Here the equivalent fine-grained story is
``jax.profiler`` traces; this module provides the same coarse per-phase table
the reference prints at exit.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

_ENABLED = os.environ.get("LAMBDAGAP_TIMETAG", "0") not in ("0", "", "false")


class Timer:
    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def scope(self, name: str) -> Iterator[None]:
        if not _ENABLED:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        lines = ["LambdaGapTPU timers:"]
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            lines.append(f"  {name}: {self.totals[name]:.4f}s x{self.counts[name]}")
        return "\n".join(lines)

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


global_timer = Timer()
