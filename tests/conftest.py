"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The driver/bench run on real TPU; tests exercise the same code paths on CPU
(the reference's analog: CPU-vs-GPU parity tests, tests/python_package_test/
test_dual.py). 8 virtual devices let distributed learners be tested without
hardware (SURVEY.md §4).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
