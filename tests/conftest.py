"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The driver/bench run on real TPU; tests exercise the same code paths on CPU
(the reference's analog: CPU-vs-GPU parity tests, tests/python_package_test/
test_dual.py). 8 virtual devices let distributed learners be tested without
hardware (SURVEY.md §4).

NOTE: the environment's site hook may pre-register a remote TPU backend and
force ``JAX_PLATFORMS``; ``jax.config.update`` after import wins as long as
no backend has been initialized yet, so it must happen here, before any test
imports touch a jax array.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
