"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The driver/bench run on real TPU; tests exercise the same code paths on CPU
(the reference's analog: CPU-vs-GPU parity tests, tests/python_package_test/
test_dual.py). 8 virtual devices let distributed learners be tested without
hardware (SURVEY.md §4).

NOTE: the environment's site hook may pre-register a remote TPU backend and
force ``JAX_PLATFORMS``; ``jax.config.update`` after import wins as long as
no backend has been initialized yet, so it must happen here, before any test
imports touch a jax array.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


# ---------------------------------------------------------------------------
# multi-process capability probe
# ---------------------------------------------------------------------------
# The CPU PJRT client cannot execute computations spanning processes; every
# multi-process test on a CPU-only box dies with this exact message deep in
# a subprocess. Probing it ONCE per session and skipping loudly keeps those
# tests from masquerading as failures (and from masking real regressions:
# any OTHER failure in the children still fails the test).
MP_CPU_REASON = "Multiprocess computations aren't implemented on the CPU backend"

_MP_PROBE_CHILD = r"""
import os, sys
sys.path.insert(0, %r)
import numpy as np
import jax
rank = int(sys.argv[1]); port = sys.argv[2]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
from lambdagap_tpu.parallel.sharding import (DATA_AXIS, make_mesh, shard_map,
                                             spec)
import jax.numpy as jnp
mesh = make_mesh(0)
x = jax.make_array_from_process_local_data(
    jax.sharding.NamedSharding(mesh, spec("grad")), np.ones(4, np.float32))
op = jax.jit(shard_map(lambda v: jax.lax.psum(jnp.sum(v), DATA_AXIS),
                       mesh=mesh, in_specs=(spec("grad"),),
                       out_specs=spec("rep"), check_vma=False))
print("MP_PROBE_" + "OK", float(np.asarray(op(x))))
"""

_mp_probe_result = {}


def multiprocess_cpu_error() -> str:
    """"" when 2-process collectives work here; the skip reason otherwise.

    Spawns two minimal children (distributed init + one cross-process psum)
    in the same stripped environment the real multi-process tests use.
    Cached for the session — the probe runs once, not per test.
    """
    if "err" in _mp_probe_result:
        return _mp_probe_result["err"]
    import socket
    import subprocess
    import sys
    import tempfile
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    child = _MP_PROBE_CHILD % (os.getcwd(),)
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "mp_probe.py")
        with open(script, "w") as f:
            f.write(child)
        env = {k: v for k, v in os.environ.items()
               if "AXON" not in k and k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs = [subprocess.Popen([sys.executable, script, str(r), port],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  cwd=os.getcwd(), env=env)
                 for r in range(2)]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out = "probe timed out"
            outs.append(out)
    # reason check FIRST: a failed child's traceback quotes its own source,
    # so the success marker must never gate a failure
    if any(MP_CPU_REASON in o for o in outs):
        err = MP_CPU_REASON
    elif all("MP_PROBE_OK" in o for o in outs):
        err = ""
    else:
        # an unexpected probe failure must NOT skip-convert real test
        # failures — report capability as present and let the test fail
        # with its own diagnostics
        err = ""
    _mp_probe_result["err"] = err
    return err


def skip_unless_multiprocess() -> None:
    """pytest.skip (with the exact backend message) when this host cannot
    run cross-process JAX computations."""
    err = multiprocess_cpu_error()
    if err:
        pytest.skip(err)
