"""A hazard-free module: the scan must report nothing here."""
import jax.numpy as jnp


def scale(x, factor):
    return x * jnp.float32(factor)
