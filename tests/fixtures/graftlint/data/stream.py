"""R1/R7 fixture (out-of-core stream path): a blocking host sync inside
the shard-ring fill loop defeats the H2D/compute overlap silently (the
run still converges, just at un-overlapped link speed), and a timing
bracket over the pump is only honest when it closes with the ring-slot
completion sync (``wait_ready``)."""
import time

import jax
import jax.numpy as jnp


def stream_windows(nch, fetch, consume):
    ring = []
    for c in range(nch):
        buf = jax.device_put(fetch(c))
        _ = float(jnp.sum(buf))  # BAD:R1
        ring.append(buf)
        consume(c, ring.pop(0))


def _train_tree_stream(state, windows):
    for w in windows:
        arr = jax.device_put(w)
        state = state + jnp.sum(arr)
        host = jax.device_get(state)  # BAD:R1
    return state


def fill_ring_once(host_buf):
    # not a hot name, not in a loop: a one-time setup upload may sync
    dev = jax.device_put(host_buf)
    return jax.device_get(dev)


def time_pump_unsynced(ring, windows):
    t0 = time.perf_counter()
    for w in windows:
        jnp.dot(w, w)
    return time.perf_counter() - t0  # BAD:R7


def time_pump_ring_synced(ring, windows):
    # GOOD: the bracket closes by draining the ring — wait_ready is the
    # slot-completion sync, so the delta covers finished transfers
    t0 = time.perf_counter()
    for w in windows:
        jnp.dot(w, w)
    ring.wait_ready()
    return time.perf_counter() - t0
