# graftlint: disable-file=R4
"""File-level suppression fixture: R4 is off for this whole file."""
import jax.numpy as jnp


def make(n):
    return jnp.zeros(n)


def make2(n):
    return jnp.arange(n)
