"""R1 fixture (compiled-forest subsystem): infer/ is a HOT_PATHS file —
a D2H inside the node-block packing loop serializes every tree of a
hot-swap's compile against the serving chip, and the engine's jitted
drivers are hot by function name with no loop needed."""
import jax
import jax.numpy as jnp


def pack_node_blocks(groups, budget):
    # the breadth-first node-block packing loop: one iteration per tree
    # group per compile; a device fetch here stalls the swap build
    blocks, cur, used = [], [], 0
    for root, nodes in groups:
        size = jnp.asarray([len(nodes)]).sum()
        used += size.item()  # BAD:R1
        cur.append((root, nodes))
        if used >= budget:
            blocks.append(cur)
            cur, used = [], 0
    if cur:
        blocks.append(cur)
    return blocks


def _predict_compiled(x, blocks):
    # hot by function name (the engine's jitted driver), no loop needed
    out = jnp.zeros((1, x.shape[0]), jnp.float32)
    return jax.device_get(out)  # BAD:R1


def artifact_digest(buffers):
    # not hot: one-time content hashing on host-side numpy buffers
    return jax.device_get(jnp.asarray(sorted(buffers)))
