"""R1 fixture (batch-scoring driver): infer/stream.py is a HOT_PATHS
file — its ring-fill/drive loop runs once per pumped window for the whole
out-of-core pass, so a D2H inside it serializes every H2D prefetch
against every score readback (the two overlaps the double-ring design
exists to protect), and the driver itself is hot by function name."""
import jax
import jax.numpy as jnp


def fill_score_ring(windows, scorer, ring):
    # the ring-fill loop: one iteration per scoring window; fetching the
    # scores synchronously here defeats the D2H ring — the copy must be
    # issued async and consumed a window later
    total = 0.0
    for key, dev in windows:
        scores = jnp.asarray(scorer(dev), jnp.float32)
        checksum = scores.sum()
        total += checksum.item()  # BAD:R1
        ring.append((key, scores))
    return total


def predict_stream(source, scorer):
    # hot by function name (the batch-scoring driver): a blocking fetch
    # per window runs at un-overlapped link speed even outside a loop
    out = jnp.zeros((1, 8), jnp.float32)
    return jax.device_get(scorer(out))  # BAD:R1


def assemble_report(tiles):
    # not hot: one-time result assembly over host-side numpy tiles
    return sorted(tiles)
