"""R7 fixture: cost-plane wall joins (the obs/costplane.py note_wall feeds).

A wall noted into the cost plane is divided into analytic rooflines, so an
unsynced bracket poisons every fraction-of-roofline built on it: the bad
bracket times only the enqueue of the dispatch it wraps. Good brackets end
device-complete (device_get / block_until_ready) before the clock is read.
"""
import time

import jax
import jax.numpy as jnp


def bad_wall_join(plane, x):
    t0 = time.perf_counter()
    y = jnp.tanh(x)
    plane.note_wall("predict", time.perf_counter() - t0)  # BAD:R7
    return y


def good_device_complete_wall(plane, x):
    t0 = time.perf_counter()
    y = jax.device_get(jnp.tanh(x))
    plane.note_wall("predict", time.perf_counter() - t0)
    return y


def good_blocked_window(plane, scorer, dev):
    # the predict_stream pump's shape: the scorer result is blocked on
    # inside the bracket, so the noted window wall is device-complete
    t0 = time.perf_counter()
    scorer(dev).block_until_ready()
    plane.note_wall("predict_stream", time.perf_counter() - t0)


def suppressed_dispatch_wall(plane, x):
    t0 = time.perf_counter()
    y = jnp.sum(x)
    # graftlint: disable=R7 — measures enqueue latency on purpose (a
    # dispatch-overhead counter, not a roofline wall)
    plane.note_wall("dispatch_only", time.perf_counter() - t0)
    return y
