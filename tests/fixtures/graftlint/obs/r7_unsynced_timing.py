"""R7 fixture: perf_counter deltas bracketing async device dispatch.

Bad brackets time a jax dispatch (or a boosting-loop method that returns
device values) with no completion sync before the clock is read; good
brackets either sync inside the bracket or time host-returning calls.
"""
import time

import jax.numpy as jnp
import numpy as np


def bad_jnp_delta(x):
    t0 = time.perf_counter()
    y = jnp.sin(x) * 2.0
    return y, time.perf_counter() - t0  # BAD:R7


def bad_update_loop(booster):
    t0 = time.time()
    for _ in range(10):
        booster.update()
    return time.time() - t0  # BAD:R7


def good_synced_loop(booster):
    t0 = time.perf_counter()
    for _ in range(10):
        booster.update()
    np.asarray(booster.scores[:1])      # forces device completion
    return time.perf_counter() - t0


def good_float_forced(x):
    t0 = time.perf_counter()
    s = float(jnp.sum(x))               # float() over the device scalar
    return s, time.perf_counter() - t0


def good_host_returning(booster, x):
    t0 = time.perf_counter()
    y = booster.predict(x)              # predict syncs internally
    return y, time.perf_counter() - t0


def suppressed_warmup(booster):
    t0 = time.time()
    booster.update()
    # graftlint: disable=R7 — warmup bracket intentionally includes only
    # dispatch+compile; the steady-state loop below it is the synced one
    return time.time() - t0
