"""R1 fixture (trace/fleet plane, ISSUE 12): a D2H sync inside span
bookkeeping or the scrape merge. Span enter/exit runs on every sampled
request at every hop — a device sync there charges the request the very
latency the span claims to observe; one inside the scrape-merge loop
convoys the signal plane behind the data plane. Flagged via the hot
function names (``record``/``merge_snapshots``) AND via loop-in-hot-path
(any function in an ``/obs/trace`` file)."""
import jax
import jax.numpy as jnp


class SpanRecorder:
    def record(self, name, value, t0, dur):
        # hot by function name: span exit must be pure host bookkeeping
        payload = jnp.asarray(value)
        return float(jnp.sum(payload))  # BAD:R1

    def flush_ring(self, ring):
        # arbitrary name, but a loop body inside an /obs/trace file: a
        # sync per ring record stalls every flight-recorder flush
        out = []
        for rec in ring:
            dev = jnp.asarray(rec)
            out.append(jax.device_get(dev))  # BAD:R1
        return out


def span_duration_host(t0, t1):
    # host-only arithmetic: no device involved, never flagged
    return max(t1 - t0, 0.0)
