"""R1 fixture (Pallas histogram path): a D2H read inside the per-tile
loop of ops/hist_pallas.py serializes every histogram chunk of every
split — flagged even though the enclosing function name is arbitrary."""
import jax
import jax.numpy as jnp


def tiled_hist_kernel_wrapper(bins, gh, fblk):
    acc = jnp.zeros((8, bins.shape[1]), jnp.float32)
    for f in range(fblk):
        acc = acc + gh
        _ = float(jnp.sum(acc))  # BAD:R1
    return acc


def hist_pallas(bins, gh8, num_bins):
    # hot by function name, no loop needed
    out = jnp.sum(gh8)
    return jax.device_get(out)  # BAD:R1


def pick_blocks_host(shape):
    # not a hot name, not in a loop: fine (one-time block-shape choice)
    return jax.device_get(jnp.asarray(shape))
