"""R1 fixture (linear-leaf solve path): a D2H read inside the
moment-accumulation chunk loop of ops/linear.py serializes every chunk of
every tree's leaf solve — flagged even under an arbitrary function name
(loop-in-hot-path), and in the named hot functions without a loop."""
import jax
import jax.numpy as jnp


def chunked_moment_wrapper(X, leaf_idx, nch):
    acc = jnp.zeros((8, 9, 9), jnp.float32)
    for c in range(nch):
        acc = acc + jnp.einsum("wp,wq->pq", X, X)
        _ = float(jnp.sum(acc))  # BAD:R1
    return acc


def accumulate_leaf_moments(X, leaf_idx, grad, hess, feat_tbl):
    # hot by function name, no loop needed
    out = jnp.einsum("wp,wq->pq", X, X)
    return jax.device_get(out)  # BAD:R1


def pick_width_host(shape):
    # not a hot name, not in a loop: fine (one-time width choice)
    return jax.device_get(jnp.asarray(shape))
