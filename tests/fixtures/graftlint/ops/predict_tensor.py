"""R1 fixture (tensorized predict path): a D2H sync inside the tile
traversal loop of ops/predict_tensor.py serializes every tile dispatch —
flagged even though the enclosing function name is arbitrary."""
import jax
import jax.numpy as jnp


def tiled_predict(x, tiles):
    carry = jnp.zeros((1, x.shape[0]), jnp.float32)
    for blk, tc, _ in tiles:
        carry = carry + blk
        _ = float(jnp.sum(carry))  # BAD:R1
    return carry


def predict_forest_tensor(x, forest):
    # hot by function name, no loop needed
    out = jnp.sum(forest)
    return jax.device_get(out)  # BAD:R1


def build_tiles_host(forest):
    # not a hot name, not in a loop: fine (one-time layout build)
    return jax.device_get(forest)
