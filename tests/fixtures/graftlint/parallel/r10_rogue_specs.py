"""R10 fixture (ISSUE 10): every way a module can bypass the registry.

With ``parallel/sharding.py`` (the partition-rule registry) in the scanned
set, spec literals, private mesh construction, the bare jax ``shard_map``
import (the seed bug that killed test collection on jax<0.6), and private
axis constants are all findings — the grep acceptance test promoted into
a package-wide semantic rule.
"""
import numpy as np
from jax import shard_map  # BAD:R10 — bypasses the registry's compat shim
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROGUE_AXIS = "rows"  # BAD:R10 — private axis constant, not a registry axis


def private_mesh(devs):
    return Mesh(np.asarray(devs), ("rows",))  # BAD:R10 — use make_mesh()


def local_spec_literal(mesh, arr):
    sharding = NamedSharding(mesh, P("data"))  # BAD:R10 — spec literal
    return sharding


def registry_resolved(mesh, spec):
    # specs resolved through the registry (a variable here) are fine
    return NamedSharding(mesh, spec)
