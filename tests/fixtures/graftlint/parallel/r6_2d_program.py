"""R6 fixture (ISSUE 15): a 2-D program inventing a third axis.

The fused 2-D learner reduces over BOTH registry axes — psum over
``data`` for the histogram partials, all_gather over ``feature`` for the
split decision. With a genuine ``dd x ff`` mesh live, a collective over
any OTHER name is exactly the drift R6 exists to catch: it would trace
fine on a mesh that happened to declare the private axis and fail (or
silently mis-reduce through a rogue Mesh) everywhere else. The registry
(``parallel/sharding.py`` MESH_AXES) stays the one axis universe.
"""
import numpy as np
from jax import lax
from jax.sharding import Mesh

from .sharding import DATA_AXIS, FEATURE_AXIS, MESH_AXES


def make_grid_mesh(devs, dd, ff):
    # the registry-shaped 2-D mesh: both axes named, dd x ff extents —
    # but a private Mesh() next to the registry is its own R10 finding
    # (make_mesh is the one constructor)
    return Mesh(np.asarray(devs).reshape(dd, ff), MESH_AXES)  # BAD:R10


def leaf_hist_2d(local_partial):
    # the 2-D decomposition's two legitimate collectives
    full_cols = lax.psum(local_partial, DATA_AXIS)
    return lax.all_gather(full_cols, FEATURE_AXIS)


def bad_grid_axis(local_partial):
    # a learner psum-ing over an axis the registry does not declare
    # while the 2-D mesh is live
    return lax.psum(local_partial, "grid")  # BAD:R6


def bad_gather_axis(winners):
    return lax.all_gather(winners, "cols")  # BAD:R6
