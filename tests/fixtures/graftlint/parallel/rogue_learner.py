"""R6 fixture: a learner inventing a private mesh axis.

Before the registry, declaring your own ``Mesh`` legitimized any axis name
— exactly how ad-hoc per-learner specs drifted. With the registry in the
scanned set (``parallel/sharding.py`` MESH_AXES), a collective over an axis
the registry does not declare is a finding even though this module's own
``Mesh`` mentions it.
"""
import numpy as np
from jax import lax
from jax.sharding import Mesh

from .sharding import DATA_AXIS


def make_rogue_mesh(devs):
    # a private Mesh next to the registry is its own finding since R10
    return Mesh(np.asarray(devs), ("rows",))    # BAD:R10


def good_registry_axis(local):
    return lax.psum(local, DATA_AXIS)


def bad_private_axis(local):
    return lax.psum(local, "rows")  # BAD:R6


def dynamic_axis_skipped(local, axis):
    return lax.psum(local, axis)
