"""Fixture partition-rule registry (mirrors lambdagap_tpu/parallel/sharding.py):
when a ``parallel/sharding.py`` declaring MESH_AXES is in the scanned set,
R6 checks collectives against THESE axes only."""

DATA_AXIS = "data"
FEATURE_AXIS = "feature"
MESH_AXES = (DATA_AXIS, FEATURE_AXIS)
