"""R1 fixture (ISSUE 15): a D2H sync inside the per-shard ring-fill
loop of the composed stream x 2-D-mesh path.

``_s2_pump`` is the composed mode's window pump: the host builds one
stacked per-block buffer per window and ONE mesh-sharded device_put
lands every data block's slice on its own device. A blocking host sync
inside the per-block fill loop serializes EVERY shard's H2D behind the
device — the overlap dies fleet-wide while training still converges, so
nothing crashes; only the phase breakdown (or this rule) notices.
"""
import jax
import jax.numpy as jnp
import numpy as np


def _s2_pump(blocks, consume):
    for c in range(len(blocks)):
        stacked = []
        for d, block in enumerate(blocks[c]):
            buf = jax.device_put(block)
            # forcing per-block completion defeats the ring
            stacked.append(np.asarray(jax.device_get(buf)))  # BAD:R1
        consume(c, jnp.stack([jnp.asarray(b) for b in stacked]))


def _train_tree_stream2d(state, picks):
    for k in range(len(picks)):
        meta = state["leaf_f"][k]
        host = float(jnp.sum(meta))  # BAD:R1
        if host <= 0.0:
            break
    return state


def build_block_buffers(blocks):
    # clean: host-side gather/memcpy work only — no device sync in the
    # fill path; the mesh-sharded put happens once per window downstream
    return [np.concatenate(b, axis=0) for b in blocks]
