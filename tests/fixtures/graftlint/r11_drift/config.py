"""R11 fixture (ISSUE 10): a miniature Config with one knob of each kind.

``alpha_rate`` is read by the consumer module (clean); ``beta_window`` is
declared but read by nobody (R11a); ``legacy_knob`` is unread too but
listed in COMPAT_ACCEPTED — the declaration file owns its exemption.
"""
from dataclasses import dataclass

COMPAT_ACCEPTED = frozenset({"legacy_knob"})


@dataclass
class Config:
    alpha_rate: float = 0.1
    beta_window: int = 64  # BAD:R11 — declared but never read anywhere
    legacy_knob: int = 0   # accepted-but-inert: exempt via COMPAT_ACCEPTED
    # composition axes read by the r12_combos fixture (this file is the
    # fixture tree's one Config, so axis knobs must be declared here or
    # R11b would flag the R12 fixture's reads as typos)
    linear_tree: bool = False
    use_quantized_grad: bool = False
    data_residency: str = "auto"
    tree_layout: str = "auto"
