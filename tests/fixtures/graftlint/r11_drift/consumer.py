"""R11 fixture (ISSUE 10): the three knob-drift read shapes.

``cfg.alpha_rate`` is a clean declared read. ``cfg.alpha_rte`` is the typo
class — no such field, method, or dynamically assigned attribute, so the
read fails at runtime (R11b). The ``getattr`` fallback default disagreeing
with the declared default (0.5 vs 0.1) is the silent-divergence class
(R11c) — the no-config code path behaves differently from the documented
default. The ``params.get`` with the MATCHING default shows the clean
shape; dynamic attributes assigned onto the config (``cfg.resolved``) are
declarations by assignment, not typos.
"""


def fit(cfg, params):
    lr = cfg.alpha_rate
    bad = cfg.alpha_rte  # BAD:R11 — typo'd knob read
    fallback = getattr(cfg, "alpha_rate", 0.5)  # BAD:R11 — divergent default
    ok = params.get("alpha_rate", 0.1)
    cfg.resolved = True
    return lr, bad, fallback, ok, cfg.resolved
