"""R12 fixture (ISSUE 14): the silent-combo module.

Two demotion shapes the composition-matrix rule must catch:

- a feature-axis knob rewritten inside a branch with NO warning/raise —
  the caller asked for ``tree_layout=sorted`` under quantized gradients
  and silently got the gather layout (the exact shape that hid the
  stream x quantized and linear x quantized degradations before PRs 7/11
  made them loud);
- a demotion warning that names only ONE of the two axes — the reader of
  the log line cannot tell which combination forced the fallback.

The compliant shape at the bottom (warning naming both knobs, then the
write) must scan clean.
"""


def resolve_combo(cfg):
    if cfg.use_quantized_grad and cfg.tree_layout == "sorted":
        cfg.tree_layout = "gather"  # BAD:R12 — silent demotion, no warning
    if cfg.linear_tree and cfg.data_residency == "stream":
        log.warning("linear_tree does not "  # BAD:R12 — one knob named
                    "support streaming input; falling back")
        cfg.data_residency = "hbm"
    return cfg


def resolve_loudly(cfg):
    if cfg.linear_tree and cfg.use_quantized_grad:
        log.warning("use_quantized_grad is not applied with linear_tree; "
                    "training runs in full precision")
        cfg.use_quantized_grad = False
    return cfg
