"""R14 fixture (ISSUE 14): inert suppressions.

The first comment suppresses R1 on a statement where R1 never fires —
dead weight that would silently absorb a FUTURE R1 finding at that site
(the PR-10 frontend ``disable=R5`` class, now a finding). The second is a
live suppression (R1 really fires under it) and must NOT be flagged.
"""
import jax
import jax.numpy as jnp


def helper(n):
    # graftlint: disable=R1 — inert: nothing below syncs  # BAD:R14
    return jnp.zeros(n, dtype=jnp.float32)


def train(xs):
    total = 0.0
    for x in xs:
        # graftlint: disable=R1 — live: this sync is real and justified
        total += float(jax.device_get(x))
    return total
