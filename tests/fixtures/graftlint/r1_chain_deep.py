"""R1 fixture (ISSUE 14): a host sync THREE call-graph hops from the hot
function (train_one_iter -> stage_partition -> _gather_stats -> here).
Per-file linting and one-hop caller resolution both scan this clean; the
transitive effect inference flags it, and the finding's provenance chain
names every frame between the hot root and the sync."""
import jax


def fetch_partition_count(state):
    return int(jax.device_get(state.count))  # BAD:R1 — 3 hops from hot


def deep_and_uncalled(state):
    # same shape, but no hot function reaches it at any depth: clean
    return int(jax.device_get(state.count))
