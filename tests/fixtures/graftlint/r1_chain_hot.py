"""R1 fixture (ISSUE 14): the hot ROOT of a three-hop sync chain.

This file scans clean — the sync lives two modules away
(``r1_chain_deep.py``), reached through ``r1_chain_mid.py``. One-hop
resolution (the ISSUE-10 retarget) never saw past ``stage_partition``;
the transitive effect inference walks the whole chain and the finding in
the deep module names the full provenance path
(``train_one_iter -> stage_partition -> fetch_partition_count``).
"""
from .r1_chain_mid import stage_partition


def train_one_iter(state):
    return stage_partition(state) + 1
