"""R1 fixture (ISSUE 14): the middle frames of the three-hop sync chain.
Nothing here is hot by name or path, and nothing here syncs — this file
scans clean. It only FORWARDS hotness: ``train_one_iter`` (r1_chain_hot)
calls ``stage_partition``, which calls ``_gather_stats``, which calls the
deep helper that syncs (r1_chain_deep)."""
from .r1_chain_deep import fetch_partition_count


def _gather_stats(state):
    return fetch_partition_count(state)


def stage_partition(state):
    return _gather_stats(state)
