"""R1 fixture (ISSUE 10): a host-sync helper in a COLD file.

Nothing here is hot by name or path — per-file linting scans it clean.
But ``r1_hot_caller.py``'s ``train_one_iter`` calls ``fetch_row_count``
directly, so the sync runs once per boosting iteration; the call-graph
retarget flags it here, naming the hot caller.
"""
import jax


def fetch_row_count(state):
    return int(jax.device_get(state.count))  # BAD:R1 — called from a hot fn


def cold_and_uncalled(state):
    # same sync shape, but nothing hot calls this helper: clean
    return int(jax.device_get(state.count))
