"""R1 fixture: host-device syncs inside hot functions are flagged;
identical syncs in cold helpers are not."""
import jax
import jax.numpy as jnp
import numpy as np


def train(xs, dev_val):
    total = 0.0
    for x in xs:
        total += float(jax.device_get(x))  # BAD:R1
    v = dev_val.item()  # BAD:R1
    arr = np.asarray(jnp.sum(dev_val))  # BAD:R1
    f = float(jnp.max(dev_val))  # BAD:R1
    return total, v, arr, f


def get_gradients(scores, label):
    g = scores - label
    jax.device_get(g)  # BAD:R1
    return g


def helper(dev_val):
    # cold function: the same syncs are fine here
    host = float(jax.device_get(dev_val))
    return np.asarray(jnp.sum(dev_val)) + host


def also_fine(rows):
    # float()/np.asarray of host values never flag, even in hot names
    return [float(r) for r in rows]
