"""R1 fixture (ISSUE 10): the hot function whose call makes a cold helper
hot. This file itself has no sync and scans clean — the finding lands in
r1_cold_helper.py, where the sync lives."""
from .r1_cold_helper import fetch_row_count


def train_one_iter(state):
    n = fetch_row_count(state)
    return n + 1
