"""R2 fixture: jit-in-loop and jitted closures over mutable self state."""
import jax


def rebuild_per_step(xs):
    outs = []
    for x in xs:
        f = jax.jit(lambda v: v * 2)  # BAD:R2
        outs.append(f(x))
    return outs


def build_once(xs):
    f = jax.jit(lambda v: v * 2)
    return [f(x) for x in xs]


class Model:
    def __init__(self, scale):
        self.scale = scale
        self.bias = 0.0

    def update(self, b):
        self.bias = b

    def compiled(self):
        def kernel(x):
            return x * self.scale + self.bias
        return jax.jit(kernel)  # BAD:R2

    def compiled_ok(self):
        # immutable self.scale (only assigned in __init__) is fine to close
        # over; mutable state rides as an argument
        def kernel(x, bias):
            return x * self.scale + bias
        return jax.jit(kernel)
