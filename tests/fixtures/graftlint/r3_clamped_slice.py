"""R3 fixture: dynamic slices without a visible bounds invariant."""
import jax.numpy as jnp
from jax import lax


def sliced_unguarded(xs, off, t):
    return lax.dynamic_slice(xs, (off,), (t,))  # BAD:R3


def update_unguarded(xs, vals, off):
    return lax.dynamic_update_slice(xs, vals, (off,))  # BAD:R3


def sliced_assert_guard(xs, off, t):
    assert xs.shape[0] % t == 0
    return lax.dynamic_slice(xs, (off,), (t,))


def sliced_raise_guard(xs, off, t):
    if xs.shape[0] % t != 0:
        raise ValueError("tile must divide the padded length")
    return lax.dynamic_slice(xs, (off,), (t,))


def sliced_clamped_start(xs, off, t):
    return lax.dynamic_slice(
        xs, (jnp.minimum(off, xs.shape[0] - t),), (t,))


def outer_guard_covers_nested(xs, t):
    if xs.shape[0] % t != 0:
        raise ValueError("tile must divide the padded length")

    def body(off):
        return lax.dynamic_slice(xs, (off,), (t,))

    return body
