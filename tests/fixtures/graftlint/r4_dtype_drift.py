"""R4 fixture: array creation without an explicit dtype."""
import jax.numpy as jnp


def bad_creations(n):
    a = jnp.zeros(n)  # BAD:R4
    b = jnp.ones((n, 2))  # BAD:R4
    c = jnp.full((n,), 1e30)  # BAD:R4
    d = jnp.arange(n)  # BAD:R4
    return a, b, c, d


def good_creations(n):
    a = jnp.zeros(n, jnp.float32)
    b = jnp.ones((n, 2), dtype=jnp.float32)
    c = jnp.full((n,), 1e30, jnp.float32)
    d = jnp.arange(n, dtype=jnp.int32)
    e = jnp.zeros_like(a)          # _like inherits: never flagged
    f = jnp.asarray([1.0, 2.0])    # asarray inherits: never flagged
    return a, b, c, d, e, f
