"""R6 fixture: collective axis names vs declared mesh axes."""
import numpy as np
from jax import lax
from jax.sharding import Mesh

DATA_AXIS = "data"


def make_mesh(devs):
    # with the fixture registry in the scanned set, a private Mesh is an
    # R10 finding too (this fixture's subject stays the R6 axis checks)
    return Mesh(np.asarray(devs), (DATA_AXIS,))  # BAD:R10


def good_psum(local):
    return lax.psum(local, DATA_AXIS)


def good_literal(local):
    return lax.all_gather(local, "data", tiled=True)


def bad_psum(local):
    return lax.psum(local, "batch")  # BAD:R6


def bad_axis_index():
    return lax.axis_index("model")  # BAD:R6


def dynamic_axis_skipped(local, axis):
    # unresolvable axis expressions are never guessed at
    return lax.psum(local, axis)
