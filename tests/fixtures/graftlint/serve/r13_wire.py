"""R13 fixture (ISSUE 14): a rogue wire verb on each side of the socket.

A mini frontend module carrying BOTH wire surfaces: ``_op_<verb>``
handlers and a client that sends ops. ``flush`` has a handler no shipped
client can reach; ``drain`` is sent by the client and answers
``unknown op`` at runtime. Both directions are findings — the bijection
is the invariant, not either surface alone.
"""


class _Conn:
    def _op_predict(self, req_id, frame):
        self.send({"id": req_id, "ok": True, "values": []})

    def _op_flush(self, req_id, frame):  # BAD:R13 — no client sends flush
        self.send({"id": req_id, "ok": True})


class MiniClient:
    def predict(self, x):
        return self._send({"op": "predict", "x": x})

    def drain(self):
        return self._call("drain")  # BAD:R13 — no _op_drain handler
