"""R1 fixture (serve path): any sync inside a loop in serve/ is hot."""
import jax


def flush(batch):
    out = []
    for item in batch:
        out.append(jax.device_get(item))  # BAD:R1
    return out


def single(item):
    # not in a loop and not a hot function name: fine
    return jax.device_get(item)
