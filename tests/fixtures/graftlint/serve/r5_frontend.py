"""R5 fixture (ISSUE 9): frontend write-mutex discipline.

A frontend connection serializes frame writes with a mutex; holding it
across a blocking ``sendall`` to a slow client convoys every batcher
reply callback targeting that connection. The real frontend
(serve/frontend.py) accepts exactly this shape on loopback-class sockets
with a written justification — the rule exists so the trade-off stays a
decision, not an accident.
"""
import threading


class BadConn:
    def __init__(self, sock):
        self.sock = sock
        self._tx_lock = threading.Lock()

    def reply(self, payload):
        with self._tx_lock:
            self.sock.sendall(payload)  # BAD:R5
