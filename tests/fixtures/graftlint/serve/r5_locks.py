"""R5 fixture: blocking under a lock; mixed locked/unlocked writes."""
import threading
import time


class Controller:
    def __init__(self):
        self._lock = threading.Lock()
        self.generation = 0
        self.active = None

    def bad_blocking_result(self, fut):
        with self._lock:
            return fut.result()  # BAD:R5

    def bad_sleep_under_lock(self):
        with self._lock:
            time.sleep(0.1)  # BAD:R5

    def flip(self, model):
        with self._lock:
            self.active = model
            self.generation += 1

    def bad_unlocked_write(self, model):
        self.active = model  # BAD:R5

    def ok_lock_free_read(self):
        return self.active

    def ok_blocking_outside(self, fut):
        res = fut.result()
        with self._lock:
            self.active = res
        return res
