"""R5 fixture (ISSUE 9): registry lock discipline.

The hazard the fleet registry must not have: compiling a forest while
holding the registry lock. An XLA forest build takes seconds; every
dispatch-path reader resolving ANY model convoys behind it, so one cold
model freezes the whole fleet's p99. The real registry
(serve/registry.py) builds outside its lock and single-flights concurrent
re-admissions through a per-entry event instead.
"""
import threading


class BadRegistry:
    def __init__(self, build_cache):
        self._build = build_cache
        self._lock = threading.Lock()
        self._entries = {}

    def get(self, name, gbdt):
        with self._lock:
            cache = self._entries.get(name)
            if cache is None:
                cache = self._build(gbdt, 0)  # BAD:R5
                self._entries[name] = cache
            return cache
