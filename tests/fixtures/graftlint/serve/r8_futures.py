"""R8 fixture: future/exception discipline in serve batch runners."""


def swallow_everything(probe):
    try:
        probe()
    except Exception:  # BAD:R8 — swallowed: no log, no counter, no re-raise
        pass


def run_batch_loses_futures(batch, predict):
    """Resolves futures on success, but the except path exits the runner
    without resolving anything: every caller in the batch hangs."""
    try:
        out = predict([r.x for r in batch])
        for r, y in zip(batch, out):
            r.future.set_result(y)
    except RuntimeError as e:  # BAD:R8 — futures never resolved on error
        log_error(e)


def run_batch_resolves_futures(batch, predict):
    """GOOD: the except path fans the error out to every future."""
    try:
        out = predict([r.x for r in batch])
        for r, y in zip(batch, out):
            r.future.set_result(y)
    except RuntimeError as e:
        for r in batch:
            if not r.future.done():
                r.future.set_exception(e)


def run_batch_reraises(batch, predict):
    """GOOD: the except path propagates to a resolving caller."""
    try:
        out = predict([r.x for r in batch])
        for r, y in zip(batch, out):
            r.future.set_result(y)
    except RuntimeError:
        log_error("dispatch failed")
        raise


def log_error(e):
    print("error:", e)
