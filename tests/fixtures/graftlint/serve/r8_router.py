"""R8 fixture (ISSUE 9): router failover future discipline.

The hazard of naive failover: a dispatch function that resolves request
futures, with an except path that drops the dead replica and exits —
every in-flight request of that replica hangs its caller forever. The
real router (serve/router.py) re-enters its replica-pick loop, whose
every exit terminates the future (result, per-request error, or an
explicit no-replica rejection).
"""


def route_all(replicas, requests):
    for req in requests:
        replica = replicas[0]
        try:
            out = replica.run(req.x)
            req.future.set_result(out)
        except ConnectionError:  # BAD:R8
            replicas.pop(0)
