"""R1/R9 fixture pair (ISSUE 13): the autonomics-controller hazard
class. The control loop's actuation — reconnect, respawn, warm/compile —
is long-running by nature; holding ANY dispatch-adjacent lock across it
convoys the request path behind the control plane (the r9_scrape class,
now with the controller's own lock identities: ``self._mu`` defeats
R5's name heuristic, the semantic index resolves it to a real
``threading.Lock``). And the controller lives in serve/, an R1 hot
path: a device sync inside its per-replica loop would charge every
tick with a host-device round trip. The clean shapes at the bottom are
what the real ``serve/autonomics.py`` does: snapshot under the lock,
actuate outside it."""
import threading

import jax.numpy as jnp


class LockedController:
    def __init__(self, replicas):
        self._replicas = replicas
        self._mu = threading.Lock()      # identity-resolved, name-opaque

    def _respawn(self, replica):
        # the blocking respawn wait lives one resolved call away: R5's
        # lexical scan of the caller's with-body never sees it
        return replica.proc_future.result(30.0)

    def revive_all_locked(self):
        out = []
        with self._mu:
            for r in self._replicas:
                out.append(self._respawn(r))  # BAD:R9
        return out

    def probe_locked(self, sock):
        with self._mu:
            sock.sendall(b"probe\n")     # BAD:R9

    def warm_scores(self, batches):
        out = []
        for x in batches:
            out.append(float(jnp.sum(x)))  # BAD:R1
        return out

    # -- the clean shapes (the real controller's discipline) -----------
    def revive_all(self):
        with self._mu:
            replicas = list(self._replicas)
        return [self._respawn(r) for r in replicas]

    def warm_scores_device(self, batches):
        # keep the accumulation on device; one terminal fetch, no loop
        return jnp.stack([jnp.sum(x) for x in batches])
