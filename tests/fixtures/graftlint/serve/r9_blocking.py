"""R9 fixture (ISSUE 10): blocking work under a lock that R5 cannot see.

Two shapes R5's lexical, name-heuristic scope misses:

- the lock attribute is named ``_mu`` — no "lock" substring, so R5's
  ``with <lock>:`` detector never engages; the semantic index knows the
  attribute was initialized to ``threading.Lock()`` and flags the
  ``Event.wait`` held under it;
- the blocking ``sendall`` lives one resolved call away (``publish``
  holds the lock and calls ``self._push``) — invisible to any lexical
  scan of the ``with`` body.
"""
import threading


class Publisher:
    def __init__(self, sock):
        self.sock = sock
        self._mu = threading.Lock()
        self._done = threading.Event()

    def _push(self, payload):
        self.sock.sendall(payload)

    def publish(self, payload):
        with self._mu:
            self._push(payload)  # BAD:R9 — sendall reachable under _mu

    def wait_done(self):
        with self._mu:
            self._done.wait()  # BAD:R9 — Event.wait while holding _mu
