"""R9 fixture (ISSUE 10): one half of a cross-module lock-order cycle.

This module's admission path holds REG_LOCK and flushes stats (which takes
STATS_LOCK in r9_cycle_b); that module's rollup path holds STATS_LOCK and
audits the registry (which takes REG_LOCK here). Two threads entering the
two paths concurrently deadlock — a property NO single-file lint can see:
each file in isolation is a perfectly ordinary lock-then-call shape.
"""
import threading

from .r9_cycle_b import flush_stats

REG_LOCK = threading.Lock()
_MODELS = {}


def admit(name, model):
    with REG_LOCK:
        _MODELS[name] = model
        flush_stats(name)  # BAD:R9 — acquires STATS_LOCK while REG_LOCK held


def audit_registry(names):
    with REG_LOCK:
        return [n for n in names if n in _MODELS]
