"""R9 fixture (ISSUE 10): the other half of the cross-module lock cycle.

``rollup`` holds STATS_LOCK and calls back into r9_cycle_a's
``audit_registry`` (which takes REG_LOCK) — the reverse order of
r9_cycle_a.admit. Each edge of the cycle is flagged in the module that
creates it. (The circular import never executes: graftlint parses, it
does not import.)
"""
import threading

from .r9_cycle_a import audit_registry

STATS_LOCK = threading.Lock()
_COUNTS = {}


def flush_stats(name):
    with STATS_LOCK:
        _COUNTS[name] = _COUNTS.get(name, 0) + 1


def rollup(names):
    with STATS_LOCK:
        live = audit_registry(names)  # BAD:R9 — REG_LOCK under STATS_LOCK
        return {n: _COUNTS.get(n, 0) for n in live}
