"""R9 fixture (ISSUE 14): blocking work TWO resolved calls below a lock.

The ISSUE-10 rule walked exactly ONE call away from the ``with`` block,
so a trivial extract-method refactor (``_encode_and_write`` between the
lock and the ``sendall``) silently un-flagged the hazard. The transitive
effect inference propagates ``blocking`` through any depth, and the
finding's provenance chain names every intermediate frame. The
snapshot-then-write shape at the bottom (blocking call AFTER the lock is
released) must scan clean at every depth.
"""
import threading


class DeepPublisher:
    def __init__(self, sock):
        self.sock = sock
        self._mu = threading.Lock()

    def _write_frame(self, payload):
        self.sock.sendall(payload)

    def _encode_and_write(self, payload):
        return self._write_frame(payload)

    def publish(self, payload):
        with self._mu:
            self._encode_and_write(payload)  # BAD:R9 — sendall 2 calls down

    def publish_outside(self, payload):
        with self._mu:
            frame = payload
        self._encode_and_write(frame)
