"""R9 fixture (ISSUE 10): a CLEAN hierarchical lock order.

The real registry -> stats shape: the registry's admission path holds its
own lock and bumps stats (which takes the stats lock) — a one-directional
edge. No path ever acquires the registry lock while holding the stats
lock, so the acquisition graph is acyclic and the module must scan clean.
The condition-variable wait is the canonical pattern (wait RELEASES the
held lock) and must not flag either.
"""
import threading


class HierStats:
    def __init__(self):
        self.hier_stats_lock = threading.Lock()
        self.admitted = 0

    def bump(self):
        with self.hier_stats_lock:
            self.admitted += 1


class HierRegistry:
    def __init__(self):
        self.hier_reg_lock = threading.Lock()
        self._stats = HierStats()
        self._entries = {}
        self._cv = threading.Condition()

    def admit(self, name, model):
        with self.hier_reg_lock:
            self._entries[name] = model
            self._stats.bump()           # registry -> stats: one direction

    def wait_for(self, name):
        with self._cv:
            while name not in self._entries:
                self._cv.wait()          # releases _cv: the cond pattern
            return self._entries[name]

    def announce(self, name, model):
        with self.hier_reg_lock:
            self._entries[name] = model
        with self._cv:
            self._cv.notify_all()
