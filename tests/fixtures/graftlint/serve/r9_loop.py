"""R9 fixture (ISSUE 20): the promotion-controller hazard class. The
shadow window lives on the other side of an RPC — fetching it is a
blocking ``Future.result`` wait, and it sits one *resolved call* away
from the tick: R5's lexical scan of the with-body sees only an innocent
method call, but the semantic index resolves ``_shadow_metrics`` to the
blocking wait and R9 flags holding the controller lock across it. The
clean shape at the bottom is what the real ``loop/controller.py`` does:
snapshot state under the lock, fetch and decide outside it, write the
transition back."""
import threading


class LockedPromoter:
    def __init__(self, shadow_client):
        self._shadow = shadow_client
        self._mu = threading.Lock()      # identity-resolved, name-opaque
        self._state = "idle"

    def _shadow_metrics(self):
        # the blocking window fetch lives one resolved call away: the
        # shadow replica answers over a socket, seconds away when it is
        # overloaded — and shadow overload must NEVER convoy the tick
        return self._shadow.window_future.result(30.0)

    def tick_locked(self):
        with self._mu:
            window = self._shadow_metrics()  # BAD:R9
            if window["compared"] >= 200:
                self._state = "promoting"
        return self._state

    # -- the clean shape (the real controller's discipline) ------------
    def tick(self):
        with self._mu:
            state = self._state
        if state != "shadowing":
            return state
        window = self._shadow_metrics()  # no lock held: sheds, not convoys
        with self._mu:
            if window["compared"] >= 200:
                self._state = "promoting"
            return self._state
