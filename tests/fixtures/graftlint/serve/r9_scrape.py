"""R9 fixture (ISSUE 12): a blocking fleet scrape under a router-side
lock. The scrape RPC (``Future.result`` on a stats call) lives one
resolved call away (``scrape`` holds ``_lock`` and calls ``_fetch``), so
R5's lexical scan of the ``with`` body never sees it — the semantic
index's call graph does. A scraper that blocks the dispatch lock on a
slow replica's stats RPC convoys EVERY request behind the control plane;
the fix (and the shape the real ``obs/fleet.FleetScraper`` uses) is to
snapshot the replica list under the lock and fetch outside it."""
import threading


class LockedScraper:
    def __init__(self, replicas):
        self._replicas = replicas
        self._lock = threading.Lock()

    def _fetch(self, replica):
        return replica.stats_future.result(2.0)

    def scrape(self):
        out = []
        with self._lock:
            for r in self._replicas:
                out.append(self._fetch(r))  # BAD:R9
        return out

    def scrape_outside(self):
        # the correct shape: the lock guards only the list snapshot
        with self._lock:
            replicas = list(self._replicas)
        return [self._fetch(r) for r in replicas]
