"""Suppression fixture: every hazard here is inline-suppressed."""
import jax
import jax.numpy as jnp


def train(xs):
    total = 0.0
    for x in xs:
        total += float(jax.device_get(x))  # graftlint: disable=R1
    # graftlint: disable=R4 — justification comments may continue over
    # several lines; the suppression covers the next whole statement
    acc = jnp.zeros(
        (8, 8))
    return total, acc
