"""Arrow ingestion (reference: include/LightGBM/arrow.h, the Arrow paths in
src/c_api.cpp, behavioral spec tests/python_package_test/test_arrow.py):
pyarrow Tables construct Datasets and predict; Arrays/ChunkedArrays carry
label/weight/group/init_score; dictionary columns are categorical."""
import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import lambdagap_tpu as lgb
from sklearn.metrics import roc_auc_score


def _chunked_table(X, types=None, n_chunks=3):
    n, d = X.shape
    bounds = np.linspace(0, n, n_chunks + 1).astype(int)
    cols = []
    for j in range(d):
        typ = (types or {}).get(j, pa.float64())
        chunks = [pa.array(X[a:b, j].astype(np.float64), type=typ)
                  for a, b in zip(bounds[:-1], bounds[1:])]
        cols.append(pa.chunked_array(chunks))
    return pa.table(cols, names=[f"f{j}" for j in range(d)])


def test_table_construct_matches_numpy():
    rng = np.random.RandomState(0)
    X = rng.randn(1200, 6)
    X[:, 2] = rng.randint(0, 30, 1200)        # integral column
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    table = _chunked_table(X, types={2: pa.int32()})
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    b_np = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    b_pa = lgb.train(params,
                     lgb.Dataset(table, label=pa.chunked_array([y[:500],
                                                                y[500:]])),
                     num_boost_round=8)
    np.testing.assert_allclose(b_np.predict(X), b_pa.predict(X),
                               rtol=1e-6, atol=1e-8)
    # predict straight from the Table too
    np.testing.assert_allclose(b_pa.predict(table), b_pa.predict(X),
                               rtol=1e-6, atol=1e-8)
    # feature names come from the Table schema
    assert b_pa.feature_name() == [f"f{j}" for j in range(6)]


def test_arrow_weights_and_groups():
    rng = np.random.RandomState(1)
    X = rng.randn(900, 5)
    y = np.clip((X[:, 0] + rng.randn(900) * 0.3) > 0, 0, 4).astype(float)
    w = rng.rand(900) + 0.5
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    b_np = lgb.train(params, lgb.Dataset(X, label=y, weight=w),
                     num_boost_round=5)
    b_pa = lgb.train(params, lgb.Dataset(_chunked_table(X),
                                         label=pa.array(y),
                                         weight=pa.array(w)),
                     num_boost_round=5)
    np.testing.assert_allclose(b_np.predict(X), b_pa.predict(X),
                               rtol=1e-6, atol=1e-8)

    # lambdarank with an arrow group array
    groups = np.full(30, 30, np.int64)
    yr = rng.randint(0, 4, 900).astype(float)
    pr = {"objective": "lambdarank", "num_leaves": 7, "verbose": -1,
          "min_data_in_leaf": 5}
    br_np = lgb.train(pr, lgb.Dataset(X, label=yr, group=groups),
                      num_boost_round=4)
    br_pa = lgb.train(pr, lgb.Dataset(_chunked_table(X), label=pa.array(yr),
                                      group=pa.array(groups)),
                      num_boost_round=4)
    np.testing.assert_allclose(br_np.predict(X), br_pa.predict(X),
                               rtol=1e-6, atol=1e-8)


def test_arrow_init_score_and_nulls():
    rng = np.random.RandomState(2)
    X = rng.randn(800, 4)
    y = (X[:, 0] > 0).astype(float)
    init = rng.randn(800) * 0.1
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    b_np = lgb.train(params, lgb.Dataset(X, label=y, init_score=init),
                     num_boost_round=5)
    b_pa = lgb.train(params, lgb.Dataset(_chunked_table(X), label=pa.array(y),
                                         init_score=pa.array(init)),
                     num_boost_round=5)
    np.testing.assert_allclose(
        b_np.predict(X, raw_score=True), b_pa.predict(X, raw_score=True),
        rtol=1e-6, atol=1e-8)

    # nulls become NaN (missing) — parity with the NaN numpy matrix
    Xn = X.copy()
    Xn[::7, 1] = np.nan
    mask = np.isnan(Xn[:, 1])
    col = pa.array([None if m else float(v)
                    for v, m in zip(Xn[:, 1], mask)], type=pa.float64())
    table = pa.table({"f0": pa.array(Xn[:, 0]), "f1": col,
                      "f2": pa.array(Xn[:, 2]), "f3": pa.array(Xn[:, 3])})
    bn = lgb.train(params, lgb.Dataset(Xn, label=y), num_boost_round=5)
    bp = lgb.train(params, lgb.Dataset(table, label=pa.array(y)),
                   num_boost_round=5)
    np.testing.assert_allclose(bn.predict(Xn), bp.predict(Xn),
                               rtol=1e-6, atol=1e-8)


def test_arrow_dictionary_categorical():
    rng = np.random.RandomState(3)
    n = 1000
    cats = rng.randint(0, 6, n)
    X = np.column_stack([rng.randn(n, 3), cats])
    y = (X[:, 0] + (cats % 3 == 1) * 2.0 + 0.1 * rng.randn(n) > 0.5)
    y = y.astype(float)
    dict_col = pa.DictionaryArray.from_arrays(
        pa.array(cats, type=pa.int32()),
        pa.array([f"c{k}" for k in range(6)]))
    table = pa.table({"a": pa.array(X[:, 0]), "b": pa.array(X[:, 1]),
                      "c": pa.array(X[:, 2]), "cat": dict_col})
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(table, label=pa.array(y)),
                  num_boost_round=10)
    ds = lgb.Dataset(table, label=pa.array(y)).construct()
    assert ds.mappers[3].bin_type == "categorical"
    assert roc_auc_score(y, b.predict(X)) > 0.9
