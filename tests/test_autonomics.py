"""Fleet autonomics (ISSUE 13): revival + probation, placement +
residency routing, delta hot-swap atomicity, the goodput-knee
autoscaler, and — the acceptance criterion — off-by-default behavior:
no knob, no controller, no thread, byte-identical snapshots.

Controller behaviors are driven through the public ``tick()`` with fake
replicas and injected clocks — deterministic, no wall-clock sleeps; the
end-to-end version under real load/SIGKILL lives in
tools/autonomics_gate.py.
"""
import json
import os
import threading

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.guard.degrade import SwapFailed
from lambdagap_tpu.guard.faults import FaultPlan
from lambdagap_tpu.obs.signals import SignalPlane
from lambdagap_tpu.serve import (Autonomics, ForestServer, LocalReplica,
                                 Router, apply_delta, make_delta,
                                 plan_placement)
from lambdagap_tpu.serve.delta import DeltaMismatch, delta_bytes
from lambdagap_tpu.serve.placement import plan_changes


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------
class FakeReplica:
    """A routable replica with scriptable health and counted submits."""

    def __init__(self, name, health="ok"):
        self.name = name
        self._health = health
        self.submits = 0
        self.closed = False

    def submit(self, x, model=None, tenant=None, trace=None):
        from concurrent.futures import Future
        from lambdagap_tpu.guard.degrade import ReplicaUnavailable
        if self._health == "dead":
            raise ReplicaUnavailable(f"{self.name} is dead")
        self.submits += 1
        f = Future()
        f.set_result(("served-by", self.name))
        return f

    def health(self):
        return self._health

    def close(self):
        self.closed = True


def _signals_with_margin(knee_rps, offered_rps):
    """A SignalPlane whose latest tick carries the given knee state."""
    plane = SignalPlane(alpha=1.0)
    plane.knee.knee_rps = knee_rps
    plane.knee.offered_rps = offered_rps
    plane.knee.ticks = 5
    plane.update({"merged": {}, "time_unix": 1.0})
    # update() re-observed 0 rps; force the fields we are scripting
    plane.knee.knee_rps = knee_rps
    plane.knee.offered_rps = offered_rps
    plane._latest["goodput"] = plane.knee.snapshot()
    plane._latest["interval"]["good_fraction"] = 1.0
    return plane


# ---------------------------------------------------------------------------
# off by default (acceptance criterion)
# ---------------------------------------------------------------------------
def test_router_snapshot_byte_identical_without_autonomics():
    """With no controller attached, the router snapshot carries exactly
    the pre-autonomics schema — no probation/placement/autonomics keys
    anywhere."""
    r = Router([FakeReplica("r0"), FakeReplica("r1")])
    snap = r.snapshot()
    assert sorted(snap) == ["failovers", "rejected_no_replica", "replicas"]
    for info in snap["replicas"].values():
        assert sorted(info) == ["dead", "health", "inflight", "routed"]
    # and the snapshot is json-stable (the byte-identity the gate diffs)
    json.dumps(snap, sort_keys=True)


def test_cli_target_off_by_default_no_controller_thread():
    from lambdagap_tpu.cli import _build_serve_target
    from lambdagap_tpu.config import Config
    rng = np.random.RandomState(0)
    X = rng.randn(300, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    cfg = Config.from_params({"verbose": -1})
    assert cfg.serve_autonomics is False          # the default
    before = {t.name for t in threading.enumerate()}
    target = _build_serve_target(cfg, b._booster)
    after = {t.name for t in threading.enumerate()}
    try:
        assert isinstance(target, ForestServer)   # no router wrapping
        assert not any("autonomics" in t for t in after - before)
    finally:
        target.close()


def test_config_knob_validation():
    from lambdagap_tpu.config import Config
    with pytest.raises(Exception):
        Config.from_params({"serve_autonomics_probe_window": 0})
    with pytest.raises(Exception):
        Config.from_params({"serve_autonomics_scale_out_margin": 0.9,
                            "serve_autonomics_scale_in_margin": 0.2})
    cfg = Config.from_params({"serve_autonomics": "true",
                              "serve_autonomics_max_replicas": 4})
    assert cfg.serve_autonomics is True
    assert cfg.serve_autonomics_max_replicas == 4


# ---------------------------------------------------------------------------
# revival + probation
# ---------------------------------------------------------------------------
def test_dead_replica_revived_with_backoff_and_probation():
    t = [0.0]
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = Router([r0, r1])
    revived = FakeReplica("r0")
    attempts = []

    def revive(name, old):
        attempts.append(t[0])
        if len(attempts) < 3:
            raise ConnectionError("still down")
        return revived

    auto = Autonomics(router, revive=revive, revive_backoff_s=1.0,
                      probe_window=2, clock=lambda: t[0])
    auto._backoff_for("r0").jitter = 0.0          # exact schedule
    r0._health = "dead"
    router._mark_dead(r0)

    auto.tick()                                   # attempt 1: fails
    assert attempts == [0.0]
    auto.tick()                                   # backoff: not due yet
    assert attempts == [0.0]
    t[0] = 1.0
    auto.tick()                                   # attempt 2 at +1s: fails
    assert attempts == [0.0, 1.0]
    t[0] = 2.5
    auto.tick()                                   # not due (next at +3.0)
    assert attempts == [0.0, 1.0]
    t[0] = 3.0
    auto.tick()                                   # attempt 3: succeeds
    assert attempts == [0.0, 1.0, 3.0]
    snap = router.snapshot()
    assert snap["replicas"]["r0"]["dead"] is False
    assert snap["replicas"]["r0"]["probation"] is True
    # probation: the revived replica serves only as the DEGRADED tier
    picked = router._pick(set())
    assert picked is r1                           # ok tier wins
    # two healthy ticks clear the probe window
    auto.tick()
    assert router.snapshot()["replicas"]["r0"].get("probation") is True
    auto.tick()
    assert "probation" not in router.snapshot()["replicas"]["r0"]
    assert auto.counters["revivals"] == 1
    assert auto.counters["revival_failures"] == 2
    assert auto.counters["promotions"] == 1


def test_unhealthy_probation_resets_probe_streak():
    t = [0.0]
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = Router([r0, r1])
    auto = Autonomics(router, probe_window=2, clock=lambda: t[0])
    router.set_probation("r0", True)
    r0._health = "degraded"
    auto.tick()                                   # unhealthy: streak 0
    r0._health = "ok"
    auto.tick()                                   # streak 1
    assert "probation" in router.snapshot()["replicas"]["r0"]
    auto.tick()                                   # streak 2: promoted
    assert "probation" not in router.snapshot()["replicas"]["r0"]


def test_injected_revive_fault_counts_as_failure():
    t = [0.0]
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = Router([r0, r1])
    router._mark_dead(r0)
    auto = Autonomics(router, revive=lambda n, o: FakeReplica(n),
                      faults=FaultPlan("revive_fail=1"),
                      clock=lambda: t[0])
    auto.tick()
    assert auto.counters["revival_failures"] == 1
    t[0] = 100.0
    auto.tick()                                   # fault exhausted: revives
    assert auto.counters["revivals"] == 1


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------
def test_plan_placement_bin_packs_under_budget():
    models = {"hot": {"bytes": 60, "traffic": 100},
              "warm": {"bytes": 60, "traffic": 10},
              "cold": {"bytes": 60, "traffic": 1}}
    plan = plan_placement(models, ["r0", "r1"], budget_bytes=120)
    assert sorted(plan) == ["cold", "hot", "warm"]
    assert all(len(v) == 1 for v in plan.values())
    # hot gets first pick; the three models spread 2+1 across budgets
    per_replica = {}
    for m, (r,) in plan.items():
        per_replica.setdefault(r, []).append(m)
    assert all(len(ms) <= 2 for ms in per_replica.values())


def test_plan_placement_over_budget_model_still_placed():
    plan = plan_placement({"huge": {"bytes": 1000, "traffic": 1}},
                          ["r0", "r1"], budget_bytes=10)
    assert plan == {"huge": ["r0"]}


def test_plan_placement_deterministic_and_spread():
    models = {"a": {"bytes": 10, "traffic": 5},
              "b": {"bytes": 10, "traffic": 5}}
    p1 = plan_placement(models, ["r0", "r1", "r2"], budget_bytes=100,
                        spread=2)
    p2 = plan_placement(models, ["r0", "r1", "r2"], budget_bytes=100,
                        spread=2)
    assert p1 == p2
    assert all(len(v) == 2 for v in p1.values())


def test_plan_changes_lists_only_new_assignments():
    old = {"a": ["r0"], "b": ["r1"]}
    new = {"a": ["r0", "r2"], "b": ["r0"], "c": ["r1"]}
    assert plan_changes(old, new) == {"a": ["r2"], "b": ["r0"],
                                      "c": ["r1"]}


def test_router_routes_model_traffic_to_resident_replica():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = Router([r0, r1])
    router.set_placement({"m": ("r1",)})
    for _ in range(6):
        router.submit(np.zeros((1, 2), np.float32), model="m").result(5)
    assert r1.submits == 6 and r0.submits == 0
    # un-placed models still balance by least-inflight
    for _ in range(4):
        router.submit(np.zeros((1, 2), np.float32)).result(5)
    assert r0.submits > 0
    # placement is a preference, not a partition: dead preferred replica
    # fails over to the other
    r1._health = "dead"
    router._mark_dead(r1)
    router.submit(np.zeros((1, 2), np.float32), model="m").result(5)
    assert r0.submits > 4


# ---------------------------------------------------------------------------
# delta hot-swap
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def trained_pair(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("autonomics_models")
    rng = np.random.RandomState(7)
    X = rng.randn(500, 6).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    b_v1 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    p_v1 = os.path.join(str(tmp), "v1.txt")
    b_v1.save_model(p_v1)
    b_v2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3,
                     init_model=p_v1)
    p_v2 = os.path.join(str(tmp), "v2.txt")
    b_v2.save_model(p_v2)
    return X, p_v1, p_v2


def test_delta_roundtrip_and_mismatch(trained_pair):
    _X, p_v1, p_v2 = trained_pair
    t1, t2 = open(p_v1).read(), open(p_v2).read()
    delta = make_delta(t1, t2)
    assert delta is not None and delta["base_trees"] == 5
    assert apply_delta(t1, delta) == t2           # byte-exact reconstruction
    assert len(delta["append"].encode()) < len(t2.encode()) / 2
    assert delta_bytes(delta) < len(t2.encode())
    # shrinking forests are not deltas
    assert make_delta(t2, t1) is None
    # wrong base: refused, never spliced
    with pytest.raises(DeltaMismatch):
        apply_delta(t2, delta)
    with pytest.raises(DeltaMismatch):
        apply_delta(t1, {"format": 99})


def test_registry_swap_delta_end_to_end(trained_pair):
    X, p_v1, p_v2 = trained_pair
    t1, t2 = open(p_v1).read(), open(p_v2).read()
    server = ForestServer(lgb.Booster(model_file=p_v1),
                          max_delay_ms=1.0)
    try:
        gen = server.swap_delta(make_delta(t1, t2))
        assert gen == 1
        expect = lgb.Booster(model_file=p_v2).predict(X[:8])
        got = server.predict(X[:8])
        assert np.array_equal(np.asarray(expect, np.float32).reshape(-1),
                              np.asarray(got).reshape(-1))
        # a stale delta now fails against the NEW resident base and the
        # active generation keeps serving (breaker-fed rollback path)
        with pytest.raises(SwapFailed):
            server.swap_delta(make_delta(t1, t2))
        assert server.generation == 1
    finally:
        server.close()


def test_rollout_delta_atomic_or_rolled_back(trained_pair):
    X, p_v1, p_v2 = trained_pair
    mk = lambda: ForestServer(lgb.Booster(model_file=p_v1),  # noqa: E731
                              max_delay_ms=1.0)
    s0, s1, s2 = mk(), mk(), mk()
    router = Router([LocalReplica("r0", s0), LocalReplica("r1", s1),
                     LocalReplica("r2", s2)], own_replicas=True)
    auto = Autonomics(router)
    try:
        out = auto.rollout_delta(p_v2, base_source=p_v1)
        assert out["mode"] == "delta"
        assert out["delta_bytes"] < out["full_bytes"]
        texts = {s.model_text() for s in (s0, s1, s2)}
        assert len(texts) == 1                    # whole fleet on v2
        assert auto.counters["delta_rollouts"] == 1

        # next rollout: r1 armed to fail -> the fleet must roll back
        b_v3 = lgb.train({"objective": "binary", "num_leaves": 7,
                          "verbose": -1},
                         lgb.Dataset(X, label=(X[:, 0] > 0).astype(
                             np.float32)), num_boost_round=2,
                         init_model=p_v2)
        s1._faults = FaultPlan("delta_swap_fail=1")
        with pytest.raises(SwapFailed):
            auto.rollout_delta(b_v3)
        from lambdagap_tpu.serve.delta import split_model_text
        forests = {tuple(split_model_text(s.model_text())[1])
                   for s in (s0, s1, s2)}
        assert len(forests) == 1                  # no mixed generations
        # and it is the BASE forest (v2's trees), not v3's: the tail
        # (re-serialized parameters) may differ from the file, the
        # forest may not
        assert forests == {tuple(split_model_text(open(p_v2).read())[1])}
        assert auto.counters["delta_rollbacks"] == 1
    finally:
        router.close()


def test_swap_delta_and_prefetch_over_the_wire(trained_pair):
    from lambdagap_tpu.serve import FrontendClient, ServeFrontend
    X, p_v1, p_v2 = trained_pair
    t1, t2 = open(p_v1).read(), open(p_v2).read()
    server = ForestServer(lgb.Booster(model_file=p_v1), max_delay_ms=1.0)
    fe = ServeFrontend(server).start()
    client = FrontendClient("127.0.0.1", fe.port)
    try:
        info = client.prefetch()                  # resident already
        assert info["resident"] is True and not info["readmitted"]
        gen = client.swap_delta(make_delta(t1, t2))
        assert gen == 1
        expect = lgb.Booster(model_file=p_v2).predict(X[:4])
        got = client.predict(X[:4])
        assert np.array_equal(np.asarray(expect, np.float32).reshape(-1),
                              np.asarray(got).reshape(-1))
        # a stale delta answers SwapFailed as the REAL class client-side
        with pytest.raises(SwapFailed):
            client.swap_delta(make_delta(t1, t2))
    finally:
        client.close()
        fe.close()
        server.close()


def test_router_fleet_swap_delta_surface(trained_pair):
    """The ForestServer-compatible fleet surface: a frontend fronting a
    ROUTER serves the same swap_delta/prefetch verbs."""
    _X, p_v1, p_v2 = trained_pair
    t1, t2 = open(p_v1).read(), open(p_v2).read()
    mk = lambda: ForestServer(lgb.Booster(model_file=p_v1),  # noqa: E731
                              max_delay_ms=1.0)
    s0, s1 = mk(), mk()
    router = Router([LocalReplica("r0", s0), LocalReplica("r1", s1)],
                    own_replicas=True)
    try:
        info = router.prefetch()                  # all live replicas
        assert sorted(info) == ["r0", "r1"]
        gen = router.swap_delta(make_delta(t1, t2))
        assert gen == 1
        assert s0.generation == s1.generation == 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------
def test_autoscaler_out_in_with_hysteresis_and_cooldown():
    t = [0.0]
    r0 = FakeReplica("r0")
    router = Router([r0])
    built = []

    def scale(index):
        rep = FakeReplica(f"s{index}")
        built.append(rep.name)
        return rep

    plane = _signals_with_margin(knee_rps=100.0, offered_rps=98.0)
    auto = Autonomics(router, signals=plane, scale=scale,
                      scale_out_margin=0.1, scale_in_margin=0.5,
                      min_replicas=1, max_replicas=2,
                      hysteresis_ticks=2, cooldown_s=10.0,
                      clock=lambda: t[0])
    auto.tick()                                   # streak 1: no action
    assert built == []
    auto.tick()                                   # streak 2: scale OUT
    assert built == ["s0"]
    assert set(router.replica_names()) == {"r0", "s0"}
    auto.tick()                                   # cooldown: no repeat
    auto.tick()
    assert len(built) == 1
    # recover: wide margin -> scale back IN (after cooldown + hysteresis)
    plane2 = _signals_with_margin(knee_rps=100.0, offered_rps=10.0)
    auto.signals = plane2
    t[0] = 11.0
    auto.tick()
    auto.tick()
    assert set(router.replica_names()) == {"r0"}
    assert auto.counters["scale_outs"] == 1
    assert auto.counters["scale_ins"] == 1
    # only controller-added replicas are retired; the floor holds
    auto.tick()
    assert set(router.replica_names()) == {"r0"}


def test_autoscaler_inert_without_knee_evidence():
    t = [0.0]
    router = Router([FakeReplica("r0")])
    plane = _signals_with_margin(knee_rps=0.0, offered_rps=0.0)
    auto = Autonomics(router, signals=plane,
                      scale=lambda i: FakeReplica(f"s{i}"),
                      max_replicas=3, hysteresis_ticks=1,
                      clock=lambda: t[0])
    for _ in range(5):
        auto.tick()
    assert router.replica_names() == ["r0"]       # cold fleet untouched


def test_controller_thread_starts_and_stops():
    router = Router([FakeReplica("r0")])
    auto = Autonomics(router, interval_s=0.05).start()
    assert auto.running
    names = {th.name for th in threading.enumerate()}
    assert "lambdagap-autonomics" in names
    router.attach_autonomics(auto)
    snap = router.snapshot()
    assert "autonomics" in snap and "counters" in snap["autonomics"]
    router.close()                                # closes the controller
    assert not auto.running
