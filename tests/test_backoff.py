"""guard/backoff.py: the one bounded-exponential-backoff policy (ISSUE
13 satellite) — cap, reset-on-success, deterministic jitter under a
seed — plus its three consumers' contracts: the swap breaker's cooldown
is unchanged by the refactor, the fleet scraper backs off after failed
scrapes, and escalating breaker windows work when asked for.
"""
import pytest

from lambdagap_tpu.guard.backoff import Backoff
from lambdagap_tpu.guard.degrade import CircuitBreaker


def test_exponential_growth_and_hard_cap():
    b = Backoff(base_s=1.0, factor=2.0, max_s=5.0, jitter=0.0)
    assert [b.delay_for(k) for k in range(5)] == [1.0, 2.0, 4.0, 5.0, 5.0]


def test_cap_applies_after_jitter():
    b = Backoff(base_s=4.0, factor=2.0, max_s=8.0, jitter=0.5, seed=3)
    # every delay, jittered or not, respects the bound
    assert all(b.delay_for(k) <= 8.0 for k in range(20))


def test_jitter_deterministic_under_seed():
    a = Backoff(base_s=1.0, factor=2.0, max_s=60.0, jitter=0.25, seed=42)
    b = Backoff(base_s=1.0, factor=2.0, max_s=60.0, jitter=0.25, seed=42)
    seq_a = [a.delay_for(k) for k in range(8)]
    assert seq_a == [b.delay_for(k) for k in range(8)]
    # call order/count must not matter: re-query out of order
    assert a.delay_for(3) == seq_a[3]
    # a different seed desynchronizes (the anti-thundering-herd point)
    c = Backoff(base_s=1.0, factor=2.0, max_s=60.0, jitter=0.25, seed=43)
    assert [c.delay_for(k) for k in range(8)] != seq_a
    # jitter stays within the configured fraction
    for k in range(6):
        raw = 1.0 * 2.0 ** k
        assert abs(seq_a[k] - raw) <= 0.25 * raw + 1e-9


def test_schedule_reset_on_success():
    t = [0.0]
    b = Backoff(base_s=1.0, factor=2.0, max_s=8.0, jitter=0.0,
                clock=lambda: t[0])
    assert b.ready()                     # nothing armed yet
    assert b.note_failure() == 1.0
    assert not b.ready()
    t[0] = 0.5
    assert not b.ready()
    t[0] = 1.0
    assert b.ready()                     # delay elapsed
    assert b.note_failure() == 2.0       # second failure: grown
    assert b.attempts == 2
    b.note_success()
    assert b.attempts == 0 and b.ready()
    assert b.note_failure() == 1.0       # back to the base delay


def test_rearm_keeps_current_window():
    t = [0.0]
    b = Backoff(base_s=1.0, factor=2.0, max_s=8.0, jitter=0.0,
                clock=lambda: t[0])
    b.note_failure()
    t[0] = 1.0
    assert b.ready()
    b.rearm()                            # probe consumed: same window
    assert not b.ready() and b.attempts == 1
    t[0] = 2.0
    assert b.ready()


def test_invalid_parameters_rejected():
    with pytest.raises(ValueError):
        Backoff(base_s=-1.0)
    with pytest.raises(ValueError):
        Backoff(factor=0.5)
    with pytest.raises(ValueError):
        Backoff(base_s=2.0, max_s=1.0)
    with pytest.raises(ValueError):
        Backoff(jitter=1.0)


# -- consumers -----------------------------------------------------------
def test_breaker_semantics_unchanged_by_backoff_refactor():
    """The PR 5 breaker contract, post-refactor: threshold opens, fixed
    cooldown half-opens, one probe per window, success closes."""
    t = [0.0]
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=lambda: t[0])
    assert br.state() == "closed" and br.allow()
    br.record_failure()
    assert br.state() == "closed"
    br.record_failure()
    assert br.state() == "open" and not br.allow()
    t[0] = 9.9
    assert br.state() == "open"
    t[0] = 10.0
    assert br.state() == "half_open"
    assert br.allow()                    # the probe
    assert not br.allow()                # only one probe per cooldown
    t[0] = 20.0
    assert br.allow()
    br.record_success()
    assert br.state() == "closed" and br.consecutive_failures == 0


def test_breaker_cooldown_mutable_after_construction():
    # tests/test_guard_serve.py sets breaker.cooldown_s = 0.0 on a live
    # server; the property must keep honoring that idiom
    t = [0.0]
    br = CircuitBreaker(threshold=1, cooldown_s=30.0, clock=lambda: t[0])
    br.record_failure()
    assert br.state() == "open"
    br.cooldown_s = 0.0
    assert br.state() == "half_open"


def test_breaker_escalating_windows_via_custom_backoff():
    t = [0.0]
    br = CircuitBreaker(
        threshold=1, clock=lambda: t[0],
        backoff=Backoff(base_s=1.0, factor=2.0, max_s=8.0, jitter=0.0,
                        clock=lambda: t[0]))
    br.record_failure()                  # opens: window 1s
    t[0] = 1.0
    assert br.state() == "half_open" and br.allow()
    br.record_failure()                  # failed probe: window grows to 2s
    t[0] = 2.0
    assert br.state() == "open"
    t[0] = 3.0
    assert br.state() == "half_open"


def test_fleet_scraper_backs_off_after_scrape_errors():
    from lambdagap_tpu.obs.fleet import FleetScraper

    class Flaky:
        def __init__(self):
            self.calls = 0

        def stats_snapshot(self, reservoirs=False, timeout_s=None):
            self.calls += 1
            raise ConnectionError("replica down")

    target = Flaky()
    sc = FleetScraper(target, interval_s=0.5)
    # drive the loop body by hand (no wall clock): each failed scrape
    # must arm a growing retry window
    with pytest.raises(ConnectionError):
        sc.scrape()
    sc._err_backoff.note_failure()
    assert not sc._err_backoff.ready()
    first = sc._err_backoff.delay_for(0)
    second = sc._err_backoff.delay_for(1)
    assert second == 2 * first
    sc._err_backoff.note_success()
    assert sc._err_backoff.ready()
