"""BinMapper tests (reference analog: bin construction behavior exercised
throughout tests/python_package_test/test_basic.py)."""
import numpy as np
import pytest

from lambdagap_tpu.data.binning import (BIN_CATEGORICAL, MISSING_NAN,
                                        MISSING_NONE, MISSING_ZERO, BinMapper)


def test_simple_numerical_bins():
    vals = np.repeat(np.arange(10, dtype=float), 20)
    m = BinMapper.find_bin(vals, total_sample_cnt=len(vals), max_bin=255,
                           min_data_in_bin=1)
    assert m.missing_type == MISSING_NONE
    assert not m.is_trivial
    bins = m.values_to_bins(np.arange(10, dtype=float))
    # distinct values get distinct bins, order preserving
    assert len(np.unique(bins)) == 10
    assert np.all(np.diff(bins) > 0)


def test_max_bin_respected():
    rng = np.random.RandomState(0)
    vals = rng.randn(10000)
    for max_bin in (15, 63, 255):
        m = BinMapper.find_bin(vals, len(vals), max_bin=max_bin, min_data_in_bin=1)
        assert m.num_bin <= max_bin
        bins = m.values_to_bins(vals)
        assert bins.max() < m.num_bin


def test_equal_count_binning():
    rng = np.random.RandomState(1)
    vals = rng.randn(100000)
    m = BinMapper.find_bin(vals, len(vals), max_bin=16, min_data_in_bin=1)
    bins = m.values_to_bins(vals)
    counts = np.bincount(bins, minlength=m.num_bin)
    # roughly equal-count (within 3x of mean)
    nonzero = counts[counts > 0]
    assert nonzero.min() > len(vals) / 16 / 3


def test_nan_gets_own_bin():
    vals = np.concatenate([np.random.RandomState(2).randn(1000),
                           [np.nan] * 100])
    m = BinMapper.find_bin(vals, len(vals), max_bin=255, min_data_in_bin=1)
    assert m.missing_type == MISSING_NAN
    bins = m.values_to_bins(np.asarray([np.nan, 0.0]))
    assert bins[0] == m.num_bin - 1       # NaN -> last bin
    assert bins[1] != m.num_bin - 1


def test_zero_as_missing():
    vals = np.random.RandomState(3).randn(500)
    m = BinMapper.find_bin(vals, total_sample_cnt=1000,  # 500 implicit zeros
                           max_bin=255, min_data_in_bin=1, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO
    assert m.default_bin == m.values_to_bins(np.zeros(1))[0]


def test_zero_bin_separate():
    # zeros (sparse convention: absent from sample) land in their own bin
    vals = np.asarray([-2.0, -1.0, 1.0, 2.0] * 50)
    m = BinMapper.find_bin(vals, total_sample_cnt=400, max_bin=255,
                           min_data_in_bin=1)
    b = m.values_to_bins(np.asarray([-1.5, 0.0, 1.5]))
    assert len(np.unique(b)) == 3


def test_categorical_bins():
    rng = np.random.RandomState(4)
    cats = rng.choice([1, 2, 3, 7, 9], size=1000,
                      p=[0.5, 0.25, 0.15, 0.07, 0.03]).astype(float)
    m = BinMapper.find_bin(cats, len(cats), max_bin=255, min_data_in_bin=1,
                           bin_type=BIN_CATEGORICAL)
    bins = m.values_to_bins(np.asarray([1.0, 2.0, 3.0, 7.0, 9.0]))
    # most frequent category gets bin 1 (bin 0 is NaN/unseen dummy)
    assert bins[0] == 1
    assert len(np.unique(bins)) == 5
    # unseen category -> dummy bin 0
    assert m.values_to_bins(np.asarray([999.0]))[0] == 0


def test_trivial_feature():
    vals = np.zeros(100)
    m = BinMapper.find_bin(vals[vals != 0], total_sample_cnt=100, max_bin=255,
                           min_data_in_bin=3)
    assert m.is_trivial


def test_bin_to_value_roundtrip():
    rng = np.random.RandomState(5)
    vals = rng.randn(5000)
    m = BinMapper.find_bin(vals, len(vals), max_bin=63, min_data_in_bin=3)
    bins = m.values_to_bins(vals)
    # threshold semantics: v <= upper_bound(bin) for every v in that bin
    for b in np.unique(bins)[:-1]:
        ub = m.bin_to_value(int(b))
        assert np.all(vals[bins == b] <= ub)


def test_native_binner_matches_python():
    """The native single-pass binner (native/binner.cpp) must agree with
    BinMapper.values_to_bins bit-for-bit, including NaN routing, clustered
    values, and categorical columns (left to the python path)."""
    import lambdagap_tpu.native as nat
    from lambdagap_tpu.config import Config
    from lambdagap_tpu.data.dataset import BinnedDataset
    if nat.get_lib() is None:
        import pytest
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(5)
    n = 60_000
    X = np.column_stack([
        rng.randn(n),                          # smooth
        np.round(rng.randn(n) * 2) / 2,        # clustered
        rng.standard_cauchy(n) * 1e4,          # heavy tails
        rng.randint(0, 12, n).astype(float),   # categorical
        np.where(rng.rand(n) < 0.3, np.nan, rng.rand(n)),   # NaN-missing
        np.where(rng.rand(n) < 0.7, 0.0, rng.randn(n)),     # sparse zeros
    ])
    y = rng.rand(n)
    cfg = Config.from_params({"max_bin": 63, "verbose": -1,
                              "categorical_feature": [3]})
    ds_native = BinnedDataset.from_matrix(X, cfg, label=y)
    orig = nat.bin_matrix_native
    nat.bin_matrix_native = lambda *a, **k: False
    try:
        ds_py = BinnedDataset.from_matrix(X, cfg, label=y)
    finally:
        nat.bin_matrix_native = orig
    assert np.array_equal(ds_native.binned, ds_py.binned)
    # f64 input path too
    ds64 = BinnedDataset.from_matrix(X.astype(np.float64), cfg, label=y)
    assert np.array_equal(ds64.binned, ds_py.binned)


def test_sketch_merge_exact_equals_single_stream():
    """ISSUE-8 sharded construction: merging per-shard QuantileSketches
    (psum-style reduction) must equal one sketch over all rows — exactly,
    below the budget — so sharded binning derives the same boundaries as
    single-host binning."""
    from lambdagap_tpu.data.binning import QuantileSketch
    rng = np.random.RandomState(3)
    vals = np.concatenate([rng.randn(4000),
                           np.zeros(500),
                           np.full(100, np.nan),
                           np.round(rng.randn(1000) * 2) / 2])
    rng.shuffle(vals)
    whole = QuantileSketch(budget=4096)
    whole.push(vals)
    parts = [QuantileSketch(budget=4096) for _ in range(4)]
    for i, chunk in enumerate(np.array_split(vals, 4)):
        parts[i].push(chunk)
    merged = parts[0]
    for p in parts[1:]:
        merged.merge(p)
    whole._merge_pending()
    assert merged.total == whole.total
    assert merged.na_cnt == whole.na_cnt
    np.testing.assert_array_equal(merged.distinct, whole.distinct)
    np.testing.assert_array_equal(merged.counts, whole.counts)
    # and the finalized mappers agree bit-for-bit
    ma = merged.to_mapper(max_bin=63, min_data_in_bin=3)
    mb = whole.to_mapper(max_bin=63, min_data_in_bin=3)
    assert ma.bin_upper_bound == mb.bin_upper_bound
    assert ma.missing_type == mb.missing_type
    assert ma.num_bin == mb.num_bin


def test_sketch_state_vector_roundtrip():
    """The fixed-size wire form (the multi-host allgather payload) must
    round-trip losslessly — merge over deserialized states equals merge
    over the live sketches."""
    from lambdagap_tpu.data.binning import QuantileSketch
    rng = np.random.RandomState(4)
    budget = 512
    a, b = QuantileSketch(budget=budget), QuantileSketch(budget=budget)
    a.push(np.where(rng.rand(3000) < 0.2, np.nan, rng.randn(3000)))
    b.push(rng.randn(2000) * 3)
    va, vb = a.state_vector(), b.state_vector()
    assert va.shape == (3 + 2 * budget,) and vb.shape == va.shape
    ra = QuantileSketch.from_state_vector(va, budget)
    rb = QuantileSketch.from_state_vector(vb, budget)
    assert (ra.total, ra.na_cnt) == (a.total, a.na_cnt)
    np.testing.assert_array_equal(ra.distinct, a.distinct)
    np.testing.assert_array_equal(ra.counts, a.counts)
    live = a.merge(b)
    wire = ra.merge(rb)
    np.testing.assert_array_equal(wire.distinct, live.distinct)
    np.testing.assert_array_equal(wire.counts, live.counts)
    assert (wire.total, wire.na_cnt) == (live.total, live.na_cnt)


def test_sharded_construction_matches_single_host_binning():
    """End to end: per-shard sequence construction (sketches merged,
    boundaries broadcast, shards binned locally) produces the identical
    packed matrix as single-reader construction — the 1-device special
    case contract of ISSUE 8's sharded dataset construction."""
    from lambdagap_tpu.data.stream import ShardedBinnedDataset
    rng = np.random.RandomState(5)
    n = 6000
    X = np.column_stack([rng.randn(n),
                         np.where(rng.rand(n) < 0.5, 0.0, rng.randn(n)),
                         rng.randint(0, 7, n).astype(float)])
    y = rng.rand(n)
    from lambdagap_tpu.config import Config as _Config
    cfg = _Config.from_params({"max_bin": 63, "verbose": -1})

    class _View:
        batch_size = 1024

        def __init__(self, lo, hi):
            self.lo, self.hi = lo, hi

        def __len__(self):
            return self.hi - self.lo

        def __getitem__(self, sl):
            return X[self.lo + sl.start:self.lo + sl.stop]

    single = ShardedBinnedDataset.from_sequences(
        [_View(0, n)], cfg, shard_rows=2048, label=y)
    bounds = [0, 1700, 3400, 5100, n]      # 4 uneven shard owners
    sharded = ShardedBinnedDataset.from_sequences(
        [_View(a, b) for a, b in zip(bounds, bounds[1:])], cfg,
        shard_rows=2048, label=y)
    for ma, mb in zip(single.mappers, sharded.mappers):
        assert ma.bin_upper_bound == mb.bin_upper_bound
        assert ma.num_bin == mb.num_bin
    assert np.array_equal(single.binned, sharded.binned)
