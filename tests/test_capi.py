"""Standalone C serving ABI (native/capi.cpp — the reference-c_api-shaped
model-load + predict surface, reference: src/c_api.cpp). A C consumer loads
a saved text model and predicts with no Python/JAX in the loop; here the
ABI is driven through ctypes and checked against Booster.predict."""
import ctypes
import os

import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb
from lambdagap_tpu import native


pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native lib unavailable")


def _capi():
    lib = ctypes.CDLL(native._build_lib())
    lib.LGBM_BoosterCreateFromModelfile.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterLoadModelFromString.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterPredictForMat.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double)]
    lib.LGBM_BoosterPredictForMatSingleRow.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _load(lib, model_str: str):
    h = ctypes.c_void_p()
    it = ctypes.c_int()
    rc = lib.LGBM_BoosterLoadModelFromString(model_str.encode(),
                                             ctypes.byref(it),
                                             ctypes.byref(h))
    assert rc == 0, lib.LGBM_GetLastError()
    return h, int(it.value)


def _predict(lib, h, X, num_class=1, predict_type=0):
    X = np.ascontiguousarray(X, dtype=np.float64)
    out = np.zeros((len(X), num_class), dtype=np.float64)
    rc = lib.LGBM_BoosterPredictForMat(
        h, X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(X), X.shape[1], 1, predict_type,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    return out[:, 0] if num_class == 1 else out


def test_binary_with_categorical_and_missing(tmp_path):
    X, y = make_classification(2500, 8, n_informative=5, random_state=0)
    Xc = np.column_stack([X[:, :7], np.abs(X[:, 7] * 4).astype(int)])
    Xc[::13, 2] = np.nan
    bst = lgb.train({"objective": "binary", "num_leaves": 31, "verbose": -1,
                     "categorical_feature": [7]},
                    lgb.Dataset(Xc, label=y), num_boost_round=12)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    lib = _capi()
    h = ctypes.c_void_p()
    it = ctypes.c_int()
    rc = lib.LGBM_BoosterCreateFromModelfile(path.encode(), ctypes.byref(it),
                                             ctypes.byref(h))
    assert rc == 0, lib.LGBM_GetLastError()
    assert it.value == 12
    got = _predict(lib, h, Xc[:400])
    np.testing.assert_allclose(got, bst.predict(Xc[:400]), rtol=1e-6,
                               atol=1e-9)
    raw = _predict(lib, h, Xc[:400], predict_type=1)
    np.testing.assert_allclose(raw, bst.predict(Xc[:400], raw_score=True),
                               rtol=1e-5, atol=1e-5)
    # single-row entry
    out = np.zeros(1)
    row = np.ascontiguousarray(Xc[5], dtype=np.float64)
    rc = lib.LGBM_BoosterPredictForMatSingleRow(
        h, row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        Xc.shape[1], 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    np.testing.assert_allclose(out[0], got[5], rtol=1e-12)
    lib.LGBM_BoosterFree(h)


def test_multiclass_and_column_major():
    X, y = make_classification(2000, 10, n_informative=6, n_classes=3,
                               random_state=1)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    lib = _capi()
    h, it = _load(lib, bst.model_to_string())
    assert it == 8
    got = _predict(lib, h, X[:300], num_class=3)
    np.testing.assert_allclose(got, bst.predict(X[:300]), rtol=1e-6,
                               atol=1e-9)
    # column-major input
    Xc = np.asfortranarray(X[:300].astype(np.float64))
    out = np.zeros((300, 3))
    rc = lib.LGBM_BoosterPredictForMat(
        h, Xc.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 300,
        X.shape[1], 0, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    np.testing.assert_allclose(out, got, rtol=1e-12)
    lib.LGBM_BoosterFree(h)


def test_linear_tree_model():
    rng = np.random.RandomState(2)
    X = rng.rand(1500, 4) * 4
    y = 2.0 * X[:, 0] - X[:, 1] + 0.05 * rng.randn(1500)
    bst = lgb.train({"objective": "regression", "num_leaves": 6,
                     "linear_tree": True, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    lib = _capi()
    h, _ = _load(lib, bst.model_to_string())
    got = _predict(lib, h, X[:200])
    np.testing.assert_allclose(got, bst.predict(X[:200]), rtol=1e-5,
                               atol=1e-6)
    lib.LGBM_BoosterFree(h)


def test_malformed_model_fails_loudly():
    lib = _capi()
    h = ctypes.c_void_p()
    it = ctypes.c_int()
    rc = lib.LGBM_BoosterLoadModelFromString(
        b"tree\nTree=0\nnum_leaves=5\n", ctypes.byref(it), ctypes.byref(h))
    assert rc != 0
    assert lib.LGBM_GetLastError()
