"""Standalone C serving ABI (native/capi.cpp — the reference-c_api-shaped
model-load + predict surface, reference: src/c_api.cpp). A C consumer loads
a saved text model and predicts with no Python/JAX in the loop; here the
ABI is driven through ctypes with the REFERENCE signatures
(include/LightGBM/c_api.h:1289/:1327 — data_type, start/num_iteration,
parameter, out_len) and checked against Booster.predict."""
import ctypes
import os

import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb
from lambdagap_tpu import native


pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native lib unavailable")

F32, F64 = 0, 1                # C_API_DTYPE_*
NORMAL, RAW, LEAF = 0, 1, 2    # C_API_PREDICT_*


def _capi():
    lib = ctypes.CDLL(native._build_lib())
    lib.LGBM_BoosterCreateFromModelfile.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterLoadModelFromString.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    # reference c_api.h:1289
    lib.LGBM_BoosterPredictForMat.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double)]
    # reference c_api.h:1327
    lib.LGBM_BoosterPredictForMatSingleRow.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double)]
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _load(lib, model_str: str):
    h = ctypes.c_void_p()
    it = ctypes.c_int()
    rc = lib.LGBM_BoosterLoadModelFromString(model_str.encode(),
                                             ctypes.byref(it),
                                             ctypes.byref(h))
    assert rc == 0, lib.LGBM_GetLastError()
    return h, int(it.value)


def _predict(lib, h, X, num_class=1, predict_type=0, dtype=np.float64,
             start_iteration=0, num_iteration=-1, out_cols=None,
             row_major=1):
    X = np.ascontiguousarray(X, dtype=dtype)
    cols = num_class if out_cols is None else out_cols
    out = np.zeros((len(X), cols), dtype=np.float64)
    out_len = ctypes.c_int64()
    rc = lib.LGBM_BoosterPredictForMat(
        h, X.ctypes.data_as(ctypes.c_void_p),
        F32 if dtype == np.float32 else F64,
        len(X), X.shape[1], row_major, predict_type,
        start_iteration, num_iteration, b"",
        ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == out.size
    return out[:, 0] if cols == 1 else out


def test_binary_with_categorical_and_missing(tmp_path):
    X, y = make_classification(2500, 8, n_informative=5, random_state=0)
    Xc = np.column_stack([X[:, :7], np.abs(X[:, 7] * 4).astype(int)])
    Xc[::13, 2] = np.nan
    bst = lgb.train({"objective": "binary", "num_leaves": 31, "verbose": -1,
                     "categorical_feature": [7]},
                    lgb.Dataset(Xc, label=y), num_boost_round=12)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    lib = _capi()
    h = ctypes.c_void_p()
    it = ctypes.c_int()
    rc = lib.LGBM_BoosterCreateFromModelfile(path.encode(), ctypes.byref(it),
                                             ctypes.byref(h))
    assert rc == 0, lib.LGBM_GetLastError()
    assert it.value == 12
    got = _predict(lib, h, Xc[:400])
    np.testing.assert_allclose(got, bst.predict(Xc[:400]), rtol=1e-6,
                               atol=1e-9)
    raw = _predict(lib, h, Xc[:400], predict_type=RAW)
    np.testing.assert_allclose(raw, bst.predict(Xc[:400], raw_score=True),
                               rtol=1e-5, atol=1e-5)
    # float32 input, same rows
    got32 = _predict(lib, h, Xc[:400], dtype=np.float32)
    np.testing.assert_allclose(got32, got, rtol=1e-4, atol=1e-5)
    # single-row entry (reference signature)
    out = np.zeros(1)
    out_len = ctypes.c_int64()
    row = np.ascontiguousarray(Xc[5], dtype=np.float64)
    rc = lib.LGBM_BoosterPredictForMatSingleRow(
        h, row.ctypes.data_as(ctypes.c_void_p), F64,
        Xc.shape[1], 1, NORMAL, 0, -1, b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0
    assert out_len.value == 1
    np.testing.assert_allclose(out[0], got[5], rtol=1e-12)
    lib.LGBM_BoosterFree(h)


def test_multiclass_and_column_major():
    X, y = make_classification(2000, 10, n_informative=6, n_classes=3,
                               random_state=1)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    lib = _capi()
    h, it = _load(lib, bst.model_to_string())
    assert it == 8
    got = _predict(lib, h, X[:300], num_class=3)
    np.testing.assert_allclose(got, bst.predict(X[:300]), rtol=1e-6,
                               atol=1e-9)
    # column-major input: the Fortran-order buffer of X[:300]
    buf = np.ascontiguousarray(X[:300].astype(np.float64).T)
    out = np.zeros((300, 3))
    out_len = ctypes.c_int64()
    rc = lib.LGBM_BoosterPredictForMat(
        h, buf.ctypes.data_as(ctypes.c_void_p), F64, 300, X.shape[1], 0,
        NORMAL, 0, -1, b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, lib.LGBM_GetLastError()
    assert out_len.value == 900
    np.testing.assert_allclose(out, got, rtol=1e-12)
    lib.LGBM_BoosterFree(h)


def test_iteration_range_and_leaf_index():
    X, y = make_regression(1200, 6, noise=0.1, random_state=3)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    lib = _capi()
    h, _ = _load(lib, bst.model_to_string())
    part = _predict(lib, h, X[:100], start_iteration=2, num_iteration=5)
    np.testing.assert_allclose(
        part, bst.predict(X[:100], start_iteration=2, num_iteration=5),
        rtol=1e-6, atol=1e-8)
    leaves = _predict(lib, h, X[:50], predict_type=LEAF, out_cols=10)
    ref_leaves = bst.predict(X[:50], pred_leaf=True)
    np.testing.assert_array_equal(leaves.astype(int), ref_leaves)
    lib.LGBM_BoosterFree(h)


def test_sqrt_and_ova_transforms():
    # reg_sqrt: model text records "regression sqrt"; C predict applies
    # sign(x)*x^2 (reference: RegressionL2loss with sqrt_,
    # src/objective/regression_objective.hpp:149)
    rng = np.random.RandomState(4)
    X = rng.rand(1000, 5)
    y = (3.0 * X[:, 0] + X[:, 1]) ** 2
    bst = lgb.train({"objective": "regression", "reg_sqrt": True,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    assert next(l for l in bst.model_to_string().split("\n")
                if l.startswith("objective=")) == "objective=regression sqrt"
    lib = _capi()
    h, _ = _load(lib, bst.model_to_string())
    got = _predict(lib, h, X[:200])
    np.testing.assert_allclose(got, bst.predict(X[:200]), rtol=1e-5,
                               atol=1e-6)
    lib.LGBM_BoosterFree(h)
    # multiclassova with non-default sigmoid
    Xc, yc = make_classification(1500, 8, n_informative=5, n_classes=3,
                                 random_state=5)
    bst2 = lgb.train({"objective": "multiclassova", "num_class": 3,
                      "sigmoid": 1.7, "verbose": -1},
                     lgb.Dataset(Xc, label=yc), num_boost_round=6)
    assert "sigmoid:1.7" in bst2.model_to_string().split("feature_names")[0]
    h2, _ = _load(lib, bst2.model_to_string())
    got2 = _predict(lib, h2, Xc[:200], num_class=3)
    np.testing.assert_allclose(got2, bst2.predict(Xc[:200]), rtol=1e-5,
                               atol=1e-7)
    lib.LGBM_BoosterFree(h2)


def test_linear_tree_model():
    rng = np.random.RandomState(2)
    X = rng.rand(1500, 4) * 4
    y = 2.0 * X[:, 0] - X[:, 1] + 0.05 * rng.randn(1500)
    bst = lgb.train({"objective": "regression", "num_leaves": 6,
                     "linear_tree": True, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    lib = _capi()
    h, _ = _load(lib, bst.model_to_string())
    got = _predict(lib, h, X[:200])
    np.testing.assert_allclose(got, bst.predict(X[:200]), rtol=1e-5,
                               atol=1e-6)
    lib.LGBM_BoosterFree(h)


def test_malformed_model_fails_loudly():
    lib = _capi()
    h = ctypes.c_void_p()
    it = ctypes.c_int()
    rc = lib.LGBM_BoosterLoadModelFromString(
        b"tree\nTree=0\nnum_leaves=5\n", ctypes.byref(it), ctypes.byref(h))
    assert rc != 0
    assert lib.LGBM_GetLastError()


def test_predict_for_file_and_save_model(tmp_path):
    X, y = make_regression(900, 5, noise=0.1, random_state=6)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=6)
    lib = _capi()
    lib.LGBM_BoosterPredictForFile.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
    lib.LGBM_BoosterSaveModel.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p]
    h, _ = _load(lib, bst.model_to_string())
    # training-file layout: label in column 0 (auto-detected + skipped)
    data = str(tmp_path / "data.tsv")
    np.savetxt(data, np.column_stack([y[:100], X[:100]]), delimiter="\t")
    result = str(tmp_path / "preds.txt")
    rc = lib.LGBM_BoosterPredictForFile(h, data.encode(), 0, NORMAL, 0, -1,
                                        b"", result.encode())
    assert rc == 0, lib.LGBM_GetLastError()
    got = np.loadtxt(result)
    np.testing.assert_allclose(got, bst.predict(X[:100]), rtol=1e-6,
                               atol=1e-8)
    # feature-only layout (no label column)
    data2 = str(tmp_path / "feat.csv")
    np.savetxt(data2, X[:50], delimiter=",")
    rc = lib.LGBM_BoosterPredictForFile(h, data2.encode(), 0, NORMAL, 0, -1,
                                        b"", result.encode())
    assert rc == 0, lib.LGBM_GetLastError()
    np.testing.assert_allclose(np.loadtxt(result), bst.predict(X[:50]),
                               rtol=1e-6, atol=1e-8)
    # explicit has_label=false defeats the label auto-detect heuristic on
    # a feature file that happens to carry one extra (ignored) column
    data3 = str(tmp_path / "feat6.csv")
    np.savetxt(data3, np.column_stack([X[:50], np.zeros(50)]), delimiter=",")
    rc = lib.LGBM_BoosterPredictForFile(h, data3.encode(), 0, NORMAL, 0, -1,
                                        b"has_label=false", result.encode())
    assert rc == 0, lib.LGBM_GetLastError()
    np.testing.assert_allclose(np.loadtxt(result), bst.predict(X[:50]),
                               rtol=1e-6, atol=1e-8)
    # truncated SaveModel must fail loudly, not write a different model
    rc = lib.LGBM_BoosterSaveModel(h, 0, 3, 0,
                                   str(tmp_path / "t.txt").encode())
    assert rc != 0
    # SaveModel round-trips the loaded text
    saved = str(tmp_path / "saved.txt")
    rc = lib.LGBM_BoosterSaveModel(h, 0, -1, 0, saved.encode())
    assert rc == 0
    h2, it2 = _load(lib, open(saved).read())
    assert it2 == 6
    lib.LGBM_BoosterFree(h)
    lib.LGBM_BoosterFree(h2)
