"""CLI, file loading (native parser), binary cache, sklearn wrappers, codegen
(reference analog: tests/c_api_test, test_consistency.py CLI-vs-API checks,
test_sklearn.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb
from lambdagap_tpu.cli import main as cli_main
from lambdagap_tpu.config import Config
from lambdagap_tpu.data.loader import (detect_format, load_binary,
                                       load_data_file, save_binary)


@pytest.fixture
def csv_files(tmp_path, rng):
    X = rng.randn(500, 6)
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    train = tmp_path / "train.csv"
    data = np.column_stack([y, X])
    np.savetxt(train, data, delimiter=",", fmt="%.8g")
    return str(train), X, y


def test_detect_and_load_csv(csv_files):
    path, X, y = csv_files
    assert detect_format(path) == "csv"
    cfg = Config.from_params({"verbose": -1})
    ds = load_data_file(path, cfg)
    assert ds.num_data == 500
    np.testing.assert_allclose(ds.metadata.label, y, rtol=1e-6)


def test_load_libsvm(tmp_path, rng):
    lines = []
    X = np.zeros((200, 5))
    y = rng.randint(0, 2, 200).astype(float)
    for i in range(200):
        feats = sorted(rng.choice(5, 3, replace=False))
        toks = [f"{int(y[i])}"]
        for f in feats:
            v = round(float(rng.randn()), 4)
            X[i, f] = v
            toks.append(f"{f}:{v}")
        lines.append(" ".join(toks))
    path = tmp_path / "train.svm"
    path.write_text("\n".join(lines) + "\n")
    assert detect_format(str(path)) == "libsvm"
    cfg = Config.from_params({"verbose": -1})
    ds = load_data_file(str(path), cfg)
    assert ds.num_data == 200
    np.testing.assert_allclose(ds.metadata.label, y)


def test_load_libsvm_qid(tmp_path, rng):
    """LETOR files carry ``qid:N`` tokens; they must become query boundaries
    (reference: parser.cpp LibSVM + rank examples), not silently parse to an
    all-zero matrix."""
    lines = []
    vals = []
    for i in range(60):
        q = i // 20
        v = round(float(rng.randn()), 4)
        vals.append(v)
        lines.append(f"{i % 3} qid:{q} 0:{v} 2:1.5")
    path = tmp_path / "letor.svm"
    path.write_text("\n".join(lines) + "\n")
    cfg = Config.from_params({"verbose": -1})
    ds = load_data_file(str(path), cfg)
    assert ds.num_data == 60
    assert ds.metadata.num_queries == 3
    np.testing.assert_array_equal(ds.metadata.query_boundaries, [0, 20, 40, 60])


def test_load_libsvm_malformed_fails(tmp_path):
    path = tmp_path / "bad.svm"
    path.write_text("1 0:1.0 junk 2:0.5\n")
    cfg = Config.from_params({"verbose": -1})
    with pytest.raises(Exception):
        load_data_file(str(path), cfg)


def test_query_sidecar(tmp_path, rng):
    X = rng.randn(100, 4)
    y = rng.randint(0, 3, 100).astype(float)
    path = tmp_path / "rank.tsv"
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt="%.6g")
    np.savetxt(str(path) + ".query", np.asarray([25, 25, 50]), fmt="%d")
    cfg = Config.from_params({"verbose": -1})
    ds = load_data_file(str(path), cfg)
    assert ds.metadata.num_queries == 3


def test_binary_cache_roundtrip(tmp_path, csv_files):
    path, X, y = csv_files
    cfg = Config.from_params({"verbose": -1})
    ds = load_data_file(path, cfg)
    bin_path = str(tmp_path / "train.npz")
    save_binary(ds, bin_path)
    ds2 = load_binary(bin_path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_allclose(ds.metadata.label, ds2.metadata.label)
    assert ds.feature_num_bins == ds2.feature_num_bins


def test_cli_train_predict(tmp_path, csv_files):
    path, X, y = csv_files
    model_path = str(tmp_path / "model.txt")
    rc = cli_main([f"task=train", f"data={path}", "objective=binary",
                   "num_iterations=5", "num_leaves=7", "verbose=-1",
                   f"output_model={model_path}"])
    assert rc == 0
    assert os.path.exists(model_path)
    out_path = str(tmp_path / "preds.txt")
    rc = cli_main([f"task=predict", f"data={path}",
                   f"input_model={model_path}", "verbose=-1",
                   f"output_result={out_path}"])
    assert rc == 0
    preds = np.loadtxt(out_path)
    assert preds.shape == (500,)
    assert np.all((preds >= 0) & (preds <= 1))


def test_cli_config_file(tmp_path, csv_files):
    path, X, y = csv_files
    conf = tmp_path / "train.conf"
    model_path = str(tmp_path / "m.txt")
    conf.write_text(f"task = train\ndata = {path}\n"
                    "objective = binary\nnum_iterations = 3\n"
                    f"output_model = {model_path}\nverbose = -1\n")
    rc = cli_main([f"config={conf}"])
    assert rc == 0
    assert os.path.exists(model_path)


def test_convert_model_cpp(tmp_path, csv_files):
    path, X, y = csv_files
    model_path = str(tmp_path / "model.txt")
    cli_main([f"task=train", f"data={path}", "objective=regression",
              "num_iterations=3", "num_leaves=7", "verbose=-1",
              f"output_model={model_path}"])
    cpp_path = str(tmp_path / "model.cpp")
    rc = cli_main([f"task=convert_model", f"input_model={model_path}",
                   f"convert_model={cpp_path}", "verbose=-1"])
    assert rc == 0
    code = open(cpp_path).read()
    assert "PredictTree0" in code and "extern \"C\" void Predict" in code


def test_sklearn_regressor():
    from lambdagap_tpu.sklearn import LGBMRegressor
    X, y = make_regression(600, 8, noise=2.0, random_state=0)
    est = LGBMRegressor(n_estimators=15, num_leaves=15)
    est.fit(X, y)
    pred = est.predict(X)
    assert np.mean((pred - y) ** 2) < 0.3 * np.var(y)
    assert est.feature_importances_.shape == (8,)
    assert est.n_features_ == 8


def test_sklearn_classifier_binary():
    from lambdagap_tpu.sklearn import LGBMClassifier
    X, y = make_classification(800, 10, random_state=1)
    est = LGBMClassifier(n_estimators=20)
    est.fit(X, y)
    proba = est.predict_proba(X)
    assert proba.shape == (800, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
    acc = np.mean(est.predict(X) == y)
    assert acc > 0.9


def test_sklearn_classifier_multiclass():
    from lambdagap_tpu.sklearn import LGBMClassifier
    X, y = make_classification(900, 12, n_classes=3, n_informative=6,
                               random_state=2)
    est = LGBMClassifier(n_estimators=15)
    est.fit(X, y)
    assert est.n_classes_ == 3
    assert est.predict_proba(X).shape == (900, 3)
    assert np.mean(est.predict(X) == y) > 0.7


def test_sklearn_ranker():
    from lambdagap_tpu.sklearn import LGBMRanker
    rng = np.random.RandomState(3)
    X = rng.randn(500, 6)
    y = rng.randint(0, 3, 500).astype(float)
    group = np.full(20, 25)
    est = LGBMRanker(n_estimators=5, min_child_samples=5)
    est.fit(X, y, group=group)
    assert est.predict(X).shape == (500,)
