"""Reference-example consistency suite.

Trains on the reference's shipped example datasets WITH the reference's own
train.conf parameters, asserting metric bars and file-vs-array / CLI-vs-API
agreement (reference model:
tests/python_package_test/test_consistency.py:1-143 + examples/*/train.conf).
These anchor accuracy to real data instead of synthetic draws.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lambdagap_tpu as lgb

EX = "/root/reference/examples"

pytestmark = pytest.mark.skipif(not os.path.isdir(EX),
                                reason="reference examples not present")

# conf keys that are host/runtime concerns, not model parameters
_SKIP_KEYS = {"task", "data", "valid_data", "output_model", "num_machines",
              "local_listen_port", "is_save_binary_file",
              "use_two_round_loading", "is_enable_sparse", "machine_list_file",
              "tree_learner"}


# LAMBDAGAP_CONSISTENCY_FULL=1 runs every example at its conf's full
# num_trees (the reference confs ship 100) with the full-length metric
# bars; the default caps at 50 to keep the quick suite quick. The full
# mode runs in tools/run_full_suite.sh's slow group.
FULL = os.environ.get("LAMBDAGAP_CONSISTENCY_FULL", "0") not in ("0", "")


def _conf(d, name="train.conf", max_trees=50):
    params = {}
    for line in open(os.path.join(EX, d, name)):
        line = line.strip()
        if line and not line.startswith("#") and "=" in line:
            k, v = [t.strip() for t in line.split("=", 1)]
            if "early_stopping" in k or k in _SKIP_KEYS:
                continue
            params[k] = v
    params["verbose"] = -1
    # keep every conf parameter but (outside FULL mode) cap rounds
    if not FULL and max_trees and int(params.get("num_trees", 100)) > max_trees:
        params["num_trees"] = max_trees
    return params


def _load(d, fname):
    mat = np.loadtxt(os.path.join(EX, d, fname))
    return mat[:, 1:], mat[:, 0]


def _ds_from_file(d, fname, params):
    return lgb.Dataset(os.path.join(EX, d, fname), params=params)


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli_env():
    """CLI subprocess env: strip the axon TPU-tunnel shim so the child runs
    the same CPU backend as the in-process API (cross-backend float noise
    flips near-ties)."""
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _cli_train_binary(model_path, num_trees):
    d = os.path.join(EX, "binary_classification")
    return subprocess.run(
        [sys.executable, "-m", "lambdagap_tpu",
         "config=" + os.path.join(d, "train.conf"),
         "data=" + os.path.join(d, "binary.train"),
         "valid_data=" + os.path.join(d, "binary.test"),
         f"num_trees={num_trees}", "output_model=" + model_path,
         "verbose=-1"],
        capture_output=True, text=True, env=_cli_env(), cwd=_REPO_ROOT)


def test_binary_example():
    d = "binary_classification"
    p = _conf(d)
    X, y = _load(d, "binary.train")
    Xt, yt = _load(d, "binary.test")
    w = np.loadtxt(os.path.join(EX, d, "binary.train.weight"))
    res = {}
    bst = lgb.train(p, lgb.Dataset(X, label=y, weight=w),
                    valid_sets=[lgb.Dataset(Xt, label=yt, reference=None,
                                            params=p)],
                    callbacks=[lgb.record_evaluation(res)])
    auc = res["valid_0"]["auc"][-1]
    # the reference's own example reaches ~0.98 train / high-0.7s test AUC
    from sklearn.metrics import roc_auc_score
    test_auc = roc_auc_score(yt, bst.predict(Xt))
    assert test_auc > (0.77 if FULL else 0.75), test_auc
    # file-loaded prediction path agrees with the array path
    pred_arr = bst.predict(Xt)
    pred_file = bst.predict(os.path.join(EX, d, "binary.test"))
    np.testing.assert_allclose(pred_arr, pred_file, rtol=1e-6)


def test_binary_file_dataset_matches_array():
    d = "binary_classification"
    p = _conf(d)
    X, y = _load(d, "binary.train")
    w = np.loadtxt(os.path.join(EX, d, "binary.train.weight"))
    ds_a = lgb.Dataset(X, label=y, weight=w, params=p).construct()
    ds_f = _ds_from_file(d, "binary.train", p).construct()
    assert ds_a.num_data == ds_f.num_data
    assert ds_a.num_features == ds_f.num_features
    np.testing.assert_allclose(ds_a.metadata.label, ds_f.metadata.label)
    np.testing.assert_allclose(ds_a.metadata.weight, ds_f.metadata.weight)
    # identical parsing + sampling -> identical bin mappers and binned rows
    assert np.array_equal(ds_a.binned, ds_f.binned)


def test_binary_cli_matches_api(tmp_path):
    """CLI training with the reference's own train.conf produces the same
    predictions as the API on the same file-loaded dataset."""
    d = os.path.join(EX, "binary_classification")
    model = str(tmp_path / "cli_model.txt")
    pred = str(tmp_path / "cli_pred.txt")
    r = _cli_train_binary(model, 20)
    assert r.returncode == 0, r.stderr[-2000:]
    r = subprocess.run(
        [sys.executable, "-m", "lambdagap_tpu", "task=predict",
         "data=" + os.path.join(d, "binary.test"),
         "input_model=" + model, "output_result=" + pred],
        capture_output=True, text=True, env=_cli_env(), cwd=_REPO_ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    cli_pred = np.loadtxt(pred)

    p = _conf("binary_classification")
    p["num_trees"] = 20
    bst = lgb.train(p, _ds_from_file("binary_classification", "binary.train",
                                     p))
    api_pred = bst.predict(_load("binary_classification", "binary.test")[0])
    np.testing.assert_allclose(cli_pred, api_pred, rtol=1e-5, atol=1e-6)


def test_regression_example():
    d = "regression"
    p = _conf(d)
    X, y = _load(d, "regression.train")
    Xt, yt = _load(d, "regression.test")
    init = np.loadtxt(os.path.join(EX, d, "regression.train.init"))
    res = {}
    ds = lgb.Dataset(X, label=y, init_score=init, params=p)
    bst = lgb.train(p, ds, valid_sets=[lgb.Dataset(X, label=y,
                                                   init_score=init,
                                                   params=p)],
                    valid_names=["training"],
                    callbacks=[lgb.record_evaluation(res)])
    l2 = res["training"]["l2"]
    assert l2[-1] < l2[0] * 0.9
    # the shipped .init scores exercise the init_score path but do not help
    # generalization; the holdout accuracy bar uses a plain model
    plain = lgb.train(p, lgb.Dataset(X, label=y, params=p))
    mse = np.mean((yt - plain.predict(Xt)) ** 2)
    assert mse < 0.8 * np.var(yt), (mse, np.var(yt))


def test_multiclass_example():
    d = "multiclass_classification"
    p = _conf(d)
    X, y = _load(d, "multiclass.train")
    Xt, yt = _load(d, "multiclass.test")
    res = {}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    valid_sets=[lgb.Dataset(Xt, label=yt, params=p)],
                    callbacks=[lgb.record_evaluation(res)])
    ml = res["valid_0"]["multi_logloss"]
    # at the conf's full 100 trees the 7k-row example overfits and the
    # final valid logloss can drift a hair above the start; the curve must
    # still have improved (the conf ships no early stopping)
    assert min(ml) < ml[0] * 0.99, (min(ml), ml[0])
    if not FULL:
        assert ml[-1] < ml[0]
    acc = np.mean(np.argmax(bst.predict(Xt), axis=1) == yt)
    # 5 classes, chance = 0.2; the example reaches ~0.43 at 50 trees and
    # ~0.46 at the conf's full 100
    assert acc > (0.42 if FULL else 0.38), acc


@pytest.mark.parametrize("d,obj", [("lambdarank", "lambdarank"),
                                   ("xendcg", "rank_xendcg")])
def test_rank_examples(d, obj):
    p = _conf(d)
    p["objective"] = obj
    res = {}
    train = _ds_from_file(d, "rank.train", p)
    valid = _ds_from_file(d, "rank.test", p)
    lgb.train(p, train, valid_sets=[train, valid],
              valid_names=["training", "valid"],
              callbacks=[lgb.record_evaluation(res)])
    key = next((k for k in res["valid"] if "ndcg@5" in k),
               next(k for k in res["valid"] if "ndcg" in k))
    # the 3k-row example overfits: training NDCG must climb hard, the
    # holdout bar is what the tiny validation fold supports
    tr_ndcg = res["training"][key]
    assert tr_ndcg[-1] > tr_ndcg[0] + 0.1, (key, tr_ndcg[0], tr_ndcg[-1])
    assert tr_ndcg[-1] > 0.9, tr_ndcg[-1]
    assert res["valid"][key][-1] > 0.45, res["valid"][key][-1]


def test_parallel_learning_example():
    """The reference's 2-machine example, run data-parallel on a 2-device
    mesh: distributed accuracy must match serial on the same data."""
    d = "parallel_learning"
    p = _conf(d)
    p["num_trees"] = 20
    X, y = _load(d, "binary.train")
    Xt, yt = _load(d, "binary.test")
    from sklearn.metrics import roc_auc_score
    serial = lgb.train(p, lgb.Dataset(X, label=y, params=p))
    dist = lgb.train({**p, "tree_learner": "data", "tpu_num_devices": 2},
                     lgb.Dataset(X, label=y, params=p))
    auc_s = roc_auc_score(yt, serial.predict(Xt))
    auc_d = roc_auc_score(yt, dist.predict(Xt))
    assert auc_d > 0.7, auc_d
    assert abs(auc_s - auc_d) < 0.05, (auc_s, auc_d)


def test_binary_linear_example():
    """The reference's shipped linear-tree config (train_linear.conf) on its
    own data (reference model: test_consistency.py test_binary_linear)."""
    d = "binary_classification"
    p = _conf(d, name="train_linear.conf")
    X, y = _load(d, "binary.train")
    Xt, yt = _load(d, "binary.test")
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p))
    from sklearn.metrics import roc_auc_score
    auc = roc_auc_score(yt, bst.predict(Xt))
    assert auc > 0.75, auc
    # the model really carries linear leaves
    assert "is_linear=1" in bst.model_to_string()


def test_regression_forced_bins_example():
    """The reference's shipped forced_bins.json drives bin boundaries on its
    own regression data (reference: examples/regression/forced_bins.json)."""
    d = "regression"
    p = _conf(d)
    p["forcedbins_filename"] = os.path.join(EX, d, "forced_bins.json")
    X, y = _load(d, "regression.train")
    ds = lgb.Dataset(X, label=y, params=p).construct()
    for feat, bounds in ((0, (0.3, 0.35, 0.4)), (1, (-0.1, -0.15, -0.2))):
        ub = ds.mappers[feat].bin_upper_bound
        for b in bounds:
            assert any(abs(x - b) < 1e-9 for x in ub), (feat, b, ub[:10])


def test_predict_conf_cli(tmp_path):
    """The reference's predict.conf flow: train via CLI, then task=predict
    driven by the shipped conf (with path overrides)."""
    d = os.path.join(EX, "binary_classification")
    model = str(tmp_path / "m.txt")
    out = str(tmp_path / "preds.txt")
    r = _cli_train_binary(model, 5)
    assert r.returncode == 0, r.stderr[-1500:]
    r = subprocess.run(
        [sys.executable, "-m", "lambdagap_tpu",
         "config=" + os.path.join(d, "predict.conf"),
         "data=" + os.path.join(d, "binary.test"),
         "input_model=" + model, "output_result=" + out],
        capture_output=True, text=True, env=_cli_env(), cwd=_REPO_ROOT)
    assert r.returncode == 0, r.stderr[-1500:]
    preds = np.loadtxt(out)
    assert preds.shape == (500,)
    assert np.all((preds >= 0) & (preds <= 1))
