"""Continued training (init_model), snapshots, and refit.

Mirrors the reference's continued-training coverage
(reference: tests/python_package_test/test_engine.py:1124+ and the CLI
refit task, src/application/application.cpp:254-290).
"""
import os

import numpy as np
import pytest

import lambdagap_tpu as lgb


def _make_data(n=800, d=10, seed=3, classification=True):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    logits = X @ rng.randn(d) + 0.3 * X[:, 0] * X[:, 1]
    if classification:
        y = (logits > 0).astype(np.float64)
    else:
        y = logits + 0.1 * rng.randn(n)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
          "min_data_in_leaf": 5, "verbose": -1, "deterministic": True}


def test_resume_matches_straight_training():
    X, y = _make_data()
    b20 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=20)

    b10 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10,
                        init_model=b10)
    assert resumed.num_trees() == 20
    p_straight = b20.predict(X, raw_score=True)
    p_resumed = resumed.predict(X, raw_score=True)
    np.testing.assert_allclose(p_resumed, p_straight, rtol=1e-4, atol=1e-5)


def test_resume_from_file(tmp_path):
    X, y = _make_data()
    b10 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "m.txt")
    b10.save_model(path)
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=5,
                        init_model=path)
    assert resumed.num_trees() == 15
    p = resumed.predict(X)
    assert np.isfinite(p).all()


def logloss(y, p):
    p = np.clip(p, 1e-9, 1 - 1e-9)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def test_resume_improves_loss():
    X, y = _make_data()
    b10 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    l10 = logloss(y, b10.predict(X))
    resumed = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=15,
                        init_model=b10)
    l25 = logloss(y, resumed.predict(X))
    assert l25 < l10


def test_resume_multiclass():
    rng = np.random.RandomState(0)
    X = rng.randn(600, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int) + (X[:, 2] > 0.5).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbose": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    r = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5,
                  init_model=b)
    straight = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    np.testing.assert_allclose(r.predict(X, raw_score=True),
                               straight.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-5)


def test_resume_dart():
    # weighted dropout needs tree_weight reconstructed on resume
    X, y = _make_data(n=400)
    params = {**PARAMS, "boosting": "dart", "drop_rate": 0.5,
              "uniform_drop": False}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    r = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5,
                  init_model=b)
    assert r.num_trees() == 10
    assert np.isfinite(r.predict(X)).all()


def test_snapshot_freq(tmp_path):
    X, y = _make_data(n=300)
    out = str(tmp_path / "model.txt")
    lgb.train({**PARAMS, "snapshot_freq": 4, "output_model": out},
              lgb.Dataset(X, label=y), num_boost_round=10)
    assert os.path.exists(out + ".snapshot_iter_4")
    assert os.path.exists(out + ".snapshot_iter_8")
    snap = lgb.Booster(model_file=out + ".snapshot_iter_8")
    assert snap.num_trees() == 8


def test_refit_changes_leaf_values_keeps_structure():
    X, y = _make_data(seed=1)
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=10)
    X2, y2 = _make_data(seed=99)
    refitted = b.refit(X2, y2)
    assert refitted.num_trees() == b.num_trees()
    s_old = b.model_to_string()
    s_new = refitted.model_to_string()
    # same split structure...
    def _field(s, key):
        return [ln for ln in s.splitlines() if ln.startswith(key)]
    assert _field(s_old, "split_feature=") == _field(s_new, "split_feature=")
    assert _field(s_old, "threshold=") == _field(s_new, "threshold=")
    # ...different leaf values
    assert _field(s_old, "leaf_value=") != _field(s_new, "leaf_value=")
    # refitted model is a sane predictor of the new data
    l_refit = logloss(y2, refitted.predict(X2))
    l_old = logloss(y2, b.predict(X2))
    assert l_refit < l_old


def test_cli_refit_and_continued(tmp_path):
    from lambdagap_tpu.cli import main as cli_main
    X, y = _make_data(n=400, d=6)
    data = np.column_stack([y, X])
    train_path = str(tmp_path / "train.csv")
    np.savetxt(train_path, data, delimiter=",", fmt="%.8g")
    model1 = str(tmp_path / "m1.txt")
    cli_main([f"task=train", f"data={train_path}", "objective=binary",
              "num_iterations=5", "num_leaves=7", f"output_model={model1}",
              "verbose=-1"])
    # continued training via input_model
    model2 = str(tmp_path / "m2.txt")
    cli_main([f"task=train", f"data={train_path}", "objective=binary",
              "num_iterations=5", "num_leaves=7", f"input_model={model1}",
              f"output_model={model2}", "verbose=-1"])
    b2 = lgb.Booster(model_file=model2)
    assert b2.num_trees() == 10
    # refit task
    model3 = str(tmp_path / "m3.txt")
    cli_main([f"task=refit", f"data={train_path}", f"input_model={model2}",
              f"output_model={model3}", "objective=binary", "verbose=-1"])
    b3 = lgb.Booster(model_file=model3)
    assert b3.num_trees() == 10
    p = b3.predict(X)
    assert p.shape == (400,) and np.isfinite(p).all()
