"""obs/costplane.py (graftmeter): the analytic FLOP/byte/HBM ledger.

The ISSUE-19 acceptance surface: jit entry points produce ledger entries
with nonzero bytes-accessed and peak-HBM, measured walls join into
per-phase fraction-of-roofline, the disarmed path records nothing, and
the COSTS.json document round-trips with the documented schema.
"""
import json

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.obs import prom
from lambdagap_tpu.obs.costplane import PLANE, CostPlane, SCHEMA_VERSION


def _data(n=500, d=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.randn(n) > 0).astype(np.float32)
    return X, y


def _train(extra=None, n=500, rounds=4):
    X, y = _data(n)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              **(extra or {})}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds)


@pytest.fixture(autouse=True)
def _fresh_plane():
    # PLANE is the process-global singleton: isolate every test
    PLANE.reset()
    yield
    PLANE.reset()
    PLANE.enabled = False
    PLANE.out_path = ""
    PLANE._peaks_override = ""


def _arm(plane=PLANE, **over):
    plane.enabled = True
    for k, v in over.items():
        setattr(plane, k, v)


# -- capture on real programs -------------------------------------------
def test_serial_train_populates_ledger_and_walls():
    b = _train({"cost_plane": True, "telemetry": True})
    programs = {e["program"] for e in PLANE.entries.values()}
    for p in ("train.serial.histogram", "train.serial.split",
              "train.serial.partition"):
        assert p in programs, programs
    for e in PLANE.entries.values():
        assert e["bytes_accessed"] > 0, e
        assert e["peak_hbm_bytes"] > 0, e
        assert e["memory_source"] in ("compiled", "analytic")
    # telemetry close() joined the per-phase walls into the plane
    attr = PLANE.attribution()
    assert any("wall_s" in rec for rec in attr["phases"].values()), attr
    assert b.predict(_data(50)[0]).shape == (50,)


def test_device_predict_captures_engine_and_wall():
    b = _train({"cost_plane": True, "tpu_fast_predict_rows": 0})
    X, _ = _data(1200)
    out = b.predict(X)
    assert out.shape == (1200,)
    predict_entries = [e for e in PLANE.entries.values()
                       if e["program"].startswith("predict.")]
    assert predict_entries, PLANE.entries.keys()
    assert all(e["bytes_accessed"] > 0 and e["peak_hbm_bytes"] > 0
               for e in predict_entries)
    assert PLANE.walls.get("predict", {}).get("seconds", 0.0) > 0


def test_observed_call_counts_and_captures_once():
    import jax
    import jax.numpy as jnp
    _arm()
    fn = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((32, 16), jnp.float32)
    b = jnp.ones((16, 8), jnp.float32)
    for _ in range(3):
        out = PLANE.observed_call("test.matmul", fn, (a, b), bucket=32,
                                  phase="test")
    assert out.shape == (32, 8)
    assert PLANE.calls["test.matmul|32"] == 3
    assert list(PLANE.entries) == ["test.matmul|32"]  # captured once
    e = PLANE.entries["test.matmul|32"]
    assert e["flops"] > 0 and e["bytes_accessed"] > 0
    assert e["peak_hbm_bytes"] >= e["arg_bytes"] + e["out_bytes"]
    assert e["arithmetic_intensity"] > 0
    # a second padding bucket is a distinct executable
    PLANE.observed_call("test.matmul", fn,
                        (jnp.ones((64, 16)), jnp.ones((16, 8))), bucket=64)
    assert "test.matmul|64" in PLANE.entries


def test_capture_failure_is_swallowed_and_not_retried():
    _arm()
    calls = []

    def plain(x):  # not jitted: .trace is missing, capture must fail soft
        calls.append(x)
        return x * 2

    assert PLANE.observed_call("test.plain", plain, (21,)) == 42
    assert PLANE.observed_call("test.plain", plain, (21,)) == 42
    assert calls == [21, 21]                 # dispatch untouched
    assert PLANE.entries == {}
    assert "test.plain|" in PLANE._attempted  # failed capture never retried


# -- disarmed path ------------------------------------------------------
def test_disarmed_plane_records_nothing():
    assert not PLANE.enabled
    assert PLANE.observed_call("x", lambda: 7, ()) == 7
    PLANE.record_host("x", flops=1, bytes_accessed=1, peak_hbm_bytes=1)
    PLANE.note_wall("x", 1.0)
    with PLANE.wall("x"):
        pass
    assert PLANE.entries == {} and PLANE.calls == {} and PLANE.walls == {}
    b = _train()                             # cost_plane defaults off
    assert PLANE.entries == {} and not PLANE.enabled
    assert b.predict(_data(50)[0]).shape == (50,)


# -- peaks --------------------------------------------------------------
def test_peaks_override_and_fallback():
    _arm(_peaks_override="197e12:819e9:17e9")
    p = PLANE.peaks()
    assert (p["name"], p["flops"], p["bandwidth"], p["hbm"]) == \
        ("override", 197e12, 819e9, 17e9)
    _arm(_peaks_override="not:numbers:here")
    p = PLANE.peaks()                        # bad spec falls back to table
    assert p["name"] != "override" and p["flops"] > 0
    _arm(_peaks_override="")
    p = PLANE.peaks()                        # CPU container row, unmeasured
    assert p["name"] == "cpu-container" and p["measured"] is False


# -- attribution math ---------------------------------------------------
def test_attribution_roofline_join():
    _arm(_peaks_override="1e9:1e9:1e9")
    PLANE.entries["p|1"] = {"program": "p", "bucket": "1", "phase": "ph",
                            "flops": 2e9, "bytes_accessed": 1e9,
                            "peak_hbm_bytes": 10}
    PLANE.calls["p|1"] = 2
    PLANE.note_wall("ph", 8.0)
    rec = PLANE.attribution()["phases"]["ph"]
    # 2 calls x 2e9 flops / 1e9 flop/s = 4s; 2 x 1e9 B / 1e9 B/s = 2s
    assert rec["bound"] == "flop"
    assert rec["roofline_s"] == pytest.approx(4.0)
    assert rec["wall_s"] == pytest.approx(8.0)
    assert rec["fraction_of_roofline"] == pytest.approx(0.5)
    assert rec["calls"] == 2


def test_wall_span_bracket():
    _arm()
    with PLANE.wall("w"):
        pass
    assert PLANE.walls["w"]["calls"] == 1
    assert PLANE.walls["w"]["seconds"] >= 0
    with pytest.raises(RuntimeError):
        with PLANE.wall("err"):
            raise RuntimeError("boom")
    assert "err" not in PLANE.walls          # failed bracket not noted


# -- host entries / export ----------------------------------------------
def test_record_host_entry():
    _arm()
    PLANE.record_host("predict.shap", flops=1e6, bytes_accessed=2e6,
                      peak_hbm_bytes=3_000_000, phase="predict_shap",
                      bucket=100)
    PLANE.record_host("predict.shap", flops=9e9, bytes_accessed=9e9,
                      peak_hbm_bytes=9, bucket=100)  # first write wins
    e = PLANE.entries["predict.shap|100"]
    assert e["memory_source"] == "host_analytic"
    assert e["flops"] == 1e6 and e["peak_hbm_bytes"] == 3_000_000
    assert PLANE.calls["predict.shap|100"] == 2


def test_to_json_schema_and_write(tmp_path):
    _arm(out_path=str(tmp_path / "COSTS.json"))
    PLANE.record_host("p", flops=1.0, bytes_accessed=2.0, peak_hbm_bytes=3,
                      phase="ph", bucket=4)
    PLANE.note_wall("ph", 0.5, calls=2)
    doc = json.loads((tmp_path / "COSTS.json").read_text()) \
        if PLANE.write() else None
    assert doc is not None
    assert doc["schema_version"] == SCHEMA_VERSION
    for k in ("backend", "device_kind", "num_devices", "peaks", "entries",
              "walls", "attribution"):
        assert k in doc, k
    assert doc["entries"]["p|4"]["calls"] == 1
    assert doc["walls"]["ph"] == {"seconds": 0.5, "calls": 2}
    assert "ph" in doc["attribution"]["phases"]


def test_by_program_maxima_over_buckets():
    _arm()
    PLANE.record_host("p", flops=5.0, bytes_accessed=100.0,
                      peak_hbm_bytes=10, bucket=1)
    PLANE.record_host("p", flops=1.0, bytes_accessed=300.0,
                      peak_hbm_bytes=7, bucket=2)
    PLANE.record_host("q", flops=2.0, bytes_accessed=50.0,
                      peak_hbm_bytes=99, bucket=1)
    byp = PLANE.by_program()
    assert byp["p"] == {"bytes_accessed": 300.0, "peak_hbm_bytes": 10.0,
                        "flops": 5.0, "calls": 2}
    assert byp["q"]["peak_hbm_bytes"] == 99.0


def test_train_traffic_per_iteration():
    _arm()
    assert PLANE.train_traffic(10) is None   # empty ledger
    PLANE.record_host("t", flops=40.0, bytes_accessed=80.0,
                      peak_hbm_bytes=1, phase="histogram", bucket=1)
    PLANE.record_host("u", flops=10.0, bytes_accessed=20.0,
                      peak_hbm_bytes=1, phase="predict", bucket=1)  # not train
    t = PLANE.train_traffic(4)
    assert t == {"programs": 1, "bytes_per_iter": 20.0,
                 "flops_per_iter": 10.0}
    assert PLANE.train_traffic(0) is None


# -- prom exposition ----------------------------------------------------
def test_prom_render_costplane():
    assert prom.render_costplane() == ""     # disarmed -> empty
    _arm()
    PLANE.record_host("p.x", flops=1e6, bytes_accessed=2e6,
                      peak_hbm_bytes=3_000_000, phase="ph", bucket=128)
    PLANE.note_wall("ph", 0.25)
    text = prom.render_costplane()
    for metric in ("lambdagap_cost_program_flops",
                   "lambdagap_cost_program_bytes_accessed",
                   "lambdagap_cost_program_peak_hbm_bytes",
                   "lambdagap_cost_program_calls_total",
                   "lambdagap_cost_phase_roofline_seconds",
                   "lambdagap_cost_phase_wall_seconds"):
        assert metric in text, metric
    assert 'program="p.x"' in text and 'bucket="128"' in text


def test_configure_arms_without_clearing():
    cfg_on = type("C", (), {"cost_plane": True, "cost_plane_out": "",
                            "cost_plane_memory": "analytic",
                            "cost_plane_peaks": ""})()
    plane = CostPlane()
    plane.configure(cfg_on)
    assert plane.enabled and plane.memory_mode == "analytic"
    plane.record_host("p", flops=1, bytes_accessed=1, peak_hbm_bytes=1)
    plane.configure(cfg_on)                  # reconfigure keeps the ledger
    assert "p|" in plane.entries
    cfg_off = type("C", (), {"cost_plane": False, "cost_plane_out": "",
                             "cost_plane_memory": "compiled",
                             "cost_plane_peaks": ""})()
    plane.configure(cfg_off)
    assert not plane.enabled
