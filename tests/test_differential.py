"""Seeded differential sweep: the host-driven serial learner and the fused
whole-tree program must agree across random config combinations (the
cross-backend analog of the reference's CPU-vs-GPU test_dual.py, run here
as host-loop vs fused on one backend so float noise stays bounded), and the
fused data-parallel program must agree with itself across mesh sizes
(1 device vs 8) — the sweep that catches a fused-path regression in any
major feature (bagging, GOSS, DART, EFB, monotone, forced splits,
linear trees, quantized gradients)."""
import json
import os

import numpy as np
import pytest

from conftest import skip_unless_multiprocess

import lambdagap_tpu as lgb


def _random_case(rng, tmp_path=None, for_dp=False):
    n = int(rng.randint(600, 1500))
    d = int(rng.randint(4, 10))
    X = rng.randn(n, d)
    cat_col = None
    if rng.rand() < 0.4:                       # a categorical column
        cat_col = int(rng.randint(d))
        X[:, cat_col] = rng.randint(0, int(rng.randint(3, 20)), n)
    # labels derive from the PRE-corruption features (NaN labels are
    # invalid input, not a differential case)
    obj = rng.choice(["binary", "regression"])
    if obj == "binary":
        y = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(float)
    else:
        y = X[:, 0] * 2 + rng.randn(n)
    if rng.rand() < 0.5:                       # missing values
        X[rng.rand(n) < 0.1, int(rng.randint(d))] = np.nan
    if rng.rand() < 0.3:                       # exact zeros (Zero missing)
        X[rng.rand(n) < 0.3, int(rng.randint(d))] = 0.0
    if rng.rand() < 0.3:                       # near-exclusive one-hot block
        k = min(d - 1, 3)
        hot = rng.randint(0, k, n)
        for j in range(k):
            X[:, d - 1 - j] = (hot == j) * np.abs(rng.randn(n))
    w = np.abs(rng.randn(n)) + 0.1 if rng.rand() < 0.4 else None
    params = {
        "objective": obj,
        "num_leaves": int(rng.choice([4, 15, 31])),
        # 1-row leaves make f32 gain ties ubiquitous and flip near-tie
        # splits between any two summation orders; 3 is still adversarial
        "min_data_in_leaf": int(rng.choice([3, 5, 20])),
        "max_bin": int(rng.choice([15, 63, 255])),
        "learning_rate": float(rng.choice([0.05, 0.1, 0.3])),
        "lambda_l1": float(rng.choice([0.0, 0.0, 1.0])),
        "lambda_l2": float(rng.choice([0.0, 1.0])),
        "min_gain_to_split": float(rng.choice([0.0, 0.0, 0.1])),
        "enable_bundle": bool(rng.rand() < 0.7),
        "verbose": -1,
    }
    # feature-level draws ------------------------------------------------
    r = rng.rand()
    if r < 0.25:
        params.update(bagging_fraction=float(rng.choice([0.5, 0.8])),
                      bagging_freq=1)
    elif r < 0.45:
        params.update(data_sample_strategy="goss",
                      top_rate=0.3, other_rate=0.2)
    if rng.rand() < 0.2:
        params.update(boosting="dart", drop_rate=0.3)
    if cat_col is None and rng.rand() < 0.3:
        mono = [0] * d
        mono[0] = 1
        params.update(monotone_constraints=mono,
                      monotone_constraints_method=str(
                          rng.choice(["basic", "intermediate", "advanced"])))
    if cat_col is None and not for_dp and rng.rand() < 0.15 \
            and params.get("boosting") != "dart":
        # linear trees now train on the fused learner too; the combo with
        # dart is a config-validation error (ISSUE 11 satellite), so the
        # draw skips it
        params.update(linear_tree=True)
    if tmp_path is not None and rng.rand() < 0.2 and cat_col != 0:
        forced = {"feature": 0, "threshold": float(np.nanmedian(X[:, 0]))}
        fp = os.path.join(str(tmp_path), "forced.json")
        with open(fp, "w") as f:
            json.dump(forced, f)
        params["forcedsplits_filename"] = fp
    if for_dp and rng.rand() < 0.25:
        params.update(use_quantized_grad=True, stochastic_rounding=False)
    if cat_col is not None:
        params["categorical_feature"] = [cat_col]
    return X, y, w, params


# tier-1 hygiene (the 870s window, ROADMAP caveat): the differential fuzz
# sweeps dominate the alphabetical window — keep a fast slice of each
# sweep in tier-1 and push the long tail behind -m slow (the full sweeps
# still run wherever slow marks do; seeds are stable so the split is too)
@pytest.mark.parametrize(
    "seed", list(range(8)) + [pytest.param(s, marks=pytest.mark.slow)
                              for s in range(8, 20)])
def test_host_vs_fused_random_config(seed, tmp_path):
    rng = np.random.RandomState(1000 + seed)
    X, y, w, params = _random_case(rng, tmp_path)
    rounds = 5
    b_host = lgb.train({**params, "tpu_fused_learner": "0"},
                       lgb.Dataset(X, label=y, weight=w),
                       num_boost_round=rounds)
    b_fused = lgb.train({**params, "tpu_fused_learner": "1"},
                        lgb.Dataset(X, label=y, weight=w),
                        num_boost_round=rounds)
    p_host = b_host.predict(X)
    p_fused = b_fused.predict(X)
    # identical algorithms; differences are float reduction order only.
    # near-tie splits can diverge structurally, so compare predictions,
    # not model text, at a tolerance covering one flipped minor split
    close = np.isclose(p_host, p_fused, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))
    np.testing.assert_allclose(np.mean(p_host), np.mean(p_fused),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize(
    "seed", list(range(4)) + [pytest.param(s, marks=pytest.mark.slow)
                              for s in range(4, 10)])
def test_dp_1dev_vs_8dev_random_config(seed, tmp_path):
    """The fused data-parallel shard_map program must produce the same
    model on a 1-device and an 8-device mesh (per-split psum + replicated
    argmax — any missing collective shows up as divergence here)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.RandomState(7000 + seed)
    X, y, w, params = _random_case(rng, tmp_path, for_dp=True)
    params.update(tree_learner="data", tpu_fused_learner="1")
    rounds = 4
    b1 = lgb.train({**params, "tpu_num_devices": 1},
                   lgb.Dataset(X, label=y, weight=w), num_boost_round=rounds)
    b8 = lgb.train({**params, "tpu_num_devices": 8},
                   lgb.Dataset(X, label=y, weight=w), num_boost_round=rounds)
    p1 = b1.predict(X)
    p8 = b8.predict(X)
    close = np.isclose(p1, p8, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))
    np.testing.assert_allclose(np.mean(p1), np.mean(p8),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize(
    "seed", list(range(3)) + [pytest.param(s, marks=pytest.mark.slow)
                              for s in range(3, 6)])
def test_feature_parallel_vs_serial_random_config(seed):
    """Random-config differential for the fused FEATURE-parallel program:
    rows are replicated so the column-sharded scan must reproduce the
    fused serial learner exactly (same arithmetic, same global-feature
    tie-break) across quantized/monotone/bagging/GOSS/EFB draws."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.RandomState(3000 + seed)
    X, y, w, params = _random_case(rng, None, for_dp=True)
    if params.get("monotone_constraints_method") == "advanced":
        # advanced demotes to intermediate on distributed learners; pin
        # both sides to the same method
        params["monotone_constraints_method"] = "intermediate"
    rounds = 4
    b_s = lgb.train({**params, "tpu_fused_learner": "1"},
                    lgb.Dataset(X, label=y, weight=w),
                    num_boost_round=rounds)
    b_f = lgb.train({**params, "tree_learner": "feature",
                     "tpu_num_devices": 8},
                    lgb.Dataset(X, label=y, weight=w),
                    num_boost_round=rounds)
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedFeatureParallelTreeLearner
    assert isinstance(b_f._booster.learner, FusedFeatureParallelTreeLearner)
    close = np.isclose(b_s.predict(X), b_f.predict(X), rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))


@pytest.mark.parametrize(
    "seed", list(range(3)) + [pytest.param(s, marks=pytest.mark.slow)
                              for s in range(3, 6)])
def test_voting_fused_vs_host_loop_random_config(seed):
    """Random-config differential for the fused VOTING program against the
    host-loop voting learner — same algorithm (local top-k vote, voted
    column psum), fused vs per-split-host-sync execution."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.RandomState(4000 + seed)
    X, y, w, params = _random_case(rng, None)
    # host-loop voting applies monotone per-split only, no quantized path,
    # and linear trees route to host on both sides — keep the comparison
    # on the shared algorithm space
    for k in ("monotone_constraints", "monotone_constraints_method",
              "linear_tree", "use_quantized_grad"):
        params.pop(k, None)
    params.update(tree_learner="voting", tpu_num_devices=8,
                  top_k=int(rng.choice([3, 8])))
    rounds = 4
    b_f = lgb.train({**params, "tpu_fused_learner": "1"},
                    lgb.Dataset(X, label=y, weight=w),
                    num_boost_round=rounds)
    b_h = lgb.train({**params, "tpu_fused_learner": "0"},
                    lgb.Dataset(X, label=y, weight=w),
                    num_boost_round=rounds)
    close = np.isclose(b_f.predict(X), b_h.predict(X), rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))


_CHILD_FUZZ = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, os.getcwd())
import jax

rank = int(sys.argv[1]); port = sys.argv[2]; workdir = sys.argv[3]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
from lambdagap_tpu.config import Config
from lambdagap_tpu.parallel.multiprocess import load_pre_partitioned
from lambdagap_tpu.models.dart import create_boosting

params = json.load(open(os.path.join(workdir, "params.json")))
cfg = Config.from_params({**params, "pre_partition": True,
                          "num_machines": 2,
                          "bin_construct_sample_cnt": 4000})
ds = load_pre_partitioned(os.path.join(workdir, f"part{rank}.tsv"), cfg)
g = create_boosting(cfg, ds)
for _ in range(4):
    g.train_one_iter()
with open(os.path.join(workdir, f"model{rank}.txt"), "w") as f:
    f.write(g.save_model_to_string())
print(f"RANK{rank}_OK")
"""


@pytest.mark.parametrize("seed", range(3))
def test_pre_partitioned_random_config(seed, tmp_path):
    """Random-config differential for the 2-process pre-partitioned path:
    both ranks must build byte-identical models under random bagging/GOSS/
    quantized/num_leaves draws (any rank-divergent reduction shows up as a
    model mismatch)."""
    skip_unless_multiprocess()
    import socket
    import subprocess
    import sys as _sys
    rng = np.random.RandomState(5000 + seed)
    n = 1600
    X = rng.randn(n, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "tree_learner": "data",
              "num_leaves": int(rng.choice([7, 15, 31])),
              "min_data_in_leaf": int(rng.choice([3, 20])),
              "verbose": -1}
    r = rng.rand()
    if r < 0.33:
        params.update(bagging_fraction=0.7, bagging_freq=1)
    elif r < 0.66:
        params.update(data_sample_strategy="goss", top_rate=0.3,
                      other_rate=0.2)
    if rng.rand() < 0.5:
        params.update(use_quantized_grad=True, stochastic_rounding=False)
    full = np.column_stack([y, X])
    np.savetxt(tmp_path / "part0.tsv", full[:800], delimiter="\t")
    np.savetxt(tmp_path / "part1.tsv", full[800:], delimiter="\t")
    with open(tmp_path / "params.json", "w") as f:
        json.dump(params, f)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "child_fuzz.py"
    script.write_text(_CHILD_FUZZ)
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen(
        [_sys.executable, str(script), str(r2), port, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.getcwd(), env=env) for r2 in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("pre-partitioned fuzz timed out")
        outs.append(out)
    for r2, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (params, f"rank {r2}:\n{out[-3000:]}")
        assert f"RANK{r2}_OK" in out
    m0 = (tmp_path / "model0.txt").read_text()
    m1 = (tmp_path / "model1.txt").read_text()
    assert m0 == m1, params
