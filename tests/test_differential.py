"""Seeded differential sweep: the host-driven serial learner and the fused
whole-tree program must agree across random config combinations (the
cross-backend analog of the reference's CPU-vs-GPU test_dual.py, run here
as host-loop vs fused on one backend so float noise stays bounded), and the
fused data-parallel program must agree with itself across mesh sizes
(1 device vs 8) — the sweep that catches a fused-path regression in any
major feature (bagging, GOSS, DART, EFB, monotone, forced splits,
linear trees, quantized gradients)."""
import json
import os

import numpy as np
import pytest

import lambdagap_tpu as lgb


def _random_case(rng, tmp_path=None, for_dp=False):
    n = int(rng.randint(600, 1500))
    d = int(rng.randint(4, 10))
    X = rng.randn(n, d)
    cat_col = None
    if rng.rand() < 0.4:                       # a categorical column
        cat_col = int(rng.randint(d))
        X[:, cat_col] = rng.randint(0, int(rng.randint(3, 20)), n)
    # labels derive from the PRE-corruption features (NaN labels are
    # invalid input, not a differential case)
    obj = rng.choice(["binary", "regression"])
    if obj == "binary":
        y = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(float)
    else:
        y = X[:, 0] * 2 + rng.randn(n)
    if rng.rand() < 0.5:                       # missing values
        X[rng.rand(n) < 0.1, int(rng.randint(d))] = np.nan
    if rng.rand() < 0.3:                       # exact zeros (Zero missing)
        X[rng.rand(n) < 0.3, int(rng.randint(d))] = 0.0
    if rng.rand() < 0.3:                       # near-exclusive one-hot block
        k = min(d - 1, 3)
        hot = rng.randint(0, k, n)
        for j in range(k):
            X[:, d - 1 - j] = (hot == j) * np.abs(rng.randn(n))
    w = np.abs(rng.randn(n)) + 0.1 if rng.rand() < 0.4 else None
    params = {
        "objective": obj,
        "num_leaves": int(rng.choice([4, 15, 31])),
        # 1-row leaves make f32 gain ties ubiquitous and flip near-tie
        # splits between any two summation orders; 3 is still adversarial
        "min_data_in_leaf": int(rng.choice([3, 5, 20])),
        "max_bin": int(rng.choice([15, 63, 255])),
        "learning_rate": float(rng.choice([0.05, 0.1, 0.3])),
        "lambda_l1": float(rng.choice([0.0, 0.0, 1.0])),
        "lambda_l2": float(rng.choice([0.0, 1.0])),
        "min_gain_to_split": float(rng.choice([0.0, 0.0, 0.1])),
        "enable_bundle": bool(rng.rand() < 0.7),
        "verbose": -1,
    }
    # feature-level draws ------------------------------------------------
    r = rng.rand()
    if r < 0.25:
        params.update(bagging_fraction=float(rng.choice([0.5, 0.8])),
                      bagging_freq=1)
    elif r < 0.45:
        params.update(data_sample_strategy="goss",
                      top_rate=0.3, other_rate=0.2)
    if rng.rand() < 0.2:
        params.update(boosting="dart", drop_rate=0.3)
    if cat_col is None and rng.rand() < 0.3:
        mono = [0] * d
        mono[0] = 1
        params.update(monotone_constraints=mono,
                      monotone_constraints_method=str(
                          rng.choice(["basic", "intermediate", "advanced"])))
    if cat_col is None and not for_dp and rng.rand() < 0.15:
        # linear trees route both sides to the host learner — the draw
        # still covers determinism of that path
        params.update(linear_tree=True)
    if tmp_path is not None and rng.rand() < 0.2 and cat_col != 0:
        forced = {"feature": 0, "threshold": float(np.nanmedian(X[:, 0]))}
        fp = os.path.join(str(tmp_path), "forced.json")
        with open(fp, "w") as f:
            json.dump(forced, f)
        params["forcedsplits_filename"] = fp
    if for_dp and rng.rand() < 0.25:
        params.update(use_quantized_grad=True, stochastic_rounding=False)
    if cat_col is not None:
        params["categorical_feature"] = [cat_col]
    return X, y, w, params


@pytest.mark.parametrize("seed", range(20))
def test_host_vs_fused_random_config(seed, tmp_path):
    rng = np.random.RandomState(1000 + seed)
    X, y, w, params = _random_case(rng, tmp_path)
    rounds = 5
    b_host = lgb.train({**params, "tpu_fused_learner": "0"},
                       lgb.Dataset(X, label=y, weight=w),
                       num_boost_round=rounds)
    b_fused = lgb.train({**params, "tpu_fused_learner": "1"},
                        lgb.Dataset(X, label=y, weight=w),
                        num_boost_round=rounds)
    p_host = b_host.predict(X)
    p_fused = b_fused.predict(X)
    # identical algorithms; differences are float reduction order only.
    # near-tie splits can diverge structurally, so compare predictions,
    # not model text, at a tolerance covering one flipped minor split
    close = np.isclose(p_host, p_fused, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))
    np.testing.assert_allclose(np.mean(p_host), np.mean(p_fused),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("seed", range(10))
def test_dp_1dev_vs_8dev_random_config(seed, tmp_path):
    """The fused data-parallel shard_map program must produce the same
    model on a 1-device and an 8-device mesh (per-split psum + replicated
    argmax — any missing collective shows up as divergence here)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.RandomState(7000 + seed)
    X, y, w, params = _random_case(rng, tmp_path, for_dp=True)
    params.update(tree_learner="data", tpu_fused_learner="1")
    rounds = 4
    b1 = lgb.train({**params, "tpu_num_devices": 1},
                   lgb.Dataset(X, label=y, weight=w), num_boost_round=rounds)
    b8 = lgb.train({**params, "tpu_num_devices": 8},
                   lgb.Dataset(X, label=y, weight=w), num_boost_round=rounds)
    p1 = b1.predict(X)
    p8 = b8.predict(X)
    close = np.isclose(p1, p8, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))
    np.testing.assert_allclose(np.mean(p1), np.mean(p8),
                               rtol=1e-3, atol=1e-3)
