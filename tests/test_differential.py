"""Seeded differential sweep: the host-driven serial learner and the fused
whole-tree program must agree across random config combinations (the
cross-backend analog of the reference's CPU-vs-GPU test_dual.py, run here
as host-loop vs fused on one backend so float noise stays bounded)."""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _random_case(rng):
    n = int(rng.randint(600, 1500))
    d = int(rng.randint(4, 10))
    X = rng.randn(n, d)
    cat_col = None
    if rng.rand() < 0.5:                       # a categorical column
        cat_col = int(rng.randint(d))
        X[:, cat_col] = rng.randint(0, int(rng.randint(3, 20)), n)
    if rng.rand() < 0.5:                       # missing values
        X[rng.rand(n) < 0.1, int(rng.randint(d))] = np.nan
    if rng.rand() < 0.3:                       # exact zeros (Zero missing)
        X[rng.rand(n) < 0.3, int(rng.randint(d))] = 0.0
    w = np.abs(rng.randn(n)) + 0.1 if rng.rand() < 0.4 else None
    obj = rng.choice(["binary", "regression"])
    if obj == "binary":
        y = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(float)
    else:
        y = X[:, 0] * 2 + rng.randn(n)
    params = {
        "objective": obj,
        "num_leaves": int(rng.choice([4, 15, 31])),
        "min_data_in_leaf": int(rng.choice([1, 5, 20])),
        "max_bin": int(rng.choice([15, 63, 255])),
        "learning_rate": float(rng.choice([0.05, 0.1, 0.3])),
        "lambda_l1": float(rng.choice([0.0, 0.0, 1.0])),
        "lambda_l2": float(rng.choice([0.0, 1.0])),
        "min_gain_to_split": float(rng.choice([0.0, 0.0, 0.1])),
        "verbose": -1,
    }
    if cat_col is not None:
        params["categorical_feature"] = [cat_col]
    return X, y, w, params


@pytest.mark.parametrize("seed", range(8))
def test_host_vs_fused_random_config(seed):
    rng = np.random.RandomState(1000 + seed)
    X, y, w, params = _random_case(rng)
    rounds = 5
    b_host = lgb.train({**params, "tpu_fused_learner": "0"},
                       lgb.Dataset(X, label=y, weight=w),
                       num_boost_round=rounds)
    b_fused = lgb.train({**params, "tpu_fused_learner": "1"},
                        lgb.Dataset(X, label=y, weight=w),
                        num_boost_round=rounds)
    p_host = b_host.predict(X)
    p_fused = b_fused.predict(X)
    # identical algorithms; differences are float reduction order only.
    # near-tie splits can diverge structurally, so compare predictions,
    # not model text, at a tolerance covering one flipped minor split
    close = np.isclose(p_host, p_fused, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, (params, float(close.mean()))
    np.testing.assert_allclose(np.mean(p_host), np.mean(p_fused),
                               rtol=1e-3, atol=1e-3)
