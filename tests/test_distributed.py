"""Distributed learner tests on the virtual 8-device CPU mesh
(reference analog: tests/distributed/_test_distributed.py DistributedMockup —
multi-process localhost training asserting parity with single-process;
here: multi-device mesh vs serial learner parity, SURVEY.md §4)."""
import jax
import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression
from sklearn.metrics import roc_auc_score

import lambdagap_tpu as lgb

NEED = 8
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple (virtual) devices")


def _data(seed=0):
    return make_classification(1200, 12, n_informative=6, random_state=seed)


def _train(X, y, tree_learner, n_dev, rounds=10, extra=None):
    params = {"objective": "binary", "tree_learner": tree_learner,
              "tpu_num_devices": n_dev, "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    params.update(extra or {})
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)


def test_data_parallel_matches_serial():
    """Distributed-vs-single parity (the reference asserts per-rank models
    agree and match accuracy; exact equality holds here because the psum-ed
    histogram equals the serial histogram up to float addition order).

    tree_learner=data defaults to the FUSED shard_map whole-tree program —
    this is the multi-chip production path under test."""
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedDataParallelTreeLearner
    X, y = _data()
    b_serial = _train(X, y, "serial", 1)
    b_data = _train(X, y, "data", min(NEED, len(jax.devices())))
    assert isinstance(b_data._booster.learner, FusedDataParallelTreeLearner)
    p1 = b_serial.predict(X)
    p2 = b_data.predict(X)
    # same splits up to reduction-order float noise
    assert roc_auc_score(y, p2) > 0.95
    np.testing.assert_allclose(p1, p2, rtol=1e-3, atol=1e-4)


def test_host_loop_data_parallel_opt_out():
    """tpu_fused_learner=0 falls back to the host-orchestrated learner and
    still matches."""
    from lambdagap_tpu.parallel import DataParallelTreeLearner
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedDataParallelTreeLearner
    X, y = _data(seed=4)
    nd = min(NEED, len(jax.devices()))
    b_host = _train(X, y, "data", nd, extra={"tpu_fused_learner": "0"})
    lrn = b_host._booster.learner
    assert isinstance(lrn, DataParallelTreeLearner)
    assert not isinstance(lrn, FusedDataParallelTreeLearner)
    b_fused = _train(X, y, "data", nd)
    np.testing.assert_allclose(b_host.predict(X), b_fused.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_fused_data_parallel_bagging_and_uneven_rows():
    """Bagging masks + a row count not divisible by the mesh (pad rows must
    stay out of histograms and scores)."""
    X, y = _data(seed=5)
    X, y = X[:1157], y[:1157]        # 1157 % 8 != 0
    nd = min(NEED, len(jax.devices()))
    b = _train(X, y, "data", nd, rounds=8,
               extra={"bagging_fraction": 0.7, "bagging_freq": 1})
    assert roc_auc_score(y, b.predict(X)) > 0.9


def test_fused_data_parallel_quantized():
    """use_quantized_grad under the fused distributed learner."""
    X, y = _data(seed=6)
    nd = min(NEED, len(jax.devices()))
    b_q = _train(X, y, "data", nd, extra={"use_quantized_grad": True})
    b_f = _train(X, y, "data", nd)
    auc_q = roc_auc_score(y, b_q.predict(X))
    auc_f = roc_auc_score(y, b_f.predict(X))
    assert auc_q > auc_f - 0.01, (auc_q, auc_f)


def test_quantized_distributed_reduction_is_exact():
    """quant_exact mode psums RAW integer level sums (scales applied after
    the collective), so quantized serial and 8-shard training see identical
    histograms — the shard count cannot change the model (the deterministic
    analog of the reference's integer ReduceScatter,
    data_parallel_tree_learner.cpp:283-298)."""
    X, y = _data(seed=7)
    nd = min(NEED, len(jax.devices()))
    extra = {"use_quantized_grad": True, "tpu_fused_learner": "1"}
    b_serial = _train(X, y, "serial", 1, rounds=5, extra=extra)
    b_dp = _train(X, y, "data", nd, rounds=5,
                  extra={"use_quantized_grad": True})
    np.testing.assert_allclose(b_serial.predict(X, raw_score=True),
                               b_dp.predict(X, raw_score=True),
                               rtol=1e-6, atol=1e-7)


def test_feature_parallel_matches_serial():
    """tree_learner=feature defaults to the FUSED whole-tree program with
    the COLUMN axis sharded: histograms/scans are shard-local and the only
    per-split traffic is the all_gather of per-shard best splits (the
    SyncUpGlobalBestSplit analog) + the winning column's psum broadcast.
    Must match the serial learner exactly (same scan, same tie-break)."""
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedFeatureParallelTreeLearner
    X, y = _data(seed=1)
    b_serial = _train(X, y, "serial", 1)
    b_feat = _train(X, y, "feature", min(NEED, len(jax.devices())))
    assert isinstance(b_feat._booster.learner,
                      FusedFeatureParallelTreeLearner)
    np.testing.assert_allclose(b_serial.predict(X), b_feat.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_host_loop_feature_parallel_opt_out():
    from lambdagap_tpu.parallel import FeatureParallelTreeLearner
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedFeatureParallelTreeLearner
    X, y = _data(seed=1)
    b = _train(X, y, "feature", min(4, len(jax.devices())),
               extra={"tpu_fused_learner": "0"})
    lrn = b._booster.learner
    assert isinstance(lrn, FeatureParallelTreeLearner)
    assert not isinstance(lrn, FusedFeatureParallelTreeLearner)
    b_serial = _train(X, y, "serial", 1)
    np.testing.assert_allclose(b_serial.predict(X), b.predict(X),
                               rtol=1e-3, atol=1e-4)


# tier-1 hygiene: the three heaviest tests here (~125s of the module's
# ~270s) move behind -m slow; the per-learner parity tests above keep
# the same programs covered in the 870s window
@pytest.mark.slow
def test_fused_feature_parallel_option_combos():
    """Monotone intermediate, extra_trees, bagging and interaction
    constraints all ride the feature-sharded program and match the fused
    serial learner pointwise (replicated rows -> identical arithmetic; the
    global-feature-order tie-break is preserved by the winner gather).

    The QUANTIZED combo asserts quality parity instead: int8 gradient
    levels make per-feature gains integer multiples of the scales, so
    distinct features routinely tie within 1 ulp — verified by exact
    integer recomputation on the first diverging split (features 2 vs 7,
    gains 30.351057 vs 30.351059) — and the chunked-f32 serial histogram
    vs the column-sliced shard histogram legitimately resolve such ties
    differently. A flipped near-tie split changes predictions
    categorically without changing model quality, so pointwise closeness
    is the wrong oracle there (a genuinely broken quant scan still fails
    the AUC bound)."""
    from sklearn.metrics import roc_auc_score
    X, y = _data(seed=21)
    nd = min(NEED, len(jax.devices()))
    combos = [
        {"use_quantized_grad": True},
        {"monotone_constraints": [1] + [0] * 11,
         "monotone_constraints_method": "intermediate"},
        {"extra_trees": True},
        {"bagging_fraction": 0.7, "bagging_freq": 1},
        {"interaction_constraints": [[0, 1, 2, 3],
                                     [4, 5, 6, 7, 8, 9, 10, 11]]},
    ]
    for extra in combos:
        b_f = _train(X, y, "feature", nd, rounds=5, extra=extra)
        b_s = _train(X, y, "serial", 1, rounds=5,
                     extra={**extra, "tpu_fused_learner": "1"})
        p_f, p_s = b_f.predict(X), b_s.predict(X)
        if extra.get("use_quantized_grad"):
            auc_f, auc_s = roc_auc_score(y, p_f), roc_auc_score(y, p_s)
            assert auc_f > 0.95, auc_f
            assert abs(auc_f - auc_s) < 0.01, (auc_f, auc_s)
        else:
            close = np.isclose(p_f, p_s, rtol=5e-3, atol=5e-3)
            assert close.mean() > 0.99, (extra, float(close.mean()))


def test_shard_rows_explicit_mask_channel():
    """ISSUE-8 satellite: shard_rows returns (sharded, mask, pad) — the
    in-bag/validity mask with pad rows already False, so callers stop
    re-deriving "real row" masks ad hoc."""
    import jax.numpy as jnp
    from lambdagap_tpu.parallel.mesh import shard_rows
    from lambdagap_tpu.parallel.sharding import make_mesh
    mesh = make_mesh(min(NEED, len(jax.devices())))
    n_dev = int(mesh.devices.size)
    N = 1201
    arr = jnp.arange(N, dtype=jnp.float32)
    sharded, mask, pad = shard_rows(mesh, arr)
    assert pad == (-N) % n_dev
    assert sharded.shape[0] == N + pad
    assert mask.shape[0] == N + pad
    assert int(mask.sum()) == N                  # pad rows masked out
    assert not bool(mask[N:].any()) if pad else True
    # an explicit in-bag mask combines with the pad mask
    inbag = jnp.asarray(np.arange(N) % 3 != 0)
    _, m2, _ = shard_rows(mesh, inbag, mask=inbag)
    assert int(m2.sum()) == int(inbag.sum())
    assert not bool(m2[N:].any()) if pad else True


@pytest.mark.slow
def test_pad_rows_contribute_exact_zeros_every_learner():
    """N not divisible by the device count: pad rows must contribute
    EXACT zeros to histograms and root counts under every distributed
    learner — the tree-0 leaf counts sum to exactly N (any pad leakage
    shows up as a count drift or a different root population)."""
    rng = np.random.RandomState(5)
    N = 1201
    X = rng.randn(N, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    nd = min(NEED, len(jax.devices()))

    def tree0_leaf_counts(b):
        t = b.model_to_string().split("Tree=0\n")[1]
        return [int(v) for v in
                t.split("leaf_count=")[1].split("\n")[0].split()]

    for tl, fused in (("data", "1"), ("data", "0"), ("voting", "1"),
                      ("voting", "0"), ("feature", "1"), ("feature", "0")):
        b = _train(X, y, tl, nd, rounds=1,
                   extra={"tpu_fused_learner": fused})
        counts = tree0_leaf_counts(b)
        assert sum(counts) == N, (tl, fused, sum(counts))
        # and with an in-bag mask: the root population is the bag size,
        # never the padded size
        b2 = _train(X, y, tl, nd, rounds=1,
                    extra={"tpu_fused_learner": fused,
                           "bagging_fraction": 0.7, "bagging_freq": 1})
        c2 = tree0_leaf_counts(b2)
        assert sum(c2) < N, (tl, fused, sum(c2))


def test_feature_forced_splits_route_to_data_parallel():
    import json
    import os
    import tempfile
    from lambdagap_tpu.parallel.fused_parallel import (
        FusedDataParallelTreeLearner, FusedFeatureParallelTreeLearner)
    X, y = _data(seed=22)
    forced = {"feature": 2, "threshold": float(np.median(X[:, 2]))}
    fd, path = tempfile.mkstemp(suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump(forced, f)
    try:
        b = _train(X, y, "feature", min(NEED, len(jax.devices())), rounds=3,
                   extra={"forcedsplits_filename": path})
        lrn = b._booster.learner
        assert isinstance(lrn, FusedDataParallelTreeLearner)
        assert not isinstance(lrn, FusedFeatureParallelTreeLearner)
        root = b.dump_model()["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == 2
    finally:
        os.unlink(path)


def test_voting_parallel_learns():
    X, y = _data(seed=2)
    b = _train(X, y, "voting", min(4, len(jax.devices())),
               extra={"top_k": 5})
    assert roc_auc_score(y, b.predict(X)) > 0.9


def test_data_parallel_regression_with_bagging():
    X, yr = make_regression(1000, 10, noise=2.0, random_state=3)
    b = lgb.train({"objective": "regression", "tree_learner": "data",
                   "tpu_num_devices": min(NEED, len(jax.devices())),
                   "bagging_fraction": 0.7, "bagging_freq": 1,
                   "verbose": -1, "num_leaves": 15},
                  lgb.Dataset(X, label=yr), num_boost_round=10)
    mse = float(np.mean((b.predict(X) - yr) ** 2))
    assert mse < 0.5 * float(np.var(yr))


def test_dryrun_multichip():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(__file__), "..",
                                    "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(min(8, len(jax.devices())))


def test_fused_dp_interaction_constraints_and_bynode():
    """The in-program per-leaf feature masks ride the data-parallel mesh:
    constraints hold on every shard-count, and by-node sampling stays
    seeded/reproducible."""
    X, y = _data()
    groups = [frozenset([0, 1]), frozenset([2, 3, 4, 5])]
    b = _train(X, y, "data", min(NEED, len(jax.devices())), rounds=5,
               extra={"interaction_constraints": [[0, 1], [2, 3, 4, 5]],
                      "feature_fraction_bynode": 0.7})
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedDataParallelTreeLearner
    assert isinstance(b._booster.learner, FusedDataParallelTreeLearner)
    for t in b._booster.host_models:
        def walk(node, path):
            if node < 0:
                if path:
                    assert any(path <= g for g in groups), path
                return
            p2 = path | {t.split_feature[node]}
            walk(t.left_child[node], p2)
            walk(t.right_child[node], p2)
        if t.num_internal:
            walk(0, frozenset())
    b2 = _train(X, y, "data", min(NEED, len(jax.devices())), rounds=5,
                extra={"interaction_constraints": [[0, 1], [2, 3, 4, 5]],
                       "feature_fraction_bynode": 0.7})
    assert b.model_to_string() == b2.model_to_string()


def test_debug_shard_agreement_check(monkeypatch):
    """LAMBDAGAP_DEBUG cross-shard divergence detection on the fused
    data-parallel path: a clean training run passes the bit-for-bit
    per-device comparison of the split sequence, and a hand-built
    divergent record is caught (compensates check_vma=False on the
    shard_map — reference analog: SyncUpGlobalBestSplit agreement,
    src/treelearner/parallel_tree_learner.h:209)."""
    from lambdagap_tpu.parallel import fused_parallel
    monkeypatch.setattr(fused_parallel, "_DEBUG_CHECKS", True)
    X, y = _data(seed=5)
    b = _train(X, y, "data", min(NEED, len(jax.devices())), rounds=3)
    assert roc_auc_score(y, b.predict(X)) > 0.9   # check ran and passed

    # negative: shards that disagree must be caught
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from lambdagap_tpu.parallel.mesh import make_mesh
    lrn = b._booster.learner
    mesh = lrn.mesh
    n_dev = int(mesh.devices.size)
    divergent = jax.device_put(
        jnp.arange(n_dev, dtype=jnp.float32),
        NamedSharding(mesh, P("data")))   # per-device values all differ

    class FakeRec:
        node_feature = divergent
        node_threshold = divergent
        node_gain = divergent
        leaf_value = divergent
        num_leaves = divergent
    with pytest.raises(Exception, match="divergence"):
        lrn._check_shard_agreement(FakeRec())


def test_fused_voting_parallel():
    """tree_learner=voting defaults to the FUSED whole-tree program (one
    compiled dispatch per tree; per-split traffic = top-k vote all_gather +
    psum of only the voted columns, reference:
    voting_parallel_tree_learner.cpp:151-184). Must match the host-loop
    voting learner (same algorithm, fused execution) and train well."""
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedVotingParallelTreeLearner
    X, y = _data(seed=11)
    params = {"top_k": 4, "min_data_in_leaf": 5}
    b_f = _train(X, y, "voting", min(NEED, len(jax.devices())), extra=params)
    assert isinstance(b_f._booster.learner, FusedVotingParallelTreeLearner)
    b_h = _train(X, y, "voting", min(NEED, len(jax.devices())),
                 extra={**params, "tpu_fused_learner": "0"})
    p_f, p_h = b_f.predict(X), b_h.predict(X)
    assert roc_auc_score(y, p_f) > 0.95
    close = np.isclose(p_f, p_h, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, float(close.mean())


@pytest.mark.slow
def test_voting_extra_trees():
    """extra_trees under voting — both variants (the reference's voting
    learner inherits it from the serial learner,
    voting_parallel_tree_learner.cpp). With top_k >= num_features every
    feature is voted, so the fused voting scan sees the fused data-parallel
    scan's inputs with the SAME PRNG streams — models agree up to
    reduction-order float noise."""
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedVotingParallelTreeLearner
    X, y = _data(seed=13)
    nd = min(NEED, len(jax.devices()))
    ex = {"extra_trees": True, "extra_seed": 17}
    b_v = _train(X, y, "voting", nd, rounds=6, extra={**ex, "top_k": 12})
    assert isinstance(b_v._booster.learner, FusedVotingParallelTreeLearner)
    b_d = _train(X, y, "data", nd, rounds=6, extra=ex)
    close = np.isclose(b_v.predict(X), b_d.predict(X), rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, float(close.mean())
    # the bandwidth-capped case trains well
    b_k = _train(X, y, "voting", nd, extra={**ex, "top_k": 4})
    assert roc_auc_score(y, b_k.predict(X)) > 0.9
    # host-loop voting accepts extra_trees too
    b_h = _train(X, y, "voting", nd,
                 extra={**ex, "top_k": 4, "tpu_fused_learner": "0"})
    assert roc_auc_score(y, b_h.predict(X)) > 0.9


def test_fused_voting_quantized():
    """use_quantized_grad under the fused voting learner: raw integer level
    sums stay shard-local, the voted-column psum reduces them exactly, and
    the gradient scales apply after the collective (the voting analog of
    the full-histogram integer reduction). The caller's config must not be
    mutated (a reused params/Config would silently lose quantization)."""
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedVotingParallelTreeLearner
    X, y = _data(seed=14)
    nd = min(NEED, len(jax.devices()))
    params = {"objective": "binary", "tree_learner": "voting",
              "tpu_num_devices": nd, "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5, "top_k": 5, "use_quantized_grad": True}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    lrn = b._booster.learner
    assert isinstance(lrn, FusedVotingParallelTreeLearner)
    assert lrn.quant and lrn.quant_exact
    assert b._booster.config.use_quantized_grad is True
    assert roc_auc_score(y, b.predict(X)) > 0.9


def test_voting_forced_splits_route_to_data_parallel():
    """forcedsplits_filename + tree_learner=voting: voting keeps histograms
    local so forced gathers cannot run — the factory routes (loudly) to the
    fused data-parallel learner and the forced schedule applies."""
    import json
    import os
    import tempfile
    from lambdagap_tpu.parallel.fused_parallel import (
        FusedDataParallelTreeLearner, FusedVotingParallelTreeLearner)
    X, y = _data(seed=15)
    forced = {"feature": 3, "threshold": float(np.median(X[:, 3]))}
    fd, path = tempfile.mkstemp(suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump(forced, f)
    try:
        nd = min(NEED, len(jax.devices()))
        b = _train(X, y, "voting", nd, rounds=3,
                   extra={"forcedsplits_filename": path, "top_k": 4})
        lrn = b._booster.learner
        assert isinstance(lrn, FusedDataParallelTreeLearner)
        assert not isinstance(lrn, FusedVotingParallelTreeLearner)
        root = b.dump_model()["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == 3
    finally:
        os.unlink(path)


def test_fused_voting_interaction_constraints():
    """Interaction constraints ride the fused voting program's in-program
    path bitmasks (same machinery as fused data-parallel)."""
    X, y = _data(seed=12)
    b = _train(X, y, "voting", min(NEED, len(jax.devices())),
               extra={"top_k": 4,
                      "interaction_constraints": "[0,1,2,3],[4,5,6,7]"})
    dump = b.dump_model()
    for ti in dump["tree_info"]:
        feats = set()
        def walk(node):
            if "split_feature" in node:
                feats.add(node["split_feature"])
                walk(node["left_child"]); walk(node["right_child"])
        walk(ti["tree_structure"])
        assert (feats <= {0, 1, 2, 3}) or (feats <= {4, 5, 6, 7}), feats
