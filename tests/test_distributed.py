"""Distributed learner tests on the virtual 8-device CPU mesh
(reference analog: tests/distributed/_test_distributed.py DistributedMockup —
multi-process localhost training asserting parity with single-process;
here: multi-device mesh vs serial learner parity, SURVEY.md §4)."""
import jax
import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression
from sklearn.metrics import roc_auc_score

import lambdagap_tpu as lgb

NEED = 8
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple (virtual) devices")


def _data(seed=0):
    return make_classification(1200, 12, n_informative=6, random_state=seed)


def _train(X, y, tree_learner, n_dev, rounds=10, extra=None):
    params = {"objective": "binary", "tree_learner": tree_learner,
              "tpu_num_devices": n_dev, "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    params.update(extra or {})
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)


def test_data_parallel_matches_serial():
    """Distributed-vs-single parity (the reference asserts per-rank models
    agree and match accuracy; exact equality holds here because the psum-ed
    histogram equals the serial histogram up to float addition order)."""
    X, y = _data()
    b_serial = _train(X, y, "serial", 1)
    b_data = _train(X, y, "data", min(NEED, len(jax.devices())))
    p1 = b_serial.predict(X)
    p2 = b_data.predict(X)
    # same splits up to reduction-order float noise
    assert roc_auc_score(y, p2) > 0.95
    np.testing.assert_allclose(p1, p2, rtol=1e-3, atol=1e-4)


def test_feature_parallel_matches_serial():
    X, y = _data(seed=1)
    b_serial = _train(X, y, "serial", 1)
    b_feat = _train(X, y, "feature", min(4, len(jax.devices())))
    np.testing.assert_allclose(b_serial.predict(X), b_feat.predict(X),
                               rtol=1e-3, atol=1e-4)


def test_voting_parallel_learns():
    X, y = _data(seed=2)
    b = _train(X, y, "voting", min(4, len(jax.devices())),
               extra={"top_k": 5})
    assert roc_auc_score(y, b.predict(X)) > 0.9


def test_data_parallel_regression_with_bagging():
    X, yr = make_regression(1000, 10, noise=2.0, random_state=3)
    b = lgb.train({"objective": "regression", "tree_learner": "data",
                   "tpu_num_devices": min(NEED, len(jax.devices())),
                   "bagging_fraction": 0.7, "bagging_freq": 1,
                   "verbose": -1, "num_leaves": 15},
                  lgb.Dataset(X, label=yr), num_boost_round=10)
    mse = float(np.mean((b.predict(X) - yr) ** 2))
    assert mse < 0.5 * float(np.var(yr))


def test_dryrun_multichip():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(__file__), "..",
                                    "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(min(8, len(jax.devices())))
