"""Exclusive Feature Bundling.

(reference: src/io/dataset.cpp:107 FindGroups / :246 FastFeatureBundling)
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.config import Config
from lambdagap_tpu.data.dataset import BinnedDataset


def _onehot_heavy(n=2000, groups=4, cards=(8, 6, 5, 7), seed=0):
    """Mutually-exclusive one-hot indicator blocks + 2 dense features —
    the classic EFB shape (bundles need low-cardinality sparse columns;
    a 255-bin continuous column can never share a <=256-bin bundle)."""
    rng = np.random.RandomState(seed)
    cols = []
    latents = []
    for g in range(groups):
        c = cards[g % len(cards)]
        k = rng.randint(0, c, n)
        latents.append(k)
        block = np.zeros((n, c))
        block[np.arange(n), k] = 1.0
        cols.append(block)
    dense = rng.randn(n, 2)
    X = np.column_stack(cols + [dense])
    y = (latents[0] * 0.5 - latents[1] * 0.3 + dense[:, 0]
         + 0.05 * rng.randn(n))
    return X, y


def test_bundle_shrinks_columns():
    X, y = _onehot_heavy()
    cfg = Config.from_params({"max_bin": 255, "min_data_in_bin": 1})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    ds.ensure_bundle(cfg)
    assert ds.bundle is not None
    # 26 one-hot columns + 2 dense: bundles must be far fewer than features
    assert ds.bundle.num_cols < ds.num_features
    assert ds.bundle.num_cols <= 8
    # every feature is mapped to exactly one column
    assert sorted(f for g in ds.bundle.members for f in g) == \
        list(range(ds.num_features))


# the 31-leaf arm is ~4x the 15-leaf one (same assertion, deeper trees)
# — tier-1 keeps the fast arm, the full matrix runs behind -m slow
@pytest.mark.parametrize(
    "leaves", [15, pytest.param(31, marks=pytest.mark.slow)])
def test_bundled_training_matches_unbundled(leaves):
    X, y = _onehot_heavy()
    base = {"objective": "regression", "num_leaves": leaves,
            "min_data_in_leaf": 10, "min_data_in_bin": 1,
            "learning_rate": 0.1, "verbose": -1,
            "tpu_fused_learner": "1", "tpu_hist_impl": "onehot"}
    b_off = lgb.train({**base, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=10)
    b_on = lgb.train({**base, "enable_bundle": True},
                     lgb.Dataset(X, label=y), num_boost_round=10)
    p_off = b_off.predict(X)
    p_on = b_on.predict(X)
    # perfectly exclusive features (max_conflict_rate=0): identical trees
    np.testing.assert_allclose(p_on, p_off, rtol=1e-4, atol=1e-5)


def test_bundled_serial_learner_unaffected():
    # host serial learner ignores the bundle artifact and must still work
    X, y = _onehot_heavy(n=800)
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1,
              "min_data_in_bin": 1, "tpu_fused_learner": "0"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    assert np.isfinite(b.predict(X)).all()
