"""extra_trees (random-threshold scans), feature_contri (per-feature gain
scaling), and the deterministic contract (reference:
feature_histogram.hpp:192-205 USE_RAND, :174 penalty;
include/LightGBM/config.h:268 deterministic)."""
import numpy as np
import pytest
from sklearn.datasets import make_classification

import lambdagap_tpu as lgb


def _data(seed=0):
    return make_classification(2000, 8, n_informative=5, random_state=seed)


def _train(X, y, rounds=10, **params):
    p = {"objective": "binary", "num_leaves": 15, "verbose": -1,
         "min_data_in_leaf": 5}
    p.update(params)
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


@pytest.mark.parametrize("fused", [False, True])
def test_extra_trees_learns_and_differs(fused):
    X, y = _data()
    f = "1" if fused else "0"
    base = _train(X, y, tpu_fused_learner=f)
    extra = _train(X, y, extra_trees=True, tpu_fused_learner=f)
    # randomized thresholds -> different model than exhaustive scan
    assert extra.model_to_string() != base.model_to_string()
    # but it still learns the signal
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, extra.predict(X)) > 0.85


@pytest.mark.parametrize("fused", [False, True])
def test_extra_trees_seed_reproducible(fused):
    X, y = _data(seed=1)
    f = "1" if fused else "0"
    a = _train(X, y, extra_trees=True, extra_seed=11, tpu_fused_learner=f)
    b = _train(X, y, extra_trees=True, extra_seed=11, tpu_fused_learner=f)
    c = _train(X, y, extra_trees=True, extra_seed=12, tpu_fused_learner=f)
    assert a.model_to_string() == b.model_to_string()
    assert a.model_to_string() != c.model_to_string()


@pytest.mark.parametrize("fused", [False, True])
def test_feature_contri_steers_root_split(fused):
    X, y = _data(seed=2)
    f = "1" if fused else "0"
    base = _train(X, y, rounds=1, tpu_fused_learner=f)
    root_feat = base.dump_model()["tree_info"][0]["tree_structure"][
        "split_feature"]
    # crush the natural winner's gain; the root must pick something else
    contri = [1.0] * X.shape[1]
    contri[root_feat] = 1e-4
    steered = _train(X, y, rounds=1, feature_contri=contri,
                     tpu_fused_learner=f)
    new_root = steered.dump_model()["tree_info"][0]["tree_structure"][
        "split_feature"]
    assert new_root != root_feat
    # all-ones contri is a no-op for the TREES (the echoed parameters
    # block legitimately records the different config)
    same = _train(X, y, rounds=1, feature_contri=[1.0] * X.shape[1],
                  tpu_fused_learner=f)

    def trees_only(s):
        return s.split("\nparameters")[0]
    assert trees_only(same.model_to_string()) == \
        trees_only(base.model_to_string())


def test_deterministic_repeat_runs_identical():
    X, y = _data(seed=3)
    a = _train(X, y, deterministic=True, bagging_fraction=0.8,
               bagging_freq=1, feature_fraction=0.8)
    b = _train(X, y, deterministic=True, bagging_fraction=0.8,
               bagging_freq=1, feature_fraction=0.8)
    assert a.model_to_string() == b.model_to_string()
