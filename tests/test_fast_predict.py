"""Native low-latency predict path (reference: src/c_api.cpp:63
SingleRowPredictorInner): small batches route through the host forest
traversal and must agree exactly with the device batched predictor."""
import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb
from lambdagap_tpu import native


pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native lib unavailable")


def test_binary_small_batch_matches_device():
    X, y = make_classification(3000, 12, n_informative=6, random_state=0)
    X[::11, 3] = np.nan
    b = lgb.train({"objective": "binary", "num_leaves": 31, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=15)
    full = b.predict(X[:600])                 # > 512 rows -> device path
    parts = np.concatenate([b.predict(X[i:i + 100])
                            for i in range(0, 600, 100)])
    np.testing.assert_allclose(full, parts, rtol=1e-6, atol=1e-7)
    one = np.array([b.predict(X[i:i + 1])[0] for i in range(20)])
    np.testing.assert_allclose(full[:20], one, rtol=1e-6, atol=1e-7)


def test_multiclass_and_categorical():
    X, y = make_classification(3000, 10, n_informative=6, n_classes=3,
                               random_state=1)
    Xc = np.column_stack([X[:, :9], np.abs(X[:, 9] * 5).astype(int)])
    b = lgb.train({"objective": "multiclass", "num_class": 3, "verbose": -1,
                   "categorical_feature": [9]},
                  lgb.Dataset(Xc, label=y), num_boost_round=10)
    full = b.predict(Xc[:600])
    parts = np.vstack([b.predict(Xc[i:i + 64]) for i in range(0, 600, 64)])
    np.testing.assert_allclose(full, parts[:600], rtol=1e-5, atol=1e-6)


def test_raw_score_and_refit_invalidation():
    X, y = make_regression(2000, 8, noise=3.0, random_state=2)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbose": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=10)
    raw_small = b.predict(X[:10], raw_score=True)
    raw_full = b.predict(X[:600], raw_score=True)[:10]
    # device path accumulates in f32, native in f64 — ordering noise only
    np.testing.assert_allclose(raw_small, raw_full, rtol=1e-5, atol=1e-5)
    # refit rewrites leaf values in place; the cached flat forest must not
    # serve stale values
    before = b.predict(X[:5])
    b2 = b.refit(X, y + 100.0)
    after = b2.predict(X[:5])
    assert not np.allclose(before, after)
    np.testing.assert_allclose(after, b2.predict(X[:600])[:5], rtol=1e-5,
                               atol=1e-5)
