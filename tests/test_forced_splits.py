"""Forced splits (reference: src/treelearner/serial_tree_learner.cpp:624
ForceSplits + examples/binary_classification/forced_splits.json): the JSON
tree of (feature, threshold) pairs is applied BFS before the gain-driven
search, in both the host serial learner and the fused device learner."""
import json
import os

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lambdagap_tpu as lgb

REF_BIN = "/root/reference/examples/binary_classification"


def _data(seed=0):
    X, y = make_classification(2000, 8, n_informative=5, random_state=seed)
    return X, y


def _train(X, y, forced, tmp_path, rounds=3, **params):
    fpath = tmp_path / "forced.json"
    fpath.write_text(json.dumps(forced))
    p = {"objective": "binary", "num_leaves": 8, "verbose": -1,
         "forcedsplits_filename": str(fpath)}
    p.update(params)
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)


def _root(booster, i=0):
    return booster.dump_model()["tree_info"][i]["tree_structure"]


@pytest.mark.parametrize("fused", [False, True])
def test_forced_root_and_children(tmp_path, fused):
    X, y = _data()
    # feature 7 is noise — never the natural best split; force it at the
    # median, then force feature 6 on both children
    med = float(np.median(X[:, 7]))
    forced = {"feature": 7, "threshold": med,
              "left": {"feature": 6, "threshold": 0.0},
              "right": {"feature": 6, "threshold": 0.0}}
    bst = _train(X, y, forced, tmp_path,
                 tpu_fused_learner="1" if fused else "0")
    for i in range(3):   # every tree gets the same forced prefix
        root = _root(bst, i)
        assert root["split_feature"] == 7
        assert abs(root["threshold"] - med) < 0.5
        assert root["left_child"]["split_feature"] == 6
        assert root["right_child"]["split_feature"] == 6


def test_forced_serial_fused_agree(tmp_path):
    X, y = _data(seed=1)
    forced = {"feature": 0, "threshold": 0.2,
              "left": {"feature": 1, "threshold": -0.1}}
    b0 = _train(X, y, forced, tmp_path, tpu_fused_learner="0")
    b1 = _train(X, y, forced, tmp_path, tpu_fused_learner="1")
    p0 = b0.predict(X[:300])
    p1 = b1.predict(X[:300])
    np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("fused", [False, True])
def test_forced_abort_on_bad_split(tmp_path, fused):
    """A forced split with no positive gain aborts forcing; training
    continues with gain-driven splits (abort_last_forced_split analog)."""
    X, y = _data(seed=2)
    # threshold below the minimum puts every row on one side -> no gain
    forced = {"feature": 0, "threshold": float(X[:, 0].min()) - 100.0,
              "left": {"feature": 1, "threshold": 0.0}}
    bst = _train(X, y, forced, tmp_path,
                 tpu_fused_learner="1" if fused else "0")
    root = _root(bst)
    assert "split_feature" in root          # tree still grew
    preds = bst.predict(X)
    assert np.all(np.isfinite(preds))


@pytest.mark.skipif(not os.path.isdir(REF_BIN),
                    reason="reference checkout not present")
def test_forced_reference_example(tmp_path):
    """The reference's shipped forced-splits config trains against its own
    binary_classification data with the forced prefix in place."""
    data = np.loadtxt(os.path.join(REF_BIN, "binary.train"))
    y, X = data[:, 0], data[:, 1:]
    forced = json.load(open(os.path.join(REF_BIN, "forced_splits.json")))
    bst = _train(X, y, forced, tmp_path, rounds=10, num_leaves=31,
                 metric="auc")
    root = _root(bst)
    assert root["split_feature"] == 25
    assert abs(root["threshold"] - 1.3) < 0.3
    assert root["left_child"]["split_feature"] == 26
    assert root["right_child"]["split_feature"] == 26
    # the model still learns
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.8


def test_forced_fused_data_parallel(tmp_path):
    """Forced splits ride the fused data-parallel (multi-chip) path too."""
    X, y = _data(seed=3)
    forced = {"feature": 7, "threshold": float(np.median(X[:, 7]))}
    bst = _train(X, y, forced, tmp_path, tree_learner="data",
                 tpu_num_devices=4, min_data_in_leaf=5)
    assert _root(bst)["split_feature"] == 7
