"""Fused whole-tree-on-device learner vs host-driven serial learner parity.

The TPU analog of the reference's CPU-vs-device dual test
(reference: tests/python_package_test/test_dual.py:19-37): both learners
implement the same leaf-wise algorithm, so trained models must match.
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _data(n=1200, d=8, seed=11, cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    if cat:
        X[:, 0] = rng.randint(0, 12, n)
    y = (X[:, 1] + np.sin(X[:, 2] * 2) +
         (X[:, 0] % 3 if cat else X[:, 3]) * 0.5 + 0.1 * rng.randn(n))
    return X, y


def _train(X, y, fused, extra=None):
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 20, "learning_rate": 0.1, "verbose": -1,
              "tpu_fused_learner": "1" if fused else "0",
              "tpu_hist_impl": "onehot"}
    if extra:
        params.update(extra)
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=([0] if extra and
                                          extra.get("_cat") else "auto"),
                     params=params)
    return lgb.train(params, ds, num_boost_round=8)


@pytest.mark.parametrize("extra", [
    None,
    {"max_depth": 3},
    {"bagging_fraction": 0.7, "bagging_freq": 1, "bagging_seed": 4},
    {"_cat": True},
    {"lambda_l1": 0.5, "lambda_l2": 2.0},
])
def test_fused_matches_serial(extra):
    cat = bool(extra and extra.get("_cat"))
    X, y = _data(cat=cat)
    ex = dict(extra or {})
    ex.pop("_cat", None)
    ex = {**ex, "_cat": cat} if cat else ex
    b_host = _train(X, y, fused=False, extra=ex)
    b_fused = _train(X, y, fused=True, extra=ex)
    p_host = b_host.predict(X)
    p_fused = b_fused.predict(X)
    np.testing.assert_allclose(p_fused, p_host, rtol=1e-4, atol=1e-5)


def test_fused_converged_tree_is_stable():
    # min_data_in_leaf so large that trees stop splitting: masked no-op
    # steps must leave state intact and predictions finite
    X, y = _data(n=300)
    b = _train(X, y, fused=True, extra={"min_data_in_leaf": 140})
    p = b.predict(X)
    assert np.isfinite(p).all()
    s = b.model_to_string()
    assert s.count("Tree=") == 8
