"""graftir (lambdagap_tpu.analysis.ir): the ISSUE-17 acceptance surface.

Covers the contract registry (registration-site anchoring, the I-rule
catalog, the stdlib-only import guarantee), the per-program verdict
cache (key sensitivity, partial-invalidation planning, the global
full-run guards), the ``--ir`` CLI through the ``--ir-results`` seam
(formats, exit codes, budget enforcement, the I/R baseline namespace
partition and its byte-stable round-trip), the merged SARIF artifact,
the G0 wiring (gate present, budgets pinned), and — through the real
worker subprocess — the mutation suite's teeth: every seeded violation
class must be CAUGHT by the shipping checkers.

The seam tests run without jax: the CLI, registry and cache are
deliberately importable from the lint side, and the tests prove it.
"""
import json
import os
import subprocess
import sys

import pytest

from lambdagap_tpu.analysis import cli
from lambdagap_tpu.analysis.core import Finding, load_baseline, \
    write_baseline
from lambdagap_tpu.analysis.ir import cache as ircache
from lambdagap_tpu.analysis.ir import contracts

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SUITE = os.path.join(REPO, "tools", "run_full_suite.sh")
GATE = os.path.join(REPO, "tools", "graftir_gate.py")


def _ir_finding(**over):
    d = {"rule": "I1", "path": "lambdagap_tpu/parallel/fused_parallel.py",
         "line": 700, "col": 0,
         "message": "collective-schedule violation: expected 1 psum over "
                    "'data', lowered 2 (program "
                    "Fused2DTreeLearner._train_tree_impl, scenario "
                    "fused2d_2x4)",
         "severity": "error",
         "snippet": "Fused2DTreeLearner._train_tree_impl"}
    d.update(over)
    return d


def _results(findings=(), programs=None):
    return {"findings": list(findings),
            "programs": programs or {
                "histogram.full_histogram": {
                    "sources": ["lambdagap_tpu/ops/histogram.py"],
                    "scenarios": ["serial_host"], "findings": []}},
            "uncontracted": [], "scenarios_run": ["serial_host"],
            "elapsed_s": 0.01}


def _seam(tmp_path, findings=(), extra_args=(), programs=None):
    rf = tmp_path / "ir_results.json"
    rf.write_text(json.dumps(_results(findings, programs)))
    return ["--ir-results", str(rf), *extra_args]


# -- registry ------------------------------------------------------------
def test_rule_catalog_covers_every_contract_clause():
    assert set(contracts.IR_RULES) == {"I1", "I2", "I3", "I4", "I5"}
    for desc in contracts.IR_RULES.values():
        assert len(desc) > 20


def test_register_program_anchors_registration_site():
    snap = dict(contracts._REGISTRY)
    try:
        c = contracts.register_program(
            "test.anchor_probe", collective_free=True, max_traces=3)
        assert c.path.replace(os.sep, "/").endswith(
            "tests/test_graftir.py")
        assert c.line > 0
        assert c.sources == (c.path,)       # default: the declaring file
        assert c.max_traces == 3
        assert contracts.get_contract("test.anchor_probe") is c
    finally:
        contracts._REGISTRY.clear()
        contracts._REGISTRY.update(snap)


def test_hot_program_inventory_registered_on_import():
    """Importing the package registers the contract inventory — the
    learners' split-step schedules, the stream kernels, the predict
    engines, the linear-leaf moments (ISSUE-17 inventory floor)."""
    # registrations live at module scope NEXT to the jitted code they
    # constrain; importing the hot modules is the registration act
    from lambdagap_tpu.infer import engine, stream            # noqa: F401
    from lambdagap_tpu.models import fused_learner, gbdt      # noqa: F401
    from lambdagap_tpu.objectives import base                 # noqa: F401
    from lambdagap_tpu.ops import (histogram, linear,         # noqa: F401
                                   partition, predict,
                                   predict_tensor, split)
    from lambdagap_tpu.parallel import fused_parallel         # noqa: F401
    names = {c.name for c in contracts.all_contracts()}
    for required in [
            "FusedTreeLearner._train_tree_impl",
            "FusedDataParallelTreeLearner._train_tree_impl",
            "FusedFeatureParallelTreeLearner.__init__.sharded",
            "FusedVotingParallelTreeLearner._train_tree_impl",
            "Fused2DTreeLearner._train_tree_impl",
            "histogram.full_histogram", "histogram.leaf_histogram",
            "split.find_best_split", "partition.split_partition",
            "predict._predict_forest_block",
            "predict_tensor._predict_tensor_tile",
            "engine._predict_compiled",
            "stream._window_scorer",
            "linear.accumulate_leaf_moments"]:
        assert required in names, f"missing contract: {required}"
    # every 2-D split-step program is contracted
    assert sum("._s2_" in n for n in names) >= 6
    # learners sharing _train_tree_impl register DISTINCT contracts
    two_d = contracts.get_contract("Fused2DTreeLearner._train_tree_impl")
    assert two_d.step_collectives and two_d.quant_int_reduction


def test_lint_side_ir_modules_are_stdlib_only():
    """The modules the lint side loads (contracts, the verdict cache,
    the runner that SPAWNS the worker) must keep jax/numpy behind the
    subprocess boundary: no module-level jax import anywhere in them —
    only capture/checks/scenarios/worker/mutations (worker-side) may
    import jax, and only at module scope there."""
    import ast
    ir_dir = os.path.join(REPO, "lambdagap_tpu", "analysis", "ir")
    lint_side = {"__init__.py", "contracts.py", "cache.py", "runner.py"}
    for name in sorted(os.listdir(ir_dir)):
        if not name.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(ir_dir, name)).read())
        top = set()
        for node in tree.body:
            if isinstance(node, ast.Import):
                top.update(a.name.split(".")[0] for a in node.names)
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                top.add((node.module or "").split(".")[0])
        if name in lint_side:
            assert "jax" not in top and "numpy" not in top, \
                f"{name} is lint-side: jax/numpy must stay worker-only"


# -- the per-program verdict cache --------------------------------------
def test_program_key_tracks_source_content(tmp_path, monkeypatch):
    monkeypatch.setattr(ircache, "REPO_ROOT", str(tmp_path))
    src = tmp_path / "mod.py"
    src.write_text("x = 1\n")
    eng = ircache.engine_hash()
    k1 = ircache.program_key("p", ["mod.py"], eng)
    assert k1 == ircache.program_key("p", ["mod.py"], eng)  # stable
    src.write_text("x = 2\n")
    assert ircache.program_key("p", ["mod.py"], eng) != k1
    src.write_text("x = 1\n")
    assert ircache.program_key("p", ["mod.py"], eng) == k1  # content, not mtime
    assert ircache.program_key("q", ["mod.py"], eng) != k1  # name in key
    assert ircache.program_key("p", ["mod.py"], "other-engine") != k1


def test_plan_partial_invalidation_and_global_guards(tmp_path, monkeypatch):
    """A source edit re-runs ONLY that program's scenarios; an engine
    edit, a contract-file set change, or a scenario-less stale entry
    forces the full run."""
    monkeypatch.setattr(ircache, "REPO_ROOT", str(tmp_path))
    (tmp_path / "a.py").write_text("a\n")
    (tmp_path / "b.py").write_text("b\n")
    cp = str(tmp_path / "cache.json")
    ircache.store(cp, {
        "prog.a": {"sources": ["a.py"], "scenarios": ["s_a"],
                   "findings": [_ir_finding()]},
        "prog.b": {"sources": ["b.py"], "scenarios": ["s_b", "s_b2"],
                   "findings": []}})
    warm, rerun = ircache.plan(ircache.load(cp))
    assert rerun == [] and set(warm) == {"prog.a", "prog.b"}
    assert warm["prog.a"] == [_ir_finding()]     # verdicts replay verbatim

    (tmp_path / "b.py").write_text("b CHANGED\n")
    warm, rerun = ircache.plan(ircache.load(cp))
    assert set(warm) == {"prog.a"} and rerun == ["s_b", "s_b2"]

    cached = ircache.load(cp)
    cached["engine"] = "tampered"
    assert ircache.plan(cached) == ({}, None)           # engine guard
    cached = ircache.load(cp)
    cached["contract_files"] = cached["contract_files"] + ["new_file.py"]
    assert ircache.plan(cached) == ({}, None)           # set guard
    cached = ircache.load(cp)
    cached["programs"]["prog.b"]["scenarios"] = []
    (tmp_path / "b.py").write_text("b CHANGED AGAIN\n")
    assert ircache.plan(cached) == ({}, None)           # scenario-less stale
    assert ircache.plan(None) == ({}, None)             # no cache at all


def test_contract_file_scan_finds_registration_modules():
    files = ircache.contract_files()
    assert "lambdagap_tpu/parallel/fused_parallel.py" in files
    assert "lambdagap_tpu/ops/histogram.py" in files
    assert "lambdagap_tpu/infer/engine.py" in files
    assert not any(f.startswith("lambdagap_tpu/analysis/") for f in files)


# -- the --ir CLI through the --ir-results seam -------------------------
def test_cli_clean_results_exit_zero(tmp_path, capsys):
    rc = cli.main(_seam(tmp_path, extra_args=["--no-baseline"]))
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 finding(s)" in out and "1 program(s)" in out


def test_cli_findings_exit_one_and_formats(tmp_path, capsys):
    f = _ir_finding()
    rc = cli.main(_seam(tmp_path, [f], ["--no-baseline"]))
    assert rc == 1
    assert "I1" in capsys.readouterr().out

    rc = cli.main(_seam(tmp_path, [f],
                        ["--no-baseline", "--format", "json"]))
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["findings"][0]["rule"] == "I1"
    assert data["programs"] == {"histogram.full_histogram":
                                ["serial_host"]}
    assert data["scenarios_run"] == ["serial_host"]

    rc = cli.main(_seam(tmp_path, [f],
                        ["--no-baseline", "--format", "github"]))
    assert rc == 1
    assert "::error file=" in capsys.readouterr().out

    rc = cli.main(_seam(tmp_path, [f],
                        ["--no-baseline", "--format", "sarif"]))
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    driver = sarif["runs"][0]["tool"]["driver"]
    assert driver["name"] == "graftir"
    assert {r["id"] for r in driver["rules"]} >= {"I1"}
    assert sarif["runs"][0]["results"][0]["ruleId"] == "I1"


def test_cli_budget_overrun_fails(tmp_path, capsys):
    rc = cli.main(_seam(tmp_path,
                        extra_args=["--no-baseline", "--max-seconds", "0"]))
    assert rc == 1
    assert "budget" in capsys.readouterr().err


def test_baseline_namespace_partition_round_trip(tmp_path, capsys):
    """The one baseline file holds BOTH namespaces: the IR writer touches
    only I-entries (AST entries pass through verbatim), the round-trip is
    byte-stable, and each pass applies only its own namespace."""
    bl = tmp_path / "baseline.json"
    # seed the AST namespace
    write_baseline([Finding(rule="R1", path="models/learner.py", line=9,
                            col=0, message="host sync",
                            snippet="jax.device_get(x)")], str(bl))
    # IR write-baseline adds the I-entry and PRESERVES the R-entry
    rc = cli.main(_seam(tmp_path, [_ir_finding()],
                        ["--write-baseline", "--baseline", str(bl)]))
    capsys.readouterr()
    assert rc == 0
    entries = load_baseline(str(bl))
    assert {e["rule"] for e in entries} == {"I1", "R1"}
    first = bl.read_text()
    rc = cli.main(_seam(tmp_path, [_ir_finding()],
                        ["--write-baseline", "--baseline", str(bl)]))
    capsys.readouterr()
    assert rc == 0 and bl.read_text() == first      # byte-stable
    # the baselined IR finding no longer fails the IR pass
    rc = cli.main(_seam(tmp_path, [_ir_finding()],
                        ["--baseline", str(bl), "--format", "json"]))
    data = json.loads(capsys.readouterr().out)
    assert rc == 0 and data["baselined"] == 1 and not data["findings"]


def test_stale_ir_baseline_entry_is_a_finding(tmp_path, capsys):
    bl = tmp_path / "baseline.json"
    write_baseline([Finding(**_ir_finding())], str(bl))
    rc = cli.main(_seam(tmp_path, [],        # the I1 finding is gone
                        ["--baseline", str(bl), "--format", "json"]))
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["findings"][0]["rule"] == "R14"
    assert "--ir --write-baseline" in data["findings"][0]["message"]


def test_merge_sarif_concatenates_runs():
    lint = cli.render_sarif([], tool="graftlint")
    ir = cli.render_sarif([Finding(**_ir_finding())], tool="graftir",
                          descriptions=contracts.IR_RULES)
    merged = json.loads(cli.merge_sarif([lint, ir]))
    assert [r["tool"]["driver"]["name"] for r in merged["runs"]] == \
        ["graftlint", "graftir"]
    assert merged["runs"][1]["results"][0]["ruleId"] == "I1"


def test_list_rules_includes_ir_catalog(capsys):
    rc = cli.main(["--list-rules", "--ir"])
    out = capsys.readouterr().out
    assert rc == 0
    for rid in contracts.IR_RULES:
        assert rid in out


# -- G0 wiring: budgets are pinned in the suite, not hoped --------------
def test_g0_budgets_asserted_in_full_suite():
    """ISSUE-17 satellite 5: the suite runs graftlint cold under its 2 s
    budget AND the graftir gate under its own 570 s budget, emitting the
    single merged SARIF artifact."""
    text = open(SUITE).read()
    assert "--max-seconds 2" in text                    # graftlint budget
    assert "graftir_gate.py --max-seconds 570" in text  # graftir budget
    assert "--sarif-out" in text                        # merged artifact
    # the graftir step must come BEFORE the test groups burn wall-clock
    assert text.index("graftir_gate.py") < text.index("=== G1")


def test_gate_script_parses_and_defaults_to_570():
    src = open(GATE).read()
    compile(src, GATE, "exec")
    assert "570" in src and "merge_sarif" in src


# -- the mutation suite's teeth (real worker, real checkers) ------------
def test_mutation_selftest_catches_every_seeded_violation(capsys):
    """Spawns the capture worker (jax subprocess) and runs the seeded
    violations through the SHIPPING check functions: extra psum (I1),
    host callback (I2), f64 literal / pre-psum scale / float-fed int
    reduction (I3), unbucketed retrace (I4). A miss here means a checker
    silently stopped matching — exactly what the G0 gate must catch."""
    rc = cli.main(["--ir", "--selftest"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert out.count("-> caught") == 6
    assert "MISSED" not in out


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
