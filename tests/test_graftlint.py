"""graftlint (lambdagap_tpu.analysis): rule fixtures, the semantic index,
suppressions, baseline mechanics, CLI exit codes/formats, and the
full-package gate.

Fixture snippets under tests/fixtures/graftlint/ mark every expected
finding with a ``# BAD:Rn`` comment on the offending line, so the tests
assert exact rule IDs AND line numbers without hardcoding them.

The full-package test is the ISSUE-2/ISSUE-10 acceptance gate: the merged
tree must scan clean (zero non-baselined findings, every baseline entry
justified), the scan must actually have teeth (nonzero findings on the
known-bad fixtures), and the two-pass run must finish inside the 2 s G0
budget.
"""
import json
import os
import re
import subprocess
import sys
import time

import pytest

from lambdagap_tpu.analysis import (all_rules, apply_baseline, build_index,
                                    load_baseline, scan, write_baseline)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PKG = os.path.join(REPO, "lambdagap_tpu")
FIXTURES = os.path.join(HERE, "fixtures", "graftlint")
BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")

_MARK = re.compile(r"#\s*BAD:(R\d+)")


def expected_markers(relpath):
    """(rule, line) pairs from # BAD:Rn markers in a fixture."""
    out = set()
    with open(os.path.join(FIXTURES, relpath)) as f:
        for i, line in enumerate(f, 1):
            for m in _MARK.finditer(line):
                out.add((m.group(1), i))
    assert out, f"fixture {relpath} declares no expected findings"
    return out


@pytest.fixture(scope="module")
def fixture_findings():
    """One scan of the whole fixture tree, grouped by file."""
    findings = scan([FIXTURES])
    by_file = {}
    for f in findings:
        by_file.setdefault(f.path, set()).add((f.rule, f.line))
    return by_file


@pytest.mark.parametrize("relpath", [
    "r1_host_sync.py",
    "r1_cold_helper.py",
    "serve/r1_serve_loop.py",
    "ops/predict_tensor.py",
    "ops/hist_pallas.py",
    "ops/linear.py",
    "r2_recompile.py",
    "r3_clamped_slice.py",
    "r4_dtype_drift.py",
    "serve/r5_locks.py",
    "serve/r5_registry.py",
    "serve/r5_frontend.py",
    "r6_collective_axis.py",
    "parallel/rogue_learner.py",
    "obs/r7_unsynced_timing.py",
    "serve/r8_futures.py",
    "serve/r8_router.py",
    "serve/r9_cycle_a.py",
    "serve/r9_cycle_b.py",
    "serve/r9_blocking.py",
    "serve/r9_scrape.py",
    "serve/r9_autonomics.py",
    "obs/trace.py",
    "parallel/r10_rogue_specs.py",
    "r11_drift/config.py",
    "r11_drift/consumer.py",
    "data/stream.py",
])
def test_rule_fixture_exact_findings(fixture_findings, relpath):
    got = fixture_findings.get(relpath, set())
    assert got == expected_markers(relpath), (
        f"{relpath}: findings {sorted(got)} != markers "
        f"{sorted(expected_markers(relpath))}")


@pytest.mark.parametrize("relpath", [
    "suppressed.py", "file_suppressed.py", "clean.py",
    "serve/r9_hierarchy.py", "r1_hot_caller.py",
])
def test_suppressions_and_clean_files(fixture_findings, relpath):
    assert fixture_findings.get(relpath, set()) == set()


def test_every_rule_has_fixture_coverage(fixture_findings):
    covered = {rule for pairs in fixture_findings.values()
               for rule, _ in pairs}
    assert covered == {r.id for r in all_rules()}


# -- the semantic index (pass 1) ----------------------------------------
def test_r6_registry_axes_collected():
    """PackageIndex reads the axis universe out of parallel/sharding.py
    (MESH_AXES + *_AXIS constants) — the single source of truth ISSUE 8
    makes graftlint enforce."""
    from lambdagap_tpu.analysis.core import ModuleContext, PackageIndex
    src_path = os.path.join(PKG, "parallel", "sharding.py")
    with open(src_path) as f:
        src = f.read()
    index = PackageIndex()
    index.collect(ModuleContext(src_path, "parallel/sharding.py", src))
    assert index.registry_axes == {"data", "feature"}
    assert index.registry_relpath == "parallel/sharding.py"


def test_index_call_graph_resolves_self_methods_and_imports():
    """The call graph resolves self methods, constructor-typed attributes
    (self._q = FairQueue(...) -> FairQueue.try_put), and cross-module
    imported functions — the resolution R1/R9 build on."""
    _ctxs, index, _fail = build_index([os.path.join(PKG, "serve")])
    submit = index.functions[("batcher.py", "MicroBatcher.submit")]
    callees = {c.qualname for _n, c in submit.resolved_calls}
    assert "FairQueue.try_put" in callees
    # reverse map: try_put knows submit calls it
    try_put = index.functions[("batcher.py", "FairQueue.try_put")]
    assert submit.key in index.callers[try_put.key]


def test_index_lock_identities():
    """Lock identity resolution: self attrs through the enclosing class,
    foreign attrs through the unique declaring class."""
    _ctxs, index, _fail = build_index([os.path.join(PKG, "serve")])
    assert index.class_locks["ModelRegistry"]["_lock"] == "Lock"
    assert index.class_locks["ModelEntry"]["swap_lock"] == "Lock"
    assert index.class_locks["FairQueue"]["_cond"] == "Condition"
    # the registry swap path produces the hierarchical edge
    # swap_lock -> registry _lock (via _admit), and it is NOT cyclic
    swap = index.functions[("registry.py", "ModelRegistry.swap")]
    acquired = {ident for ident, _n in swap.acquires}
    assert ("ModelEntry", "swap_lock") in acquired


def test_index_config_knob_tables():
    """The index carries Config declarations, defaults, aliases, the
    compat set, and read sites — R11's whole input."""
    _ctxs, index, _fail = build_index([PKG])
    assert index.config_module == "config.py"
    assert "num_leaves" in index.config_fields
    assert "learning_rate" in index.config_fields
    assert index.config_aliases.get("n_estimators") == "num_iterations"
    assert "num_threads" in index.compat_knobs
    assert "is_ranking" in index.config_methods
    # the aligned getattr fallbacks register as reads with defaults
    getattr_reads = {r.name for r in index.knob_reads
                     if r.kind == "getattr"}
    assert "guard_nonfinite" in getattr_reads


# -- R9/R10/R11 over the real tree --------------------------------------
def test_r9_full_serve_scan_clean():
    """The real serve/ fleet's lock graph is acyclic and every blocking-
    under-lock site carries a written justification (the two frontend
    sendall sites are inline-suppressed with whys)."""
    findings = scan([PKG], select=["R9"])
    assert findings == [], [f.format() for f in findings]


def test_r10_registry_enforcement_clean_scan():
    """ISSUE-10 acceptance: R10 replaces the old no-PartitionSpec-literals
    grep test as the single source of truth — no spec literals, private
    meshes, bare jax shard_map imports, or private axis constants anywhere
    in the package outside parallel/sharding.py."""
    findings = scan([PKG], select=["R10"])
    assert findings == [], [f.format() for f in findings]


def test_r10_inactive_without_registry(tmp_path):
    """Without the registry in the scanned set there is no invariant to
    enforce: the same rogue module scans R10-clean standalone."""
    import shutil
    rogue = os.path.join(FIXTURES, "parallel", "r10_rogue_specs.py")
    shutil.copy(rogue, tmp_path / "r10_rogue_specs.py")
    alone = scan([str(tmp_path / "r10_rogue_specs.py")], select=["R10"])
    assert alone == [], [f.format() for f in alone]


def test_r11_full_package_scan_clean():
    """Every declared knob is read somewhere or listed in COMPAT_ACCEPTED;
    no typo'd reads; every inline getattr/params.get default agrees with
    the declared default (the guard_nonfinite and
    stream_ingest_threshold_mb divergences this PR fixed stay fixed)."""
    findings = scan([PKG], select=["R11"])
    assert findings == [], [f.format() for f in findings]


def test_r11_compat_set_matches_declared_fields():
    """COMPAT_ACCEPTED must name real Config fields (a deleted field must
    leave the compat set too)."""
    import dataclasses
    from lambdagap_tpu.config import COMPAT_ACCEPTED, Config
    fields = {f.name for f in dataclasses.fields(Config)}
    assert COMPAT_ACCEPTED <= fields, COMPAT_ACCEPTED - fields


def test_r1_call_graph_reach_names_the_hot_caller():
    """The retargeted R1 names the hot function that reaches the cold
    helper, so the finding is actionable without reading the index."""
    target = os.path.join(FIXTURES)
    found = [f for f in scan([target], select=["R1"])
             if f.path == "r1_cold_helper.py"]
    assert len(found) == 1
    assert "train_one_iter" in found[0].message


def test_r6_registry_overrides_private_mesh_declarations(tmp_path):
    """With a registry in scope, a module's own Mesh(("rows",)) no longer
    legitimizes psum(..., "rows") — the exact ad-hoc drift the unified
    rules exist to kill. Without the registry the same file scans clean
    (fallback to declared-anywhere)."""
    rogue = os.path.join(FIXTURES, "parallel", "rogue_learner.py")
    # standalone (no registry in the scanned set): own Mesh declares "rows"
    import shutil
    shutil.copy(rogue, tmp_path / "rogue_learner.py")
    alone = scan([str(tmp_path / "rogue_learner.py")], select=["R6"])
    assert alone == [], [f.format() for f in alone]
    # with the registry: flagged
    together = scan([os.path.join(FIXTURES, "parallel")], select=["R6"])
    assert {(f.rule, os.path.basename(f.path)) for f in together} == {
        ("R6", "rogue_learner.py")}


def test_r6_clean_scan_over_refactored_parallel_package():
    """The real parallel/ package sources every PartitionSpec from the
    registry; an R6 scan of it (registry included) must be clean."""
    findings = scan([os.path.join(PKG, "parallel")], select=["R6"])
    assert findings == [], [f.format() for f in findings]


def test_select_and_disable_filters():
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    assert all(f.rule == "R4" for f in scan([target], select=["R4"]))
    assert scan([target], disable=["R4"]) == []


# -- baseline mechanics -------------------------------------------------
def test_baseline_roundtrip_absorbs_known_findings(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    new, stale = apply_baseline(findings, load_baseline(str(bl)))
    assert new == [] and stale == []


def test_baseline_reports_new_and_stale(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    bl = tmp_path / "baseline.json"
    write_baseline(findings[:-1], str(bl))  # one finding not grandfathered
    entries = load_baseline(str(bl))
    new, stale = apply_baseline(findings, entries)
    assert len(new) == 1 and stale == []
    # a fixed finding leaves its entry stale
    new2, stale2 = apply_baseline(findings[1:], entries)
    assert len(stale2) == 1 or len(new2) == 0


def test_baseline_why_preserved_on_regeneration(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    data["findings"][0]["why"] = "fixture justification"
    bl.write_text(json.dumps(data))
    write_baseline(findings, str(bl))
    regenerated = load_baseline(str(bl))
    assert any(e["why"] == "fixture justification" for e in regenerated)


def test_baseline_output_deterministic_and_sorted(tmp_path):
    """ISSUE-10 satellite: --write-baseline output is byte-stable across
    regenerations (round-trip) and ordered by (rule, path, line), so
    baseline diffs in PRs are reviewable."""
    findings = scan([FIXTURES])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    first = bl.read_text()
    # regenerate from the same findings (with the old file present, the
    # why-carry-over path included): byte-identical
    write_baseline(findings, str(bl))
    assert bl.read_text() == first
    # regenerate from a shuffled findings list: still byte-identical
    write_baseline(list(reversed(findings)), str(bl))
    assert bl.read_text() == first
    entries = load_baseline(str(bl))
    first_lines = {}
    for f in findings:
        k = f.key()
        first_lines[k] = min(f.line, first_lines.get(k, f.line))
    keys = [(e["rule"], e["path"],
             first_lines[(e["rule"], e["path"], e["snippet"])],
             e["snippet"]) for e in entries]
    assert keys == sorted(keys)


def test_checked_in_baseline_is_writer_normalized():
    """The committed baseline round-trips through the deterministic
    writer unchanged — no hand-edit drift."""
    current = open(BASELINE).read()
    findings = scan([PKG, os.path.join(REPO, "bench.py"),
                     os.path.join(REPO, "bench_serve.py"),
                     os.path.join(REPO, "tools")])
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bl.json")
        with open(out, "w") as f:
            f.write(current)
        write_baseline(findings, out)
        assert open(out).read() == current


# -- the G0 time budget -------------------------------------------------
def test_two_pass_scan_inside_g0_budget():
    """ISSUE-10 acceptance: the full two-pass run (index build + all 11
    rules) over the package completes in < 2 s. Best of two runs: the
    budget bounds the SCAN, and a single measurement deep inside a busy
    tier-1 container measures the scheduler as much as the analyzer (one
    observed 2x inflation mid-suite against a 0.75 s idle scan); a real
    regression slows both runs, a preempted slice only one. The G0 gate
    (`--max-seconds 2` in run_full_suite.sh) still enforces the budget on
    a single live run."""
    elapsed = []
    for _ in range(2):
        t0 = time.perf_counter()
        scan([PKG])
        elapsed.append(time.perf_counter() - t0)
    assert min(elapsed) < 2.0, \
        f"scan took {[f'{e:.2f}' for e in elapsed]}s (budget 2s)"


# -- CLI ----------------------------------------------------------------
def _run_cli(*args, cwd=REPO):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         *args], capture_output=True, text=True, cwd=cwd, env=env)


def test_cli_exits_nonzero_on_bad_fixture():
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "R4" in r.stdout


def test_cli_exits_zero_on_clean_file():
    r = _run_cli(os.path.join(FIXTURES, "clean.py"), "--no-baseline")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_list_rules():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule in all_rules():
        assert rule.id in r.stdout


def test_cli_json_format():
    r = _run_cli(os.path.join(FIXTURES, "r6_collective_axis.py"),
                 "--no-baseline", "--format", "json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"R6"}


def test_cli_github_format():
    """ISSUE-10 satellite: ::error annotations CI can surface inline."""
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline", "--format", "github")
    assert r.returncode == 1
    lines = [l for l in r.stdout.splitlines() if l.startswith("::")]
    assert lines
    for line in lines:
        assert re.match(r"^::error file=.+,line=\d+,col=\d+,"
                        r"title=graftlint R\d+::", line), line


def test_cli_sarif_format():
    """ISSUE-10 satellite: valid SARIF 2.1.0 with rule metadata."""
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline", "--format", "sarif")
    assert r.returncode == 1
    sarif = json.loads(r.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    results = run["results"]
    assert results and all(res["ruleId"] == "R4" for res in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("r4_dtype_drift.py")
    assert loc["region"]["startLine"] >= 1
    rule_ids = {ru["id"] for ru in run["tool"]["driver"]["rules"]}
    assert rule_ids == {"R4"}


def test_cli_max_seconds_budget():
    """--max-seconds enforces the G0 wall budget: an absurdly small budget
    fails even a clean scan; a generous one passes."""
    target = os.path.join(FIXTURES, "clean.py")
    ok = _run_cli(target, "--no-baseline", "--max-seconds", "30")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    slow = _run_cli(target, "--no-baseline", "--max-seconds", "0.0000001")
    assert slow.returncode == 1
    assert "budget" in slow.stderr


# -- the acceptance gate ------------------------------------------------
def test_full_package_scan_clean_modulo_baseline():
    """`python -m lambdagap_tpu.analysis lambdagap_tpu/` must exit 0 on
    the merged tree: no new findings, no stale baseline entries, and every
    grandfathered finding carries a written justification."""
    findings = scan([PKG])
    entries = load_baseline(BASELINE)
    new, stale = apply_baseline(findings, entries)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.format() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"
    for e in entries:
        assert e.get("why", "").strip(), (
            f"baseline entry without justification: {e}")
