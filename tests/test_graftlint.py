"""graftlint (lambdagap_tpu.analysis): rule fixtures, the semantic index,
suppressions, baseline mechanics, CLI exit codes/formats, and the
full-package gate.

Fixture snippets under tests/fixtures/graftlint/ mark every expected
finding with a ``# BAD:Rn`` comment on the offending line, so the tests
assert exact rule IDs AND line numbers without hardcoding them.

The full-package test is the ISSUE-2/ISSUE-10 acceptance gate: the merged
tree must scan clean (zero non-baselined findings, every baseline entry
justified), the scan must actually have teeth (nonzero findings on the
known-bad fixtures), and the two-pass run must finish inside the 2 s G0
budget.
"""
import json
import os
import re
import subprocess
import sys
import time

import pytest

from lambdagap_tpu.analysis import (all_rules, apply_baseline, build_index,
                                    load_baseline, scan, write_baseline)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PKG = os.path.join(REPO, "lambdagap_tpu")
FIXTURES = os.path.join(HERE, "fixtures", "graftlint")
BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")

_MARK = re.compile(r"#\s*BAD:(R\d+)")


def expected_markers(relpath):
    """(rule, line) pairs from # BAD:Rn markers in a fixture."""
    out = set()
    with open(os.path.join(FIXTURES, relpath)) as f:
        for i, line in enumerate(f, 1):
            for m in _MARK.finditer(line):
                out.add((m.group(1), i))
    assert out, f"fixture {relpath} declares no expected findings"
    return out


@pytest.fixture(scope="module")
def fixture_findings():
    """One scan of the whole fixture tree, grouped by file."""
    findings = scan([FIXTURES])
    by_file = {}
    for f in findings:
        by_file.setdefault(f.path, set()).add((f.rule, f.line))
    return by_file


@pytest.mark.parametrize("relpath", [
    "r1_host_sync.py",
    "r1_cold_helper.py",
    "r1_chain_deep.py",
    "serve/r1_serve_loop.py",
    "ops/predict_tensor.py",
    "ops/hist_pallas.py",
    "ops/linear.py",
    "r2_recompile.py",
    "r3_clamped_slice.py",
    "r4_dtype_drift.py",
    "serve/r5_locks.py",
    "serve/r5_registry.py",
    "serve/r5_frontend.py",
    "r6_collective_axis.py",
    "parallel/rogue_learner.py",
    "parallel/r6_2d_program.py",
    "parallel/stream2d.py",
    "obs/r7_unsynced_timing.py",
    "obs/costplane.py",
    "serve/r8_futures.py",
    "serve/r8_router.py",
    "serve/r9_cycle_a.py",
    "serve/r9_cycle_b.py",
    "serve/r9_blocking.py",
    "serve/r9_deep.py",
    "serve/r9_scrape.py",
    "serve/r9_autonomics.py",
    "serve/r9_loop.py",
    "obs/trace.py",
    "parallel/r10_rogue_specs.py",
    "r11_drift/config.py",
    "r11_drift/consumer.py",
    "r12_combos/silent_combo.py",
    "serve/r13_wire.py",
    "r14_inert.py",
    "data/stream.py",
    "infer/compile.py",
    "infer/stream.py",
])
def test_rule_fixture_exact_findings(fixture_findings, relpath):
    got = fixture_findings.get(relpath, set())
    assert got == expected_markers(relpath), (
        f"{relpath}: findings {sorted(got)} != markers "
        f"{sorted(expected_markers(relpath))}")


@pytest.mark.parametrize("relpath", [
    "suppressed.py", "file_suppressed.py", "clean.py",
    "serve/r9_hierarchy.py", "r1_hot_caller.py",
    "r1_chain_hot.py", "r1_chain_mid.py",
])
def test_suppressions_and_clean_files(fixture_findings, relpath):
    assert fixture_findings.get(relpath, set()) == set()


def test_every_rule_has_fixture_coverage(fixture_findings):
    covered = {rule for pairs in fixture_findings.values()
               for rule, _ in pairs}
    assert covered == {r.id for r in all_rules()}


# -- the semantic index (pass 1) ----------------------------------------
def test_r6_registry_axes_collected():
    """PackageIndex reads the axis universe out of parallel/sharding.py
    (MESH_AXES + *_AXIS constants) — the single source of truth ISSUE 8
    makes graftlint enforce."""
    from lambdagap_tpu.analysis.core import ModuleContext, PackageIndex
    src_path = os.path.join(PKG, "parallel", "sharding.py")
    with open(src_path) as f:
        src = f.read()
    index = PackageIndex()
    index.collect(ModuleContext(src_path, "parallel/sharding.py", src))
    assert index.registry_axes == {"data", "feature"}
    assert index.registry_relpath == "parallel/sharding.py"


def test_index_call_graph_resolves_self_methods_and_imports():
    """The call graph resolves self methods, constructor-typed attributes
    (self._q = FairQueue(...) -> FairQueue.try_put), and cross-module
    imported functions — the resolution R1/R9 build on."""
    _ctxs, index, _fail = build_index([os.path.join(PKG, "serve")])
    submit = index.functions[("batcher.py", "MicroBatcher.submit")]
    callees = {c.qualname for _n, c in submit.resolved_calls}
    assert "FairQueue.try_put" in callees
    # reverse map: try_put knows submit calls it
    try_put = index.functions[("batcher.py", "FairQueue.try_put")]
    assert submit.key in index.callers[try_put.key]


def test_index_lock_identities():
    """Lock identity resolution: self attrs through the enclosing class,
    foreign attrs through the unique declaring class."""
    _ctxs, index, _fail = build_index([os.path.join(PKG, "serve")])
    assert index.class_locks["ModelRegistry"]["_lock"] == "Lock"
    assert index.class_locks["ModelEntry"]["swap_lock"] == "Lock"
    assert index.class_locks["FairQueue"]["_cond"] == "Condition"
    # the registry swap path produces the hierarchical edge
    # swap_lock -> registry _lock (via _admit), and it is NOT cyclic
    swap = index.functions[("registry.py", "ModelRegistry.swap")]
    acquired = {ident for ident, _n in swap.acquires}
    assert ("ModelEntry", "swap_lock") in acquired


def test_index_config_knob_tables():
    """The index carries Config declarations, defaults, aliases, the
    compat set, and read sites — R11's whole input."""
    _ctxs, index, _fail = build_index([PKG])
    assert index.config_module == "config.py"
    assert "num_leaves" in index.config_fields
    assert "learning_rate" in index.config_fields
    assert index.config_aliases.get("n_estimators") == "num_iterations"
    assert "num_threads" in index.compat_knobs
    assert "is_ranking" in index.config_methods
    # the aligned getattr fallbacks register as reads with defaults
    getattr_reads = {r.name for r in index.knob_reads
                     if r.kind == "getattr"}
    assert "guard_nonfinite" in getattr_reads


# -- R9/R10/R11 over the real tree --------------------------------------
def test_r9_full_serve_scan_clean():
    """The real serve/ fleet's lock graph is acyclic and every blocking-
    under-lock site carries a written justification (the two frontend
    sendall sites are inline-suppressed with whys)."""
    findings = scan([PKG], select=["R9"])
    assert findings == [], [f.format() for f in findings]


def test_r10_registry_enforcement_clean_scan():
    """ISSUE-10 acceptance: R10 replaces the old no-PartitionSpec-literals
    grep test as the single source of truth — no spec literals, private
    meshes, bare jax shard_map imports, or private axis constants anywhere
    in the package outside parallel/sharding.py."""
    findings = scan([PKG], select=["R10"])
    assert findings == [], [f.format() for f in findings]


def test_r10_inactive_without_registry(tmp_path):
    """Without the registry in the scanned set there is no invariant to
    enforce: the same rogue module scans R10-clean standalone."""
    import shutil
    rogue = os.path.join(FIXTURES, "parallel", "r10_rogue_specs.py")
    shutil.copy(rogue, tmp_path / "r10_rogue_specs.py")
    alone = scan([str(tmp_path / "r10_rogue_specs.py")], select=["R10"])
    assert alone == [], [f.format() for f in alone]


def test_r11_full_package_scan_clean():
    """Every declared knob is read somewhere or listed in COMPAT_ACCEPTED;
    no typo'd reads; every inline getattr/params.get default agrees with
    the declared default (the guard_nonfinite and
    stream_ingest_threshold_mb divergences this PR fixed stay fixed)."""
    findings = scan([PKG], select=["R11"])
    assert findings == [], [f.format() for f in findings]


def test_r11_compat_set_matches_declared_fields():
    """COMPAT_ACCEPTED must name real Config fields (a deleted field must
    leave the compat set too)."""
    import dataclasses
    from lambdagap_tpu.config import COMPAT_ACCEPTED, Config
    fields = {f.name for f in dataclasses.fields(Config)}
    assert COMPAT_ACCEPTED <= fields, COMPAT_ACCEPTED - fields


def test_r1_call_graph_reach_names_the_hot_caller():
    """The retargeted R1 names the hot function that reaches the cold
    helper, so the finding is actionable without reading the index."""
    target = os.path.join(FIXTURES)
    found = [f for f in scan([target], select=["R1"])
             if f.path == "r1_cold_helper.py"]
    assert len(found) == 1
    assert "train_one_iter" in found[0].message


def test_r6_registry_overrides_private_mesh_declarations(tmp_path):
    """With a registry in scope, a module's own Mesh(("rows",)) no longer
    legitimizes psum(..., "rows") — the exact ad-hoc drift the unified
    rules exist to kill. Without the registry the same file scans clean
    (fallback to declared-anywhere)."""
    rogue = os.path.join(FIXTURES, "parallel", "rogue_learner.py")
    # standalone (no registry in the scanned set): own Mesh declares "rows"
    import shutil
    shutil.copy(rogue, tmp_path / "rogue_learner.py")
    alone = scan([str(tmp_path / "rogue_learner.py")], select=["R6"])
    assert alone == [], [f.format() for f in alone]
    # with the registry: flagged (the 2-D-program fixture's private axes
    # ride the same registry universe)
    together = scan([os.path.join(FIXTURES, "parallel")], select=["R6"])
    assert {(f.rule, os.path.basename(f.path)) for f in together} == {
        ("R6", "rogue_learner.py"), ("R6", "r6_2d_program.py")}


def test_r6_clean_scan_over_refactored_parallel_package():
    """The real parallel/ package sources every PartitionSpec from the
    registry; an R6 scan of it (registry included) must be clean."""
    findings = scan([os.path.join(PKG, "parallel")], select=["R6"])
    assert findings == [], [f.format() for f in findings]


def test_select_and_disable_filters():
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    assert all(f.rule == "R4" for f in scan([target], select=["R4"]))
    assert scan([target], disable=["R4"]) == []


# -- baseline mechanics -------------------------------------------------
def test_baseline_roundtrip_absorbs_known_findings(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    new, stale = apply_baseline(findings, load_baseline(str(bl)))
    assert new == [] and stale == []


def test_baseline_reports_new_and_stale(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    bl = tmp_path / "baseline.json"
    write_baseline(findings[:-1], str(bl))  # one finding not grandfathered
    entries = load_baseline(str(bl))
    new, stale = apply_baseline(findings, entries)
    assert len(new) == 1 and stale == []
    # a fixed finding leaves its entry stale
    new2, stale2 = apply_baseline(findings[1:], entries)
    assert len(stale2) == 1 or len(new2) == 0


def test_baseline_why_preserved_on_regeneration(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    data["findings"][0]["why"] = "fixture justification"
    bl.write_text(json.dumps(data))
    write_baseline(findings, str(bl))
    regenerated = load_baseline(str(bl))
    assert any(e["why"] == "fixture justification" for e in regenerated)


def test_baseline_output_deterministic_and_sorted(tmp_path):
    """ISSUE-10 satellite: --write-baseline output is byte-stable across
    regenerations (round-trip) and ordered by (rule, path, snippet) —
    the entry's FULL identity key (same-key findings merge into one
    entry), so baseline diffs in PRs are reviewable and the order cannot
    drift when line numbers do."""
    findings = scan([FIXTURES])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    first = bl.read_text()
    # regenerate from the same findings (with the old file present, the
    # why-carry-over path included): byte-identical
    write_baseline(findings, str(bl))
    assert bl.read_text() == first
    # regenerate from a shuffled findings list: still byte-identical
    write_baseline(list(reversed(findings)), str(bl))
    assert bl.read_text() == first
    entries = load_baseline(str(bl))
    keys = [(e["rule"], e["path"], e["snippet"]) for e in entries]
    assert keys == sorted(keys)


def test_checked_in_baseline_is_writer_normalized():
    """The committed baseline round-trips through the deterministic
    writer unchanged — no hand-edit drift."""
    current = open(BASELINE).read()
    findings = scan([PKG, os.path.join(REPO, "bench.py"),
                     os.path.join(REPO, "bench_serve.py"),
                     os.path.join(REPO, "tools")])
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bl.json")
        with open(out, "w") as f:
            f.write(current)
        write_baseline(findings, out)
        assert open(out).read() == current


# -- the G0 time budget -------------------------------------------------
def test_two_pass_scan_inside_g0_budget():
    """ISSUE-10 acceptance: the full two-pass run (index build + all 11
    rules) over the package completes in < 2 s. Best of two runs: the
    budget bounds the SCAN, and a single measurement deep inside a busy
    tier-1 container measures the scheduler as much as the analyzer (one
    observed 2x inflation mid-suite against a 0.75 s idle scan); a real
    regression slows both runs, a preempted slice only one. The G0 gate
    (`--max-seconds 2` in run_full_suite.sh) still enforces the budget on
    a single live run."""
    elapsed = []
    for _ in range(2):
        t0 = time.perf_counter()
        scan([PKG])
        elapsed.append(time.perf_counter() - t0)
    assert min(elapsed) < 2.0, \
        f"scan took {[f'{e:.2f}' for e in elapsed]}s (budget 2s)"


# -- CLI ----------------------------------------------------------------
def _run_cli(*args, cwd=REPO):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         *args], capture_output=True, text=True, cwd=cwd, env=env)


def test_cli_exits_nonzero_on_bad_fixture():
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "R4" in r.stdout


def test_cli_exits_zero_on_clean_file():
    r = _run_cli(os.path.join(FIXTURES, "clean.py"), "--no-baseline")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_list_rules():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule in all_rules():
        assert rule.id in r.stdout


def test_cli_json_format():
    r = _run_cli(os.path.join(FIXTURES, "r6_collective_axis.py"),
                 "--no-baseline", "--format", "json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"R6"}


def test_cli_github_format():
    """ISSUE-10 satellite: ::error annotations CI can surface inline."""
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline", "--format", "github")
    assert r.returncode == 1
    lines = [l for l in r.stdout.splitlines() if l.startswith("::")]
    assert lines
    for line in lines:
        assert re.match(r"^::error file=.+,line=\d+,col=\d+,"
                        r"title=graftlint R\d+::", line), line


def test_cli_sarif_format():
    """ISSUE-10 satellite: valid SARIF 2.1.0 with rule metadata."""
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline", "--format", "sarif")
    assert r.returncode == 1
    sarif = json.loads(r.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    results = run["results"]
    assert results and all(res["ruleId"] == "R4" for res in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("r4_dtype_drift.py")
    assert loc["region"]["startLine"] >= 1
    rule_ids = {ru["id"] for ru in run["tool"]["driver"]["rules"]}
    assert rule_ids == {"R4"}


def test_cli_sarif_carries_new_rule_metadata():
    """ISSUE-14 satellite: SARIF output carries R12/R13/R14 rule metadata
    (descriptions + fingerprints) for findings of the new rules."""
    r = _run_cli(FIXTURES, "--no-baseline", "--format", "sarif")
    assert r.returncode == 1
    run = json.loads(r.stdout)["runs"][0]
    rules = {ru["id"]: ru for ru in run["tool"]["driver"]["rules"]}
    assert {"R12", "R13", "R14"} <= set(rules)
    for rid in ("R12", "R13", "R14"):
        assert rules[rid]["shortDescription"]["text"]
    assert all(res["fingerprints"]["graftlint/v1"]
               for res in run["results"])


def test_cli_max_seconds_budget():
    """--max-seconds enforces the G0 wall budget: an absurdly small budget
    fails even a clean scan; a generous one passes."""
    target = os.path.join(FIXTURES, "clean.py")
    ok = _run_cli(target, "--no-baseline", "--max-seconds", "30")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    slow = _run_cli(target, "--no-baseline", "--max-seconds", "0.0000001")
    assert slow.returncode == 1
    assert "budget" in slow.stderr


# -- transitive effect inference (pass 2, ISSUE 14) ---------------------
def test_r1_provenance_chain_three_hops_names_full_path():
    """A sync three call-graph hops from the hot function is flagged in
    its own (cold) module, and the finding prints the complete provenance
    chain — the reader never reconstructs the reach by hand."""
    found = [f for f in scan([FIXTURES], select=["R1"])
             if f.path == "r1_chain_deep.py"]
    assert len(found) == 1
    msg = found[0].message
    assert ("train_one_iter -> stage_partition -> _gather_stats -> "
            "fetch_partition_count") in msg
    assert "3 hops" in msg


def test_r9_transitive_blocking_names_depth_and_chain():
    """Blocking work TWO resolved calls below a lock (invisible to the
    ISSUE-10 one-hop walk) is flagged with its call chain."""
    found = [f for f in scan([FIXTURES], select=["R9"])
             if f.path == "serve/r9_deep.py"]
    assert len(found) == 1
    msg = found[0].message
    assert "2 calls away" in msg
    assert ("DeepPublisher.publish -> DeepPublisher._encode_and_write "
            "-> DeepPublisher._write_frame") in msg


def test_effect_analysis_fixpoint_and_witness():
    """EffectAnalysis unit semantics: direct effects, transitive
    propagation through the call graph, and provenance chains."""
    from lambdagap_tpu.analysis import build_index, get_effects
    _ctxs, index, _fail = build_index([FIXTURES])
    ana = get_effects(index)
    deep = ("serve/r9_deep.py", "DeepPublisher._write_frame")
    mid = ("serve/r9_deep.py", "DeepPublisher._encode_and_write")
    top = ("serve/r9_deep.py", "DeepPublisher.publish")
    eff = ("blocking", "self.sock.sendall")
    assert eff in ana.direct[deep]
    assert eff in ana.effects[mid] and eff in ana.effects[top]
    assert ana.chain(top, eff) == [top, mid, deep]
    # the lock acquisition is an effect too
    assert ana.has(top, "acquires")
    # and hot-reachability: the chain fixtures
    assert ana.has(("r1_chain_hot.py", "train_one_iter"), "d2h_sync")


# -- R12/R13 over the real tree (ISSUE 14) ------------------------------
def test_r12_full_package_scan_clean():
    """Every axis-knob demotion in the package is loud and names both
    knobs (the learner/gbdt/data_parallel messages this PR fixed stay
    fixed)."""
    findings = scan([PKG], select=["R12"])
    assert findings == [], [f.format() for f in findings]


def test_r12_extracted_matrix_covers_known_demotion_sites():
    """ISSUE-14 acceptance: the extracted capability matrix carries the
    known lattice cells — linear x {quantized, stream, dart/rf} and
    stream x distributed — with the right behavior kind."""
    from lambdagap_tpu.analysis import build_index
    from lambdagap_tpu.analysis.rules.r12_composition import extract_matrix
    contexts, index, _fail = build_index([PKG])
    cells = {(c.knob_a, c.knob_b, c.kind)
             for c in extract_matrix(contexts, index)}
    assert ("linear_tree", "use_quantized_grad", "demote") in cells
    assert ("data_residency", "linear_tree", "demote") in cells
    assert ("boosting", "linear_tree", "error") in cells      # dart/rf
    assert ("data_residency", "tree_learner", "demote") in cells
    assert ("tree_layout", "tree_learner", "demote") in cells


def test_capability_matrix_doc_in_sync():
    """docs/capability-matrix.md matches what the tree generates (the
    same contract gen_params_doc --check enforces for Parameters.md)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "gen_capability_matrix",
        os.path.join(REPO, "tools", "gen_capability_matrix.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with open(os.path.join(REPO, "docs", "capability-matrix.md")) as f:
        assert f.read() == mod.generate()


def test_r13_full_package_scan_clean():
    """The wire surfaces are in bijection on the merged tree (handlers ==
    client ops == docs frames; kind-map covers every degrade exception;
    serve_loop verbs documented)."""
    findings = scan([PKG], select=["R13"])
    assert findings == [], [f.format() for f in findings]


def test_wire_kind_map_covers_degrade_exceptions():
    """Runtime counterpart of R13c: every exception class guard/degrade
    defines maps to itself through the wire kind-map."""
    import inspect
    from lambdagap_tpu.guard import degrade
    from lambdagap_tpu.serve.frontend import _KINDS
    for name, obj in vars(degrade).items():
        if inspect.isclass(obj) and issubclass(obj, BaseException) \
                and obj.__module__ == degrade.__name__:
            assert _KINDS.get(name) is obj, name


def test_r14_full_package_scan_clean():
    """No inert suppressions in the merged tree (the frontend disable=R5
    class this PR removed stays removed)."""
    findings = scan([PKG], select=None)
    r14 = [f for f in findings if f.rule == "R14"]
    assert r14 == [], [f.format() for f in r14]


def test_r14_not_reported_for_rules_that_did_not_run():
    """A suppression naming a rule excluded from the scan is never called
    inert — absence of evidence only counts when the rule looked."""
    target = os.path.join(FIXTURES, "r14_inert.py")
    assert scan([target], select=["R14"]) == []
    assert scan([target], disable=["R1"]) == []
    assert [f.rule for f in scan([target])] == ["R14"]


def test_stale_baseline_entry_is_r14_finding(tmp_path):
    """CLI: a baseline entry whose finding no longer exists fails the
    scan as an R14 finding (was: a stderr warning and exit 0)."""
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"version": 1, "findings": [{
        "rule": "R4", "path": "clean.py",
        "snippet": "x = jnp.zeros(3)", "count": 1, "why": "gone"}]}))
    r = _run_cli(os.path.join(FIXTURES, "clean.py"),
                 "--baseline", str(bl))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "R14" in r.stdout and "stale baseline entry" in r.stdout


def test_write_baseline_prunes_dead_entries(tmp_path):
    """--write-baseline regenerates from current findings only: entries
    whose finding no longer exists are pruned (and reported)."""
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    bl = tmp_path / "baseline.json"
    dead = {"rule": "R4", "path": "elsewhere.py",
            "snippet": "y = jnp.ones(2)", "count": 1, "why": "dead"}
    findings = scan([target])
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    data["findings"].append(dead)
    bl.write_text(json.dumps(data))
    r = _run_cli(target, "--write-baseline", "--baseline", str(bl))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pruned 1 dead entr" in r.stdout
    kept = {(e["rule"], e["path"]) for e in load_baseline(str(bl))}
    assert ("R4", "elsewhere.py") not in kept


# -- incremental scan cache (ISSUE 14) ----------------------------------
def test_cache_cold_warm_byte_identical(tmp_path):
    """Cold and warm scans produce byte-identical findings, and the warm
    scan actually hits the cache (the G0 assertion, at the API level)."""
    from lambdagap_tpu.analysis import cache as scan_cache
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    cache_file = str(tmp_path / "cache.json")
    cold = scan([target])
    key = scan_cache.scan_key([target], None, None)
    assert scan_cache.load(cache_file, key) is None       # cold: no entry
    scan_cache.store(cache_file, key, cold)
    warm = scan_cache.load(cache_file, key)
    assert warm == cold                                    # byte-identical
    # any content change invalidates the key
    assert scan_cache.scan_key(
        [os.path.join(FIXTURES, "clean.py")], None, None) != key


def test_cache_cli_warm_hit_and_identity(tmp_path):
    """CLI: second run with the same tree hits the cache and reports the
    exact same findings JSON."""
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    cache_file = str(tmp_path / "cache.json")
    args = (target, "--no-baseline", "--format", "json",
            "--cache", cache_file)
    r1 = _run_cli(*args)
    r2 = _run_cli(*args)
    cold, warm = json.loads(r1.stdout), json.loads(r2.stdout)
    assert cold["cache_hit"] is False and warm["cache_hit"] is True
    assert cold["findings"] == warm["findings"]
    assert r1.returncode == r2.returncode == 1
    # --no-cache forces a cold scan
    r3 = _run_cli(*args, "--no-cache")
    assert json.loads(r3.stdout)["cache_hit"] is False


def test_cache_invalidated_by_analyzer_options():
    """Different --select/--disable selections never share a cache
    entry."""
    from lambdagap_tpu.analysis import cache as scan_cache
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    assert scan_cache.scan_key([target], ["R4"], None) != \
        scan_cache.scan_key([target], None, None)


# -- --changed-only (pre-commit fast path, ISSUE 14) --------------------
def test_changed_only_scans_only_git_changed_files(tmp_path):
    """In a git repo, --changed-only scans exactly the changed files (a
    dirty hazard file is found; with a clean tree there is nothing to
    do), and whole-package finding classes stand down."""
    import shutil
    repo = tmp_path / "mini"
    repo.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*a):
        subprocess.run(["git", *a], cwd=repo, check=True, env=env,
                       capture_output=True)

    (repo / "good.py").write_text("import jax.numpy as jnp\n"
                                  "X = jnp.zeros(3)\n")   # R4, committed
    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    cli = [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
           ".", "--changed-only", "--no-baseline", "--format", "json"]
    clean = subprocess.run(cli[:-2], cwd=repo, env=env,
                           capture_output=True, text=True)
    assert clean.returncode == 0
    assert "no scanned files changed" in clean.stdout
    (repo / "bad.py").write_text("import jax.numpy as jnp\n"
                                 "Y = jnp.ones(4)\n")     # R4, uncommitted
    dirty = subprocess.run(cli, cwd=repo, env=env,
                           capture_output=True, text=True)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    found = json.loads(dirty.stdout)["findings"]
    assert {f["path"] for f in found} == {"bad.py"}        # good.py skipped
    # a partial scan must never regenerate the baseline (it would prune
    # every entry outside the changed files)
    refuse = subprocess.run(cli[:5] + ["--write-baseline"], cwd=repo,
                            env=env, capture_output=True, text=True)
    assert refuse.returncode == 2
    assert "needs a full scan" in refuse.stderr


# -- the acceptance gate ------------------------------------------------
def test_full_package_scan_clean_modulo_baseline():
    """`python -m lambdagap_tpu.analysis lambdagap_tpu/` must exit 0 on
    the merged tree: no new findings, no stale baseline entries, and every
    grandfathered finding carries a written justification."""
    findings = scan([PKG])
    entries = load_baseline(BASELINE)
    new, stale = apply_baseline(findings, entries)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.format() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"
    for e in entries:
        assert e.get("why", "").strip(), (
            f"baseline entry without justification: {e}")
