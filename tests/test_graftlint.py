"""graftlint (lambdagap_tpu.analysis): rule fixtures, suppressions,
baseline mechanics, CLI exit codes, and the full-package gate.

Fixture snippets under tests/fixtures/graftlint/ mark every expected
finding with a ``# BAD:Rn`` comment on the offending line, so the tests
assert exact rule IDs AND line numbers without hardcoding them.

The full-package test is the ISSUE-2 acceptance gate: the merged tree must
scan clean (zero non-baselined findings, every baseline entry justified),
and the scan must actually have teeth (nonzero findings on the known-bad
fixtures).
"""
import json
import os
import re
import subprocess
import sys

import pytest

from lambdagap_tpu.analysis import (all_rules, apply_baseline, load_baseline,
                                    scan, write_baseline)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
PKG = os.path.join(REPO, "lambdagap_tpu")
FIXTURES = os.path.join(HERE, "fixtures", "graftlint")
BASELINE = os.path.join(REPO, "tools", "graftlint_baseline.json")

_MARK = re.compile(r"#\s*BAD:(R\d)")


def expected_markers(relpath):
    """(rule, line) pairs from # BAD:Rn markers in a fixture."""
    out = set()
    with open(os.path.join(FIXTURES, relpath)) as f:
        for i, line in enumerate(f, 1):
            m = _MARK.search(line)
            if m:
                out.add((m.group(1), i))
    assert out, f"fixture {relpath} declares no expected findings"
    return out


@pytest.fixture(scope="module")
def fixture_findings():
    """One scan of the whole fixture tree, grouped by file."""
    findings = scan([FIXTURES])
    by_file = {}
    for f in findings:
        by_file.setdefault(f.path, set()).add((f.rule, f.line))
    return by_file


@pytest.mark.parametrize("relpath", [
    "r1_host_sync.py",
    "serve/r1_serve_loop.py",
    "ops/predict_tensor.py",
    "ops/hist_pallas.py",
    "r2_recompile.py",
    "r3_clamped_slice.py",
    "r4_dtype_drift.py",
    "serve/r5_locks.py",
    "serve/r5_registry.py",
    "serve/r5_frontend.py",
    "r6_collective_axis.py",
    "parallel/rogue_learner.py",
    "obs/r7_unsynced_timing.py",
    "serve/r8_futures.py",
    "serve/r8_router.py",
    "data/stream.py",
])
def test_rule_fixture_exact_findings(fixture_findings, relpath):
    got = fixture_findings.get(relpath, set())
    assert got == expected_markers(relpath), (
        f"{relpath}: findings {sorted(got)} != markers "
        f"{sorted(expected_markers(relpath))}")


@pytest.mark.parametrize("relpath", [
    "suppressed.py", "file_suppressed.py", "clean.py",
])
def test_suppressions_and_clean_files(fixture_findings, relpath):
    assert fixture_findings.get(relpath, set()) == set()


def test_every_rule_has_fixture_coverage(fixture_findings):
    covered = {rule for pairs in fixture_findings.values()
               for rule, _ in pairs}
    assert covered == {r.id for r in all_rules()}


def test_r6_registry_axes_collected():
    """PackageIndex reads the axis universe out of parallel/sharding.py
    (MESH_AXES + *_AXIS constants) — the single source of truth ISSUE 8
    makes graftlint enforce."""
    from lambdagap_tpu.analysis.core import ModuleContext, PackageIndex
    src_path = os.path.join(PKG, "parallel", "sharding.py")
    with open(src_path) as f:
        src = f.read()
    index = PackageIndex()
    index.collect(ModuleContext(src_path, "parallel/sharding.py", src))
    assert index.registry_axes == {"data", "feature"}


def test_r6_registry_overrides_private_mesh_declarations(tmp_path):
    """With a registry in scope, a module's own Mesh(("rows",)) no longer
    legitimizes psum(..., "rows") — the exact ad-hoc drift the unified
    rules exist to kill. Without the registry the same file scans clean
    (fallback to declared-anywhere)."""
    rogue = os.path.join(FIXTURES, "parallel", "rogue_learner.py")
    # standalone (no registry in the scanned set): own Mesh declares "rows"
    import shutil
    shutil.copy(rogue, tmp_path / "rogue_learner.py")
    alone = scan([str(tmp_path / "rogue_learner.py")], select=["R6"])
    assert alone == [], [f.format() for f in alone]
    # with the registry: flagged
    together = scan([os.path.join(FIXTURES, "parallel")], select=["R6"])
    assert {(f.rule, os.path.basename(f.path)) for f in together} == {
        ("R6", "rogue_learner.py")}


def test_r6_clean_scan_over_refactored_parallel_package():
    """The real parallel/ package sources every PartitionSpec from the
    registry; an R6 scan of it (registry included) must be clean."""
    findings = scan([os.path.join(PKG, "parallel")], select=["R6"])
    assert findings == [], [f.format() for f in findings]


def test_no_learner_local_partitionspec_literals():
    """ISSUE-8 acceptance: no learner-local PartitionSpec/P(...) literals
    remain in the four parallel learner modules — every spec resolves
    through parallel/sharding.py."""
    for mod in ("data_parallel", "fused_parallel", "voting_parallel",
                "feature_parallel"):
        with open(os.path.join(PKG, "parallel", f"{mod}.py")) as f:
            src = f.read()
        assert "PartitionSpec" not in src, mod
        assert not re.search(r"(?<![\w.])P\(", src), mod


def test_select_and_disable_filters():
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    assert all(f.rule == "R4" for f in scan([target], select=["R4"]))
    assert scan([target], disable=["R4"]) == []


# -- baseline mechanics -------------------------------------------------
def test_baseline_roundtrip_absorbs_known_findings(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    new, stale = apply_baseline(findings, load_baseline(str(bl)))
    assert new == [] and stale == []


def test_baseline_reports_new_and_stale(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    bl = tmp_path / "baseline.json"
    write_baseline(findings[:-1], str(bl))  # one finding not grandfathered
    entries = load_baseline(str(bl))
    new, stale = apply_baseline(findings, entries)
    assert len(new) == 1 and stale == []
    # a fixed finding leaves its entry stale
    new2, stale2 = apply_baseline(findings[1:], entries)
    assert len(stale2) == 1 or len(new2) == 0


def test_baseline_why_preserved_on_regeneration(tmp_path):
    target = os.path.join(FIXTURES, "r4_dtype_drift.py")
    findings = scan([target])
    bl = tmp_path / "baseline.json"
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    data["findings"][0]["why"] = "fixture justification"
    bl.write_text(json.dumps(data))
    write_baseline(findings, str(bl))
    regenerated = load_baseline(str(bl))
    assert any(e["why"] == "fixture justification" for e in regenerated)


# -- CLI ----------------------------------------------------------------
def _run_cli(*args, cwd=REPO):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         *args], capture_output=True, text=True, cwd=cwd, env=env)


def test_cli_exits_nonzero_on_bad_fixture():
    r = _run_cli(os.path.join(FIXTURES, "r4_dtype_drift.py"),
                 "--no-baseline")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "R4" in r.stdout


def test_cli_exits_zero_on_clean_file():
    r = _run_cli(os.path.join(FIXTURES, "clean.py"), "--no-baseline")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_list_rules():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule in all_rules():
        assert rule.id in r.stdout


def test_cli_json_format():
    r = _run_cli(os.path.join(FIXTURES, "r6_collective_axis.py"),
                 "--no-baseline", "--format", "json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"R6"}


# -- the acceptance gate ------------------------------------------------
def test_full_package_scan_clean_modulo_baseline():
    """`python -m lambdagap_tpu.analysis lambdagap_tpu/` must exit 0 on
    the merged tree: no new findings, no stale baseline entries, and every
    grandfathered finding carries a written justification."""
    findings = scan([PKG])
    entries = load_baseline(BASELINE)
    new, stale = apply_baseline(findings, entries)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.format() for f in new)
    assert stale == [], f"stale baseline entries: {stale}"
    for e in entries:
        assert e.get("why", "").strip(), (
            f"baseline entry without justification: {e}")
