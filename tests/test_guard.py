"""lambdagap_tpu.guard — training-side fault tolerance.

Covers the ISSUE-5 acceptance surface: atomic snapshot writes with a state
sidecar + trailing checksum (torn/corrupt snapshots detected and skipped),
SIGKILL-mid-train auto-resume producing a model identical to the
uninterrupted run, and the guard_nonfinite policy trio (raise emits a
diagnostic event then fails; skip_tree drops the iteration and keeps state
bit-consistent; clip keeps training finite).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.guard import (NonFiniteError, SnapshotError,
                                 latest_snapshot, read_snapshot)
from lambdagap_tpu.guard.snapshot import (atomic_write_text, capture_state,
                                          compose_snapshot, snapshot_path)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = {"objective": "regression", "verbose": -1, "min_data_in_leaf": 5}


def _data(n=400, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8).astype(np.float32)
    y = (X[:, 0] * 2 - X[:, 1] + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _trees(booster) -> str:
    """Model text up to 'end of trees' (the parameters echo differs by
    construction between guard configs; the trees are the model)."""
    return booster.model_to_string().split("end of trees")[0]


# -- snapshot format ----------------------------------------------------
def test_atomic_write_leaves_no_tmp(tmp_path):
    p = str(tmp_path / "out.txt")
    atomic_write_text(p, "hello\n")
    assert open(p).read() == "hello\n"
    assert os.listdir(tmp_path) == ["out.txt"]


def test_snapshot_roundtrip_and_checksum(tmp_path):
    X, y = _data()
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=3)
    gb = b._booster
    state = capture_state(gb)
    assert state["iteration"] == 3
    p = str(tmp_path / "m.txt.snapshot_iter_3")
    atomic_write_text(p, compose_snapshot(gb.save_model_to_string(), state))
    model_text, state2 = read_snapshot(p)
    assert state2 == json.loads(json.dumps(state))
    from lambdagap_tpu.models.gbdt import GBDT
    loaded = GBDT.from_model_string(model_text)
    assert len(loaded.models) == 3


@pytest.mark.parametrize("corruption", ["truncate", "flip", "no_trailer"])
def test_torn_snapshot_detected(tmp_path, corruption):
    X, y = _data()
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=2)
    p = str(tmp_path / "m.txt.snapshot_iter_2")
    data = compose_snapshot(b._booster.save_model_to_string(),
                            capture_state(b._booster))
    if corruption == "truncate":
        data = data[: len(data) // 2]
    elif corruption == "flip":
        data = data.replace("leaf_value=", "leaf_value=9", 1)
    else:
        data = data[: data.rindex("!snapshot_state=")]
    with open(p, "w") as f:
        f.write(data)
    with pytest.raises(SnapshotError):
        read_snapshot(p)


def test_latest_snapshot_skips_corrupt_falls_back_to_older(tmp_path):
    X, y = _data()
    out = str(tmp_path / "model.txt")
    b2 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=2)
    b3 = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=3)
    atomic_write_text(snapshot_path(out, 2), compose_snapshot(
        b2._booster.save_model_to_string(), capture_state(b2._booster)))
    # newest snapshot torn mid-write: checksum must reject it
    good = compose_snapshot(b3._booster.save_model_to_string(),
                            capture_state(b3._booster))
    with open(snapshot_path(out, 3), "w") as f:
        f.write(good[: len(good) // 2])
    found = latest_snapshot(out)
    assert found is not None
    path, _, state = found
    assert path.endswith("iter_2") and state["iteration"] == 2
    # with the torn file repaired, the newer snapshot wins
    atomic_write_text(snapshot_path(out, 3), good)
    assert latest_snapshot(out)[2]["iteration"] == 3


def test_torn_snapshot_fault_point(tmp_path):
    """The torn_snapshot fault writes a checksum-less half file in place;
    resume must skip it."""
    X, y = _data()
    out = str(tmp_path / "m.txt")
    lgb.train({**PARAMS, "snapshot_freq": 1, "output_model": out,
               "guard_faults": "torn_snapshot=3"},
              lgb.Dataset(X, label=y), num_boost_round=3)
    with pytest.raises(SnapshotError):
        read_snapshot(snapshot_path(out, 3))
    assert latest_snapshot(out)[2]["iteration"] == 2


# -- non-finite policies ------------------------------------------------
def test_nonfinite_raise_policy_and_event(tmp_path):
    X, y = _data()
    run_log = str(tmp_path / "run.jsonl")
    with pytest.raises(NonFiniteError):
        lgb.train({**PARAMS, "guard_faults": "nonfinite_grad=1",
                   "telemetry_out": run_log},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    events = [json.loads(ln) for ln in open(run_log) if ln.strip()]
    guard_events = [e for e in events if e.get("event") == "guard_nonfinite"]
    assert len(guard_events) == 1
    assert guard_events[0]["policy"] == "raise"
    assert guard_events[0]["iter"] == 1


def test_nonfinite_skip_tree_is_state_consistent():
    """skip_tree drops the poisoned iteration and restores scores exactly:
    the remaining trees match a clean run with one fewer round."""
    X, y = _data()
    b = lgb.train({**PARAMS, "guard_nonfinite": "skip_tree",
                   "guard_faults": "nonfinite_grad=2"},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    assert b.num_trees() == 4
    ref = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=4)
    assert _trees(b) == _trees(ref)
    assert np.array_equal(b.predict(X[:50]), ref.predict(X[:50]))


def test_nonfinite_skip_tree_fused_learner():
    X, y = _data()
    fused = {**PARAMS, "tpu_fused_learner": "1"}
    b = lgb.train({**fused, "guard_nonfinite": "skip_tree",
                   "guard_faults": "nonfinite_grad=2"},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    ref = lgb.train(fused, lgb.Dataset(X, label=y), num_boost_round=4)
    assert _trees(b) == _trees(ref)


def test_nonfinite_skip_tree_dart():
    X, y = _data()
    b = lgb.train({**PARAMS, "boosting": "dart",
                   "guard_nonfinite": "skip_tree",
                   "guard_faults": "nonfinite_grad=2"},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    assert b.num_trees() == 4
    assert np.all(np.isfinite(b.predict(X[:50])))


def test_nonfinite_clip_policy_finishes_finite():
    X, y = _data()
    b = lgb.train({**PARAMS, "guard_nonfinite": "clip",
                   "guard_faults": "nonfinite_grad=1"},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    assert np.all(np.isfinite(b.predict(X[:50])))


def test_guard_off_policy_unchecked():
    """off must not add any sentinel: a clean run's trees are identical to
    the default-guard run (the guard only acts on non-finite input)."""
    X, y = _data()
    b_off = lgb.train({**PARAMS, "guard_nonfinite": "off"},
                      lgb.Dataset(X, label=y), num_boost_round=4)
    b_on = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=4)
    assert _trees(b_off) == _trees(b_on)


# -- engine-level resume ------------------------------------------------
def test_engine_train_resume_auto_bit_consistent(tmp_path):
    """train(resume='auto') picks up the newest snapshot and finishes
    bit-identically to an uninterrupted run (bagging RNG restored from the
    sidecar; boost_from_average=false keeps the replay addition order)."""
    X, y = _data(600)
    out = str(tmp_path / "model.txt")
    p = {**PARAMS, "boost_from_average": False, "bagging_fraction": 0.7,
         "bagging_freq": 1, "output_model": out, "snapshot_freq": 2}
    lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    assert latest_snapshot(out)[2]["iteration"] == 4
    resumed = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume="auto")
    assert resumed.num_trees() == 8
    ref = lgb.train({k: v for k, v in p.items() if k != "snapshot_freq"},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    assert _trees(resumed) == _trees(ref)


def test_engine_resume_restores_early_stopping_state(tmp_path):
    """The early-stopping bests ride the sidecar: a resumed run counts
    patience from the recorded best instead of restarting it."""
    X, y = _data(600)
    Xv, yv = _data(200, seed=9)
    out = str(tmp_path / "model.txt")
    p = {**PARAMS, "boost_from_average": False, "output_model": out,
         "snapshot_freq": 1, "early_stopping_round": 3, "metric": "l2"}
    ds = lgb.Dataset(X, label=y)
    b1 = lgb.train(p, ds, num_boost_round=4,
                   valid_sets=[ds.create_valid(Xv, label=yv)])
    found = latest_snapshot(out)
    assert found is not None
    es = found[2].get("early_stop")
    assert es and es["best_score"], "sidecar must carry early-stop bests"
    resumed = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=8,
                        resume="auto",
                        valid_sets=[lgb.Dataset(X, label=y).create_valid(
                            Xv, label=yv)])
    ref = lgb.train({k: v for k, v in p.items() if k != "snapshot_freq"},
                    lgb.Dataset(X, label=y), num_boost_round=8,
                    valid_sets=[lgb.Dataset(X, label=y).create_valid(
                        Xv, label=yv)])
    assert resumed.best_iteration == ref.best_iteration
    assert _trees(resumed) == _trees(ref)


# -- SIGKILL + CLI auto-resume (the acceptance test) --------------------
def _cli(args, tmp_path, faults=""):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if faults:
        env["LAMBDAGAP_FAULTS"] = faults
    else:
        env.pop("LAMBDAGAP_FAULTS", None)
    return subprocess.run([sys.executable, "-m", "lambdagap_tpu", *args],
                          cwd=str(tmp_path), env=env, capture_output=True,
                          text=True, timeout=300)


def test_sigkill_mid_train_auto_resume_identical_model(tmp_path):
    """SIGKILL a CLI train mid-run (crash-at-iteration fault), rerun with
    resume=auto, and require the final model text to match the
    uninterrupted run's trees byte-for-byte."""
    X, y = _data(500, seed=3)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    args = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=6", "snapshot_freq=1", "bagging_fraction=0.7",
            "bagging_freq=1", "min_data_in_leaf=5", "verbose=1",
            "resume=auto"]
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path,
             faults="crash_at_iter=3")
    assert r.returncode == -9, f"expected SIGKILL, got {r.returncode}: " \
        f"{r.stdout}\n{r.stderr}"
    assert not (tmp_path / "m_crash.txt").exists()
    snaps = sorted(tmp_path.glob("m_crash.txt.snapshot_iter_*"))
    assert snaps, "crash must leave snapshots behind"

    r = _cli(args + ["output_model=m_crash.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resumed from snapshot" in r.stdout + r.stderr

    r = _cli(args + ["output_model=m_ref.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr

    resumed = (tmp_path / "m_crash.txt").read_text()
    ref = (tmp_path / "m_ref.txt").read_text()
    split = "end of trees"
    assert resumed.split(split)[0] == ref.split(split)[0], \
        "resumed model trees must be byte-identical to the uninterrupted run"


def test_sigkill_resume_sorted_layout_identical_model(tmp_path):
    """ISSUE-6 satellite: SIGKILL + resume=auto under tree_layout=sorted
    must stay byte-identical to an uninterrupted run. The sorted physical
    layout is rebuilt from scratch every tree (gradients change per
    iteration, the permutation restarts at identity), so nothing about it
    is — or needs to be — serialized in the snapshot."""
    X, y = _data(500, seed=7)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    args = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=6", "snapshot_freq=1", "bagging_fraction=0.7",
            "bagging_freq=1", "min_data_in_leaf=5", "verbose=1",
            "resume=auto", "tpu_fused_learner=1", "tree_layout=sorted"]
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path,
             faults="crash_at_iter=3")
    assert r.returncode == -9, f"expected SIGKILL, got {r.returncode}: " \
        f"{r.stdout}\n{r.stderr}"
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resumed from snapshot" in r.stdout + r.stderr

    r = _cli(args + ["output_model=m_ref.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    resumed = (tmp_path / "m_crash.txt").read_text()
    ref = (tmp_path / "m_ref.txt").read_text()
    split = "end of trees"
    assert resumed.split(split)[0] == ref.split(split)[0], \
        "sorted-layout resumed model must be byte-identical"


def test_sigkill_elastic_resume_different_device_count(tmp_path):
    """ISSUE-8 acceptance: SIGKILL a 4-device fused data-parallel CLI
    train mid-run, resume with ``resume=auto`` on a 2-device mesh, and
    require trees byte-identical to an uninterrupted run.

    The snapshot sidecar records the mesh + row-shard geometry
    (guard/snapshot.py capture_state); resume at a different width simply
    re-shards the per-row state over the new mesh — legal because fused
    data-parallel training is bit-identical across device counts on the
    quantized path (integer gradient levels sum exactly, so the histogram
    psum is width-invariant by construction; tools/multichip_gate.py
    gates it — the f32 path is only reduction-order-equal, where
    near-tied gains may legitimately resolve differently per width)."""
    X, y = _data(500, seed=11)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    base = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=6", "snapshot_freq=1", "min_data_in_leaf=5",
            "verbose=1", "resume=auto", "tree_learner=data",
            "tpu_fused_learner=1", "use_quantized_grad=true",
            "stochastic_rounding=false"]
    r = _cli(base + ["tpu_num_devices=4", "output_model=m_crash.txt"],
             tmp_path, faults="crash_at_iter=3")
    assert r.returncode == -9, f"expected SIGKILL, got {r.returncode}: " \
        f"{r.stdout}\n{r.stderr}"
    snaps = sorted(tmp_path.glob("m_crash.txt.snapshot_iter_*"))
    assert snaps, "crash must leave snapshots behind"
    # the sidecar carries the 4-device mesh + shard geometry
    from lambdagap_tpu.guard.snapshot import read_snapshot
    _, state = read_snapshot(str(snaps[-1]))
    assert state["mesh"]["n_devices"] == 4
    assert state["mesh"]["axes"] == ["data", "feature"]
    assert state["mesh"]["shape"] == [4, 1]
    assert state["mesh"]["n_loc"] * 4 == state["mesh"]["n_pad"]

    # resume at HALF the width
    r = _cli(base + ["tpu_num_devices=2", "output_model=m_crash.txt"],
             tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resumed from snapshot" in r.stdout + r.stderr
    assert "elastic resume" in r.stdout + r.stderr

    # uninterrupted reference (4-way; widths are bit-identical)
    r = _cli(base + ["tpu_num_devices=4", "output_model=m_ref.txt"],
             tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr

    resumed = (tmp_path / "m_crash.txt").read_text()
    ref = (tmp_path / "m_ref.txt").read_text()
    split = "end of trees"
    assert resumed.split(split)[0] == ref.split(split)[0], \
        "elastic-resumed trees must be byte-identical to the " \
        "uninterrupted run"


def test_cli_resume_skips_torn_final_snapshot(tmp_path):
    """A snapshot torn by the crash is rejected by its checksum and the
    previous good snapshot is used."""
    X, y = _data(300, seed=5)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    args = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=4", "snapshot_freq=1", "min_data_in_leaf=5",
            "verbose=1", "resume=auto", "output_model=m.txt"]
    r = _cli(args, tmp_path, faults="crash_at_iter=3,torn_snapshot=3")
    assert r.returncode == -9
    r = _cli(args, tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout + r.stderr
    assert "skipping invalid snapshot" in out
    assert "snapshot_iter_2" in out          # fell back to the older one
    assert "Resumed from snapshot" in out
    final = (tmp_path / "m.txt").read_text()
    assert final.count("Tree=") == 4         # still completed all rounds
