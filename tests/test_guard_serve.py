"""lambdagap_tpu.guard x serve — degradation-aware serving.

The acceptance invariant: under fault injection, EVERY submitted future
resolves — with a result, a ``ServeTimeout``, or an error — within its
deadline; nothing ever hangs a caller. Covers bounded-queue backpressure
(reject and block), pre-dispatch deadline shedding, swap-failure rollback
with the circuit breaker, and the OK/DEGRADED/DRAINING health state.
"""
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lambdagap_tpu as lgb
from lambdagap_tpu.guard.degrade import (CircuitBreaker, HealthMonitor,
                                         ServeOverloaded, ServeTimeout,
                                         SwapFailed, SwapRejected)
from lambdagap_tpu.serve.batcher import MicroBatcher


def _train(rounds=6, seed=0, **extra):
    X, y = make_classification(800, 10, n_informative=5, random_state=seed)
    X = X.astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tpu_fast_predict_rows": 0, **extra}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds), X


# -- circuit breaker unit -----------------------------------------------
def test_circuit_breaker_states():
    t = [0.0]
    br = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=lambda: t[0])
    assert br.state() == "closed" and br.allow()
    br.record_failure()
    assert br.state() == "closed"
    br.record_failure()
    assert br.state() == "open" and not br.allow()
    t[0] = 11.0
    assert br.state() == "half_open"
    assert br.allow()                    # the probe
    assert not br.allow()                # only one probe per cooldown
    br.record_success()
    assert br.state() == "closed" and br.allow()


def test_breaker_disabled_at_zero_threshold():
    br = CircuitBreaker(threshold=0)
    for _ in range(10):
        br.record_failure()
    assert br.state() == "closed" and br.allow()


def test_health_monitor_transitions():
    br = CircuitBreaker(threshold=1)
    h = HealthMonitor(breaker=br)
    assert h.state() == "ok"
    h.note_error()
    assert h.state() == "degraded"
    h.note_ok()
    assert h.state() == "ok"
    br.record_failure()
    assert h.state() == "degraded"       # breaker open
    br.record_success()
    assert h.state() == "ok"
    h.set_draining()
    assert h.state() == "draining"


# -- bounded queue / backpressure ---------------------------------------
def _echo_batcher(delay=0.0, **kw):
    def run(batch):
        if delay:
            time.sleep(delay)
        for r in batch:
            r.future.set_result(r.x.sum())
    return MicroBatcher(run, max_batch=4, max_delay_ms=1.0, workers=1, **kw)


def test_reject_backpressure_raises_and_accepted_all_resolve():
    mb = _echo_batcher(delay=0.05, max_queue=2, backpressure="reject")
    futures, rejected = [], 0
    try:
        for i in range(50):
            try:
                futures.append(mb.submit(np.ones((1, 4), np.float32)))
            except ServeOverloaded:
                rejected += 1
    finally:
        mb.close()
    assert rejected > 0, "a 2-deep queue must reject under burst"
    for f in futures:
        assert f.result(timeout=10) == 4.0   # every accepted future resolves


def test_block_backpressure_never_rejects():
    mb = _echo_batcher(delay=0.01, max_queue=2, backpressure="block")
    futures = [mb.submit(np.ones((1, 4), np.float32)) for _ in range(30)]
    for f in futures:
        assert f.result(timeout=10) == 4.0
    mb.close()


# -- deadlines ----------------------------------------------------------
def test_expired_requests_shed_with_serve_timeout():
    """With a slow dispatcher and a short deadline, queued requests time
    out BEFORE dispatch and resolve with ServeTimeout promptly."""
    dispatched = []

    def run(batch):
        dispatched.extend(batch)
        time.sleep(0.15)
        for r in batch:
            r.future.set_result(1.0)

    mb = MicroBatcher(run, max_batch=1, max_delay_ms=0.0, workers=1,
                      timeout_ms=50.0)
    futures = [mb.submit(np.ones((1, 2), np.float32)) for _ in range(8)]
    t0 = time.perf_counter()
    outcomes = []
    for f in futures:
        try:
            outcomes.append(("ok", f.result(timeout=10)))
        except ServeTimeout:
            outcomes.append(("timeout", None))
    elapsed = time.perf_counter() - t0
    mb.close()
    kinds = [k for k, _ in outcomes]
    assert "ok" in kinds and "timeout" in kinds
    # shed requests never reached the dispatcher
    assert len(dispatched) < len(futures)
    # and every future resolved without waiting for 8 full dispatches
    assert elapsed < 8 * 0.15


def test_server_timeout_ms_end_to_end():
    """serve_timeout_ms + a slowed dispatch (fault point): some requests
    serve, the rest shed with ServeTimeout — all resolve, none hang."""
    b, X = _train(guard_faults="serve_dispatch_slow_ms=120")
    s = b.as_server(buckets=(8,), timeout_ms=40.0, max_delay_ms=0.0,
                    workers=1)
    try:
        futures = [s.submit(X[i]) for i in range(6)]
        resolved = 0
        for f in futures:
            try:
                f.result(timeout=10)
                resolved += 1
            except ServeTimeout:
                pass
        assert resolved >= 1
        snap = s.stats_snapshot()
        assert snap["timeouts"] + resolved == 6
    finally:
        s.close()


# -- dispatch faults + health -------------------------------------------
def test_dispatch_failures_degrade_then_recover():
    b, X = _train(guard_faults="serve_dispatch_fail=2")
    s = b.as_server(buckets=(8,), max_delay_ms=0.0, workers=1)
    try:
        assert s.health.state() == "ok"
        failures = 0
        for i in range(2):
            fut = s.submit(X[i])
            with pytest.raises(Exception):
                fut.result(timeout=10)
            failures += 1
        assert failures == 2
        assert s.health.state() == "degraded"
        assert s.stats_snapshot()["health"]["state"] == "degraded"
        # faults exhausted: the next dispatch succeeds and health recovers
        out = s.submit(X[0]).result(timeout=10)
        assert np.all(np.isfinite(out.values))
        assert s.health.state() == "ok"
    finally:
        s.close()
    assert s.health.state() == "draining"
    assert s.stats_snapshot()["errors"] >= 1


def test_prometheus_exposes_health_and_shed_counters():
    b, X = _train()
    with b.as_server(buckets=(8,)) as s:
        s.predict(X[:8])
        live = s.prometheus()
    assert 'lambdagap_serve_health{state="ok"} 1' in live
    text = s.prometheus()                # post-close: draining
    assert 'lambdagap_serve_health{state="draining"} 1' in text
    assert "lambdagap_serve_timeouts_total 0" in text
    assert "lambdagap_serve_rejected_total 0" in text
    assert "lambdagap_serve_swap_failures_total 0" in text


# -- swap failure rollback + breaker ------------------------------------
def test_swap_failure_rolls_back_and_serving_continues(tmp_path):
    b, X = _train()
    ref = b.predict(X[:600])[:16]        # >512 rows -> device path (serve-parity)
    s = b.as_server(buckets=(8, 16), swap_breaker=3)
    try:
        with pytest.raises(SwapFailed):
            s.swap(str(tmp_path / "missing_model.txt"))
        assert s.generation == 0          # rollback: old forest kept
        got = s.predict(X[:16])
        assert np.array_equal(got, ref)
        snap = s.stats_snapshot()
        assert snap["swap_failures"] == 1
        assert snap["swaps"] == 0
    finally:
        s.close()


def test_swap_breaker_opens_after_consecutive_failures(tmp_path):
    b, X = _train()
    b2, _ = _train(rounds=4, seed=5)
    good = str(tmp_path / "good.txt")
    b2.save_model(good)
    s = b.as_server(buckets=(8,), swap_breaker=2)
    try:
        for _ in range(2):
            with pytest.raises(SwapFailed):
                s.swap(str(tmp_path / "nope.txt"))
        assert s.health.state() == "degraded"
        # circuit open: swaps now rejected FAST without touching the loader
        with pytest.raises(SwapRejected):
            s.swap(good)
        assert s.stats_snapshot()["health"]["swap_breaker"] == "open"
        # requests keep being served while degraded
        assert np.all(np.isfinite(s.predict(X[:8])))
        # cooldown elapsed -> half-open probe succeeds -> breaker closes
        s._swap.breaker.cooldown_s = 0.0
        gen = s.swap(good)
        assert gen == 1
        assert s.health.state() == "ok"
        assert s.stats_snapshot()["swaps"] == 1
    finally:
        s.close()


def test_futures_resolve_during_swap_failure_storm(tmp_path):
    """Concurrent clients + a failing swap loop: every submitted future
    resolves; no response mixes generations."""
    b, X = _train()
    ref = b.predict(X[:600])[:64]        # device-path reference
    s = b.as_server(buckets=(1, 8, 64), max_delay_ms=1.0, swap_breaker=0)
    errors, done = [], []
    stop = threading.Event()

    def client(cid):
        i = cid
        while not stop.is_set():
            try:
                r = s.submit(X[i % 64]).result(timeout=30)
                assert np.array_equal(r.values, ref[i % 64:i % 64 + 1])
                done.append(i)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return
            i += 3
    threads = [threading.Thread(target=client, args=(c,)) for c in range(3)]
    for t in threads:
        t.start()
    for _ in range(4):
        with pytest.raises(SwapFailed):
            s.swap(str(tmp_path / "missing.txt"))
        time.sleep(0.02)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    s.close()
    assert not errors
    assert len(done) > 0
    assert s.stats_snapshot()["swap_failures"] == 4
    assert s.generation == 0


def test_serve_loop_survives_swap_failure(tmp_path):
    from lambdagap_tpu.serve import serve_loop
    import io
    b, X = _train()
    lines = ["\t".join(f"{v:.6g}" for v in X[0]),
             f"swap={tmp_path}/missing.txt",
             "\t".join(f"{v:.6g}" for v in X[1])]
    out = io.StringIO()
    s = b.as_server(buckets=(1, 8))
    try:
        n = serve_loop(s, lines, out)
    finally:
        s.close()
    assert n == 2                        # both requests served, swap logged
    assert s.stats_snapshot()["swap_failures"] == 1
