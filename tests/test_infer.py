"""lambdagap_tpu.infer — compiled forest artifacts + traversal engine.

The ISSUE-16 acceptance surface: ``predict_engine=compiled`` is
bit-identical (``array_equal``, never closeness) to the sequential scan
oracle across the full parity matrix — ragged row tiles, NaN/default-left
routing, zero-as-missing, multi-word categorical bitsets, multiclass
routing, linear leaves, early-stop margins, mixed constant/linear
forests — plus the artifact contract: content-addressed round-trip,
hash-mismatch rejection (loud local-compile fallback, never a wrong-model
serve), exact dead-branch pruning, same-structure tree merging, and
cross-model padding buckets (ModelPack) matching each member cache
bit-for-bit through every serve path.
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.infer import (ArtifactMismatch, ArtifactStore,
                                 ForestArtifact, compile_forest,
                                 source_key_of)

# tpu_fast_predict_rows=0 drops the native small-batch shortcut to its
# 512-row floor; all parity predicts use >512 rows so the engine under
# test (not the host reference) answers
DEVICE_PARAMS = {"verbose": -1, "tpu_fast_predict_rows": 0,
                 "predict_engine": "compiled"}


def _flip(b, engine):
    gb = b._booster
    gb.config.predict_engine = engine
    gb.invalidate_predict_cache()
    return gb


def _assert_engine_parity(b, X, **predict_kw):
    """compiled vs the sequential scan oracle: exact equality."""
    _flip(b, "compiled")
    got = b.predict(X, **predict_kw)
    _flip(b, "scan")
    ref = b.predict(X, **predict_kw)
    _flip(b, "compiled")
    assert got.shape == ref.shape
    assert np.array_equal(got, ref), \
        f"compiled != scan (max diff {np.nanmax(np.abs(got - ref))})"
    return got


def _train(params, X, y, rounds=8, cats="auto"):
    return lgb.train({**DEVICE_PARAMS, **params},
                     lgb.Dataset(X, label=y, categorical_feature=cats),
                     num_boost_round=rounds)


def _data(rows=700, feats=10, seed=0, nan_col=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(rows, feats).astype(np.float32)
    if nan_col is not None:
        X[::7, nan_col] = np.nan          # exercises default-left routing
    y = (X[:, 0] + 0.5 * X[:, 1] * np.nan_to_num(X[:, 2]) > 0)
    return X, y.astype(np.float32)


# -- engine parity matrix ------------------------------------------------
def test_parity_binary_nan_default_left():
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15}, X, y)
    _assert_engine_parity(b, X)
    _assert_engine_parity(b, X, raw_score=True)


@pytest.mark.parametrize("row_block", [32, 100, 256])
def test_parity_ragged_row_tiles(row_block):
    """Odd row counts vs the traversal kernel's row_block grid: padding
    rows are sliced off exactly, whatever the remainder."""
    X, y = _data(rows=601)
    b = _train({"objective": "binary", "num_leaves": 15,
                "infer_row_block": row_block}, X, y)
    _assert_engine_parity(b, X)           # 601 % row_block != 0 for all
    _assert_engine_parity(b, X[:599])


def test_parity_zero_as_missing():
    X, y = _data(nan_col=None)
    X[::5, 1] = 0.0
    X[::3, 0] = 0.0
    b = _train({"objective": "binary", "num_leaves": 15,
                "zero_as_missing": True}, X, y)
    _assert_engine_parity(b, X)


def test_parity_categorical_multiword_bitsets():
    """A 70-category feature needs a 3-word (u32) bitset per node — the
    artifact's deduped cat_table and the kernel's word/bit gather must
    route identically to the scan oracle."""
    rng = np.random.RandomState(3)
    X, y = _data(seed=3)
    X[:, 0] = rng.randint(0, 70, size=X.shape[0]).astype(np.float32)
    y = ((X[:, 0].astype(int) % 5 < 2) ^ (X[:, 1] > 0)).astype(np.float32)
    b = _train({"objective": "binary", "num_leaves": 31,
                "min_data_per_group": 5}, X, y, rounds=10, cats=[0])
    art = compile_forest(b._booster)
    assert art.meta["cat_words"] >= 3     # the multi-word case, really
    _assert_engine_parity(b, X)


def test_parity_multiclass_routing():
    rng = np.random.RandomState(4)
    X = rng.randn(700, 8).astype(np.float32)
    X[::9, 2] = np.nan
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5)
    b = _train({"objective": "multiclass", "num_class": 3,
                "num_leaves": 15}, X, y, rounds=9)
    out = _assert_engine_parity(b, X)
    assert out.shape == (700, 3)
    _assert_engine_parity(b, X, raw_score=True)


def test_parity_linear_leaves():
    X, y = _data()
    yr = X[:, 0] * 2.0 + np.nan_to_num(X[:, 3]) + 0.1 * y
    b = _train({"objective": "regression", "num_leaves": 7,
                "linear_tree": True}, X, yr)
    assert compile_forest(b._booster).meta["has_linear"]
    _assert_engine_parity(b, X)


def test_parity_mixed_constant_linear_forest():
    """A forest mixing linear-leaf trees and constant trees (the shape a
    linear_tree continuation of a constant model produces)."""
    X, y = _data()
    yr = X[:, 0] - 0.5 * X[:, 1]
    b_lin = _train({"objective": "regression", "num_leaves": 7,
                    "linear_tree": True}, X, yr, rounds=4)
    b_const = _train({"objective": "regression", "num_leaves": 7}, X, yr,
                     rounds=4)
    gb = b_lin._booster
    gb.models = list(gb.host_models) + list(b_const._booster.host_models)
    gb.iter_ = len(gb.models)
    gb.invalidate_predict_cache()
    assert compile_forest(gb).meta["has_linear"]
    _assert_engine_parity(b_lin, X)


def test_parity_early_stop_margins():
    """pred_early_stop replays at the exact same tree boundaries as the
    scan engine — margins checked at (i % freq) == 0, same top-2 rule."""
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15,
                "pred_early_stop": True, "pred_early_stop_freq": 3,
                "pred_early_stop_margin": 0.5}, X, y, rounds=12)
    _assert_engine_parity(b, X)
    rng = np.random.RandomState(5)
    X3 = rng.randn(700, 8).astype(np.float32)
    y3 = (X3[:, 0] > 0).astype(int) + (X3[:, 1] > 0.5)
    b3 = _train({"objective": "multiclass", "num_class": 3,
                 "num_leaves": 15, "pred_early_stop": True,
                 "pred_early_stop_freq": 2,
                 "pred_early_stop_margin": 1.5}, X3, y3, rounds=9)
    _assert_engine_parity(b3, X3)


def test_leaf_index_engine_invariant():
    """predict(pred_leaf=True) under the compiled engine routes through
    the tensor leaf path — leaf ids are engine-invariant by contract."""
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15}, X, y)
    _flip(b, "compiled")
    got = b.predict(X, pred_leaf=True)
    _flip(b, "scan")
    ref = b.predict(X, pred_leaf=True)
    assert np.array_equal(got, ref)


# -- the artifact: compile, round-trip, hash admission -------------------
def test_artifact_roundtrip_and_content_hash():
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15}, X, y)
    art = compile_forest(b._booster)
    payload = art.to_bytes()
    back = ForestArtifact.from_bytes(payload, expect_hash=art.hash)
    assert back.hash == art.hash
    assert back.meta == art.meta
    assert sorted(back.buffers) == sorted(art.buffers)
    for k in art.buffers:
        assert np.array_equal(back.buffers[k], art.buffers[k])
        assert back.buffers[k].dtype == art.buffers[k].dtype
    # deterministic: re-serialization is byte-identical
    assert back.to_bytes() == payload
    # same source, fresh compile -> same source key AND same content hash
    art2 = compile_forest(b._booster)
    assert art2.source_key == art.source_key
    assert art2.hash == art.hash


def test_artifact_mismatch_is_loud():
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15}, X, y)
    payload = compile_forest(b._booster).to_bytes()
    with pytest.raises(ArtifactMismatch):
        ForestArtifact.from_bytes(payload, expect_hash="0" * 64)
    torn = payload[: len(payload) - 8]
    with pytest.raises(ArtifactMismatch):
        ForestArtifact.from_bytes(torn)
    flipped = bytearray(payload)
    flipped[-3] ^= 0x40
    with pytest.raises(ArtifactMismatch):
        ForestArtifact.from_bytes(bytes(flipped))
    with pytest.raises(ArtifactMismatch):
        ForestArtifact.from_bytes(b"NOTANARTIFACT" + payload)


def test_artifact_store_admission():
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15}, X, y)
    gb = b._booster
    art = compile_forest(gb)
    store = ArtifactStore()
    # a corrupt admission must not mutate the store
    bad = bytearray(art.to_bytes())
    bad[-1] ^= 0xFF
    with pytest.raises(ArtifactMismatch):
        store.admit_bytes(bytes(bad))
    assert len(store) == 0
    got = store.admit_bytes(art.to_bytes(), expect_hash=art.hash)
    assert got.hash == art.hash
    assert store.get(source_key_of(gb, 0, -1)).hash == art.hash
    assert store.get_by_hash(art.hash) is not None
    assert store.get("no-such-source-key") is None


# -- pruning and merging -------------------------------------------------
def test_prune_dominated_branch_exact():
    """A split dominated by an ancestor on the same feature (x <= t1 then
    x <= t2 with t2 > t1) has an unreachable arm; the compiler bypasses
    the decided node WITHOUT renumbering leaves, and routing stays
    bit-identical to the unpruned scan oracle."""
    X, y = _data(feats=4, nan_col=None)
    b = _train({"objective": "binary", "num_leaves": 8,
                "num_trees": 2}, X, y, rounds=2)
    gb = b._booster
    base = compile_forest(gb)
    # force domination: put every split on feature 0 and raise every
    # non-root threshold ABOVE the max, so each inner node's right arm is
    # reachable only through a root split that already decided
    # x0 <= threshold_root < new threshold
    text = gb.save_model_to_string()
    out_lines = []
    for line in text.split("\n"):
        if line.startswith("threshold="):
            vals = [float(v) for v in line.split("=", 1)[1].split()]
            vals = [vals[0]] + [abs(v) + 1e6 for v in vals[1:]]
            line = "threshold=" + " ".join(repr(v) for v in vals)
        elif line.startswith("split_feature="):
            n = len(line.split("=", 1)[1].split())
            line = "split_feature=" + " ".join(["0"] * n)
        out_lines.append(line)
    b2 = lgb.Booster(model_str="\n".join(out_lines),
                     params=dict(DEVICE_PARAMS))
    gb2 = b2._booster
    art = compile_forest(gb2)
    assert art.meta["nodes_pruned"] > 0
    assert base.meta["nodes_pruned"] == 0   # the real model had no dead arm
    _assert_engine_parity(b2, X)
    # pruning off: same outputs, zero pruned
    gb2.config.infer_prune = False
    gb2.invalidate_predict_cache()
    assert compile_forest(gb2).meta["nodes_pruned"] == 0
    _assert_engine_parity(b2, X)


def test_merge_tiled_trees_shares_traversal():
    """An iteration-tiled forest (the bench_serve shape) collapses to the
    base structure count: merged trees share one traversal group while
    keeping their own leaf values — outputs stay exact."""
    X, y = _data()
    b = _train({"objective": "regression", "num_leaves": 15}, X,
               X[:, 0] - X[:, 1], rounds=5)
    gb = b._booster
    gb.models = list(gb.host_models) * 6          # 30 trees, 5 structures
    gb.iter_ = len(gb.models)
    gb.invalidate_predict_cache()
    art = compile_forest(gb)
    assert art.meta["num_trees"] == 30
    assert art.meta["num_groups"] == 30 - art.meta["trees_merged"]
    assert art.meta["trees_merged"] >= 25         # 5 unique structures
    _assert_engine_parity(b, X)
    gb.config.infer_merge_trees = False
    gb.invalidate_predict_cache()
    assert compile_forest(gb).meta["num_groups"] == 30
    _assert_engine_parity(b, X)


def test_quant_u8_overflow_errors_instead_of_widening():
    X, y = _data(rows=1500)
    b = _train({"objective": "regression", "num_leaves": 31}, X,
               np.sin(np.nan_to_num(X).sum(axis=1)), rounds=30)
    gb = b._booster
    assert compile_forest(gb).meta["thr_bits"] == 16   # auto widened
    gb.config.infer_quant = "u8"
    gb.invalidate_predict_cache()
    with pytest.raises(ValueError):
        compile_forest(gb)


# -- palette edges (ISSUE 17) -------------------------------------------
def test_palette_u16_widened_forest_exact_and_admitted():
    """>256 unique thresholds: the palette auto-widens u8 -> u16 and the
    widened codes must still route bit-identically to the scan oracle —
    AND the widened artifact must survive the hash-verified store
    admission round-trip (the fleet path serves the u16 palette too)."""
    X, y = _data(rows=1500)
    b = _train({"objective": "regression", "num_leaves": 31}, X,
               np.sin(np.nan_to_num(X).sum(axis=1)), rounds=30)
    gb = b._booster
    art = compile_forest(gb)
    assert art.meta["thr_bits"] == 16
    assert art.buffers["node_thr"].dtype == np.uint16
    assert len(art.buffers["thr_table"]) > 256     # the widening reason
    _assert_engine_parity(b, X)
    store = ArtifactStore()
    got = store.admit_bytes(art.to_bytes(), expect_hash=art.hash)
    assert got.hash == art.hash
    assert np.array_equal(got.buffers["node_thr"],
                          art.buffers["node_thr"])


def test_palette_constant_only_forest_exact_and_admitted():
    """Every tree a single constant leaf (min_data_in_leaf > rows kills
    all splits): zero internal nodes, an empty threshold palette — the
    degenerate artifact must compile, round-trip the store, and predict
    bit-identically to the scan oracle."""
    X, y = _data()
    b = _train({"objective": "regression", "num_leaves": 7,
                "min_data_in_leaf": 10_000}, X, X[:, 0], rounds=3)
    gb = b._booster
    assert all(t.num_internal == 0 for t in gb.host_models)
    art = compile_forest(gb)
    # splitless rounds may stop boosting early; whatever trained, every
    # tree is a stump and the artifact must carry them all
    assert art.meta["num_trees"] == len(gb.host_models) >= 1
    got = _assert_engine_parity(b, X)
    assert np.ptp(got) == 0                       # constant forest output
    store = ArtifactStore()
    assert store.admit_bytes(art.to_bytes(),
                             expect_hash=art.hash).hash == art.hash


def test_palette_all_dead_branches_prune_to_root():
    """Every non-root split shares the root's feature AND threshold, so
    every one of them is decided by the root: the compiler bypasses ALL
    of them (nodes_pruned == num_internal - 1 per tree) and the pruned
    skeleton still routes bit-identically to the UNpruned scan oracle."""
    X, y = _data(feats=4, nan_col=None)
    b = _train({"objective": "binary", "num_leaves": 8}, X, y, rounds=2)
    gb = b._booster
    text = gb.save_model_to_string()
    out_lines = []
    for line in text.split("\n"):
        if line.startswith("threshold="):
            vals = line.split("=", 1)[1].split()
            line = "threshold=" + " ".join([vals[0]] * len(vals))
        elif line.startswith("split_feature="):
            n = len(line.split("=", 1)[1].split())
            line = "split_feature=" + " ".join(["0"] * n)
        elif line.startswith("decision_type="):
            # uniform numerical/default-left: a default-direction mismatch
            # with the ancestor keeps a same-threshold node LIVE (the
            # missing path is this node's to decide), which is not the
            # edge under test
            n = len(line.split("=", 1)[1].split())
            line = "decision_type=" + " ".join(["2"] * n)
        out_lines.append(line)
    b2 = lgb.Booster(model_str="\n".join(out_lines),
                     params=dict(DEVICE_PARAMS))
    gb2 = b2._booster
    art = compile_forest(gb2)
    expect = sum(t.num_internal - 1 for t in gb2.host_models
                 if t.num_internal > 0)
    assert art.meta["nodes_pruned"] == expect > 0
    _assert_engine_parity(b2, X)
    store = ArtifactStore()
    assert store.admit_bytes(art.to_bytes(),
                             expect_hash=art.hash).hash == art.hash


# -- cross-model packing (ModelPack) ------------------------------------
def _cache(b, **kw):
    from lambdagap_tpu.serve.cache import CompiledForestCache
    return CompiledForestCache(b._booster, **kw)


def test_pack_cross_model_bit_identity():
    """Mixed per-tenant batches through ONE packed executable match each
    member cache serving its rows alone — exactly, including mixed
    num_class and mixed feature widths across members."""
    from lambdagap_tpu.serve.cache import ModelPack
    X, y = _data()
    b1 = _train({"objective": "binary", "num_leaves": 15}, X, y)
    b2 = _train({"objective": "regression", "num_leaves": 7}, X[:, :6],
                X[:, 0] * 2.0, rounds=5)
    rng = np.random.RandomState(9)
    X3 = rng.randn(700, 8).astype(np.float32)
    y3 = (X3[:, 0] > 0).astype(int) + (X3[:, 1] > 0.5)
    b3 = _train({"objective": "multiclass", "num_class": 3,
                 "num_leaves": 15}, X3, y3, rounds=6)
    caches = {"a": _cache(b1), "b": _cache(b2), "c": _cache(b3)}
    pack = ModelPack(caches, buckets=(8, 64, 512))
    parts = [("a", X[:37], False), ("b", X[37:60, :6], False),
             ("c", X3[:25], False), ("a", X[60:61], True)]
    outs = pack.predict_mixed(parts)
    for (name, Xp, raw), got in zip(parts, outs):
        ref = caches[name].predict(Xp, raw_score=raw)
        assert np.array_equal(got, ref), f"pack != solo for {name!r}"


def test_pack_rejects_early_stop_members():
    from lambdagap_tpu.serve.cache import ModelPack
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15,
                "pred_early_stop": True, "pred_early_stop_freq": 2}, X, y)
    with pytest.raises(ValueError):
        ModelPack({"es": _cache(b)})


# -- serve paths: registry / router / TCP frontend ----------------------
def test_compiled_engine_through_every_serve_path():
    """The same rows through the server, the registry route, the router,
    and the socket frontend — all bit-identical to the compiled cache
    (which test_pack/... pins to the scan oracle)."""
    from lambdagap_tpu.serve import (FrontendClient, LocalReplica, Router,
                                     ServeFrontend)
    X, y = _data()
    b = _train({"objective": "binary", "num_leaves": 15}, X, y)
    ref = _cache(b).predict(X[:111])
    with b.as_server(buckets=(8, 64), warmup=False) as s:
        assert s.registry.entry("default").engine == "compiled"
        got = np.concatenate([s.predict(X[i:i + 37])
                              for i in range(0, 111, 37)])
        assert np.array_equal(got, ref)
        r = Router([LocalReplica("r0", s)])
        got_r = np.concatenate([r.predict(X[i:i + 37])
                                for i in range(0, 111, 37)])
        assert np.array_equal(got_r, ref)
        with ServeFrontend(s) as fe:
            with FrontendClient("127.0.0.1", fe.port) as cli:
                got_f = np.concatenate([cli.predict(X[i:i + 37])
                                        for i in range(0, 111, 37)])
                assert np.array_equal(got_f, ref)
                # the artifact plane over the wire round-trips exactly
                payload = cli.fetch_artifact()
                h = s.registry.get("default").artifact_hash
                assert cli.push_artifact(payload, expect_hash=h) == h
                with pytest.raises(ArtifactMismatch):
                    cli.push_artifact(payload[:-4])


def test_fleet_shares_one_compile_by_hash():
    """Replica B admits A's artifact, then places the model: B's build is
    a shared admission, not a second compile — and serves bit-identically
    to A. A corrupt admission raises and the subsequent build falls back
    to a loud LOCAL compile (never a wrong-model serve)."""
    from lambdagap_tpu.serve import ForestServer
    X, y = _data()
    b_model = _train({"objective": "binary", "num_leaves": 15}, X, y)
    b_boot = _train({"objective": "binary", "num_leaves": 7}, X, y,
                    rounds=2)
    A = ForestServer(b_model, warmup=False)
    try:
        payload = A.artifact_bytes()
        h = A.registry.get("default").artifact_hash
        assert A.stats.snapshot()["cache"]["compiles_local"] == 1
        assert h in A.registry.snapshot()["models"]["default"][
            "artifact_hash"]
        B = ForestServer(b_boot, warmup=False)
        try:
            with pytest.raises(ArtifactMismatch):
                B.admit_artifact(payload, expect_hash="f" * 64)
            assert B.admit_artifact(payload, expect_hash=h) == h
            B.add_model("m1", b_model._booster)
            snap = B.stats.snapshot()["cache"]
            assert snap["compiles_shared"] == 1     # the admitted one
            assert snap["compiles_local"] == 1      # only B's boot model
            assert B.registry.get("m1").artifact_hash == h
            assert np.array_equal(B.predict(X[:64], model="m1"),
                                  A.predict(X[:64]))
        finally:
            B.close()
    finally:
        A.close()


def test_packed_serve_dispatches_once_per_mixed_batch():
    """serve_pack_models: a mixed 3-tenant batch runs ONE packed dispatch
    and every tenant's rows match its solo cache exactly."""
    from lambdagap_tpu.serve import ForestServer
    X, y = _data()
    b1 = _train({"objective": "binary", "num_leaves": 15,
                 "serve_pack_models": True}, X, y)
    b2 = _train({"objective": "binary", "num_leaves": 7}, X, 1.0 - y,
                rounds=4)
    b3 = _train({"objective": "regression", "num_leaves": 7}, X,
                X[:, 0], rounds=4)
    s = ForestServer(b1, warmup=False, max_delay_ms=30.0, workers=1)
    try:
        s.add_model("t2", b2._booster)
        s.add_model("t3", b3._booster)
        futs = [s.submit(X[:13]), s.submit(X[13:20], model="t2"),
                s.submit(X[20:31], model="t3")]
        outs = [f.result(30.0) for f in futs]
        snap = s.stats_snapshot()
        assert snap["cache"]["packed_dispatches"] >= 1
        assert np.array_equal(outs[0].values,
                              s.registry.get("default").predict(X[:13]))
        assert np.array_equal(outs[1].values,
                              s.registry.get("t2").predict(X[13:20]))
        assert np.array_equal(outs[2].values,
                              s.registry.get("t3").predict(X[20:31]))
    finally:
        s.close()
