"""Sorted-leaf physical layout (tree_layout) + the Pallas histogram kernel.

The ISSUE-6 acceptance surface, all runnable on CPU in tier-1:

- ``tree_layout=sorted`` must be bit-identical to the gather oracle —
  same rows through the same arithmetic in the same order — across ragged
  leaf slices, bagging masks, categorical splits, EFB-bundled features,
  the quantized path, and both learners (host-serial and fused).
- The Pallas kernel (the TPU default since ``tpu_hist_impl=auto``
  graduated it) runs here in interpret mode: exact-reference parity for
  the int32 quantized path, split-precision tolerance for f32, in-kernel
  masking of ragged tails whose rows carry junk (a sorted window running
  into the next leaf), and layout invariance (gathered block == contiguous
  pre-sorted block, bit for bit).
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.config import Config


def _trees(booster) -> str:
    return booster.model_to_string().split("end of trees")[0]


def _data(n=900, d=8, seed=11, cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    if cat:
        X[:, 0] = rng.randint(0, 9, n)
    y = (X[:, 1] + np.sin(X[:, 2] * 2)
         + ((X[:, 0] % 3) if cat else X[:, 3]) * 0.5 + 0.1 * rng.randn(n))
    return X, y


def _train(X, y, layout, extra=None, rounds=4, cat=False):
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 10, "learning_rate": 0.1, "verbose": -1,
              "tpu_fused_learner": "1", "tpu_hist_impl": "onehot",
              "tree_layout": layout}
    if extra:
        params.update(extra)
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=([0] if cat else "auto"),
                     params=params)
    return lgb.train(params, ds, num_boost_round=rounds)


# -- sorted vs gather: bit-identical trees ------------------------------
@pytest.mark.parametrize("extra", [
    None,                             # ragged leaf slices happen on every
    {"max_depth": 3, "lambda_l1": 0.5, "lambda_l2": 2.0},   # config: 900
    {"bagging_fraction": 0.7, "bagging_freq": 1},  # rows never tile W
])
def test_fused_sorted_matches_gather(extra):
    X, y = _data()
    bg = _train(X, y, "gather", extra)
    bs = _train(X, y, "sorted", extra)
    assert _trees(bg) == _trees(bs)
    assert np.array_equal(bg.predict(X[:100]), bs.predict(X[:100]))


def test_fused_sorted_matches_gather_categorical():
    X, y = _data(cat=True)
    bg = _train(X, y, "gather", cat=True)
    bs = _train(X, y, "sorted", cat=True)
    assert _trees(bg) == _trees(bs)


def test_fused_sorted_matches_gather_efb_bundled():
    """EFB-active dataset: the sorted buffer holds BUNDLED columns and the
    partition rank-decodes the split feature out of its bundle column from
    the sorted window."""
    rng = np.random.RandomState(0)
    cols = []
    for c in (8, 6, 5, 7):
        k = rng.randint(0, c, 1400)
        blk = np.zeros((1400, c))
        blk[np.arange(1400), k] = 1.0
        cols.append(blk)
    X = np.column_stack(cols + [rng.randn(1400, 2)])
    y = X[:, 0] * 0.5 - X[:, 9] * 0.3 + X[:, -2] + 0.05 * rng.randn(1400)
    extra = {"min_data_in_bin": 1, "enable_bundle": True}
    bg = _train(X, y, "gather", extra, rounds=5)
    bs = _train(X, y, "sorted", extra, rounds=5)
    assert bs._booster.learner.bundled, "EFB bundle did not form"
    assert _trees(bg) == _trees(bs)


def test_fused_sorted_matches_gather_quantized():
    """int8-quantized path: the (g_q, h_q) levels ride the sorted payload;
    identical RNG keys -> identical levels -> identical integer sums, so
    the two layouts agree exactly (well inside the documented quantization
    tolerance vs full precision)."""
    X, y = _data()
    extra = {"use_quantized_grad": True, "num_grad_quant_bins": 16}
    bg = _train(X, y, "gather", extra)
    bs = _train(X, y, "sorted", extra)
    assert _trees(bg) == _trees(bs)


def test_fused_sorted_matches_gather_quantized_bagged():
    X, y = _data()
    extra = {"use_quantized_grad": True, "num_grad_quant_bins": 16,
             "bagging_fraction": 0.6, "bagging_freq": 1}
    bg = _train(X, y, "gather", extra)
    bs = _train(X, y, "sorted", extra)
    assert _trees(bg) == _trees(bs)


def test_serial_sorted_matches_gather():
    X, y = _data()
    extra = {"tpu_fused_learner": "0",
             "bagging_fraction": 0.7, "bagging_freq": 1}
    bg = _train(X, y, "gather", extra)
    bs = _train(X, y, "sorted", extra)
    assert _trees(bg) == _trees(bs)


def test_fused_data_parallel_sorted_matches_gather():
    """The fused data-parallel learner builds the sorted buffer with a
    shard_map pre-pass; the per-split apply is shard-local."""
    X, y = _data(n=1200)
    extra = {"tree_learner": "data", "enable_bundle": False}
    bg = _train(X, y, "gather", extra)
    bs = _train(X, y, "sorted", extra)
    assert _trees(bg) == _trees(bs)


def test_feature_parallel_opts_out_of_sorted():
    """The fused feature-parallel learner cannot decode the winning
    column from the sorted window (it lives on another shard): explicit
    opt-out, training still works."""
    X, y = _data(n=800)
    b = _train(X, y, "sorted", {"tree_learner": "feature"}, rounds=3)
    assert b._booster.learner.layout == "gather"
    assert np.isfinite(b.predict(X[:50])).all()


def test_layout_auto_resolution():
    """auto -> gather below the 2^20-row threshold, explicit knobs
    honored; sorted drops the dead column-major copy."""
    X, y = _data(n=500)
    b_auto = _train(X, y, "auto", rounds=2)
    assert b_auto._booster.learner.layout == "gather"
    b_sorted = _train(X, y, "sorted", rounds=2)
    lr = b_sorted._booster.learner
    assert lr.layout == "sorted"
    assert lr.x_cols.shape == (1, 1)      # placeholder, not a resident copy
    assert b_auto._booster.learner.x_cols.shape[0] == lr.hx_rows.shape[1]


def test_tree_layout_knob_validated():
    with pytest.raises(Exception):
        Config.from_params({"tree_layout": "bogus"})
    with pytest.raises(Exception):
        Config.from_params({"num_grad_quant_bins": 300})


def test_telemetry_layout_apply_span_tiles_wall():
    """The sorted rebuild cost shows up as its own phase and the spans
    still tile the iteration wall (the ±10% discipline test_obs enforces
    for every other phase)."""
    X, y = _data(n=900)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 10, "verbose": -1, "telemetry": True,
              "tpu_fused_learner": "1", "tree_layout": "sorted"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    tel = b._booster.telemetry
    recs = list(tel.records)
    assert recs, "telemetry produced no records"
    assert any("layout_apply" in r["phases"] for r in recs)
    for r in recs[1:]:
        span = sum(v for k, v in r["phases"].items() if k != "eval")
        assert span <= r["wall_s"] * 1.1 + 1e-3


# -- Pallas kernel (interpret mode on CPU) ------------------------------
def _np_hist(bins, g, h, count, B):
    F = bins.shape[1]
    ref = np.zeros((F, B, 3), np.float64)
    for i in range(count):
        for f in range(F):
            ref[f, bins[i, f]] += [g[i], h[i], 1.0]
    return ref


def test_hist_pallas_matches_reference():
    import jax.numpy as jnp
    from lambdagap_tpu.ops.hist_pallas import hist_pallas, pack_gh8
    rng = np.random.RandomState(0)
    P, F, B, count = 300, 5, 16, 257          # ragged final tile
    bins = rng.randint(0, B, (P, F)).astype(np.uint8)
    g = rng.randn(P).astype(np.float32)
    h = np.abs(rng.randn(P)).astype(np.float32)
    gh8 = pack_gh8(jnp.asarray(g), jnp.asarray(h), jnp.ones(P, bool))
    out = np.asarray(hist_pallas(jnp.asarray(bins), gh8, B, count))
    ref = _np_hist(bins, g, h, count, B)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=1e-4)
    # the count channel is exact
    np.testing.assert_array_equal(out[..., 2], ref[..., 2])


def test_hist_pallas_ignores_junk_past_count():
    """Rows past the dynamic count may hold ANYTHING (a sorted-layout
    window running into the next leaf): masked in-kernel."""
    import jax.numpy as jnp
    from lambdagap_tpu.ops.hist_pallas import hist_pallas, pack_gh8
    rng = np.random.RandomState(1)
    P, F, B, count = 256, 4, 8, 100
    bins = rng.randint(0, B, (P, F)).astype(np.uint8)
    g = rng.randn(P).astype(np.float32)
    h = np.abs(rng.randn(P)).astype(np.float32)
    # junk channels past count: NOT zeroed
    gh8 = np.asarray(pack_gh8(jnp.asarray(g), jnp.asarray(h),
                              jnp.ones(P, bool)))
    gh8_junk = gh8.copy()
    gh8_junk[count:] = 99.0
    out = np.asarray(hist_pallas(jnp.asarray(bins), jnp.asarray(gh8_junk),
                                 B, count))
    ref = _np_hist(bins, g, h, count, B)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=1e-4)


def test_hist_pallas_slice_order_invariance():
    """A gathered row block and the physically pre-sorted contiguous block
    holding the same rows in the same order produce bit-identical
    histograms — the layout cannot change values, only the access
    pattern."""
    import jax.numpy as jnp
    from lambdagap_tpu.ops.hist_pallas import hist_pallas, pack_gh8
    rng = np.random.RandomState(2)
    P, F, B = 512, 6, 16
    bins = rng.randint(0, B, (P, F)).astype(np.uint8)
    g = rng.randn(P).astype(np.float32)
    h = np.abs(rng.randn(P)).astype(np.float32)
    perm = rng.permutation(P)
    gh8 = np.asarray(pack_gh8(jnp.asarray(g), jnp.asarray(h),
                              jnp.ones(P, bool)))
    out_gather = np.asarray(hist_pallas(jnp.asarray(bins[perm]),
                                        jnp.asarray(gh8[perm]), B, P))
    sb, sg = np.ascontiguousarray(bins[perm]), np.ascontiguousarray(gh8[perm])
    out_sorted = np.asarray(hist_pallas(jnp.asarray(sb), jnp.asarray(sg),
                                        B, P))
    np.testing.assert_array_equal(out_gather, out_sorted)


def test_hist_pallas_q_exact_int32():
    import jax.numpy as jnp
    from lambdagap_tpu.ops.hist_pallas import hist_pallas_q, pack_ghq8
    rng = np.random.RandomState(3)
    P, F, B, count = 300, 5, 16, 201
    bins = rng.randint(0, B, (P, F)).astype(np.uint8)
    gq = rng.randint(-127, 128, P).astype(np.int8)
    hq = rng.randint(0, 128, P).astype(np.int8)
    ghq8 = pack_ghq8(jnp.asarray(gq), jnp.asarray(hq), jnp.ones(P, bool))
    out = np.asarray(hist_pallas_q(jnp.asarray(bins), ghq8, B, count))
    ref = np.zeros((F, B, 3), np.int64)
    for i in range(count):
        for f in range(F):
            ref[f, bins[i, f]] += [gq[i], hq[i], 1]
    np.testing.assert_array_equal(out, ref)


def test_fused_pallas_interpret_close_to_onehot():
    """End-to-end: tpu_hist_impl=pallas (interpret mode on CPU) trains a
    model whose predictions track the one-hot contraction's — the two
    accumulate in different orders/precisions, so this is a tolerance
    check, not bit-parity (bit-parity is asserted per layout, per impl)."""
    X, y = _data(n=400)
    p = {"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 10,
         "verbose": -1, "tpu_fused_learner": "1"}
    b1 = lgb.train({**p, "tpu_hist_impl": "onehot"},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    b2 = lgb.train({**p, "tpu_hist_impl": "pallas"},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    np.testing.assert_allclose(b2.predict(X[:100]), b1.predict(X[:100]),
                               rtol=1e-3, atol=1e-4)


def test_fused_pallas_sorted_bit_identical_to_gather():
    """The f32 acceptance bar: under the Pallas kernel, tree_layout=sorted
    is bit-identical to the gather oracle."""
    X, y = _data(n=400)
    p = {"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 10,
         "verbose": -1, "tpu_fused_learner": "1", "tpu_hist_impl": "pallas"}
    bg = lgb.train({**p, "tree_layout": "gather"},
                   lgb.Dataset(X, label=y), num_boost_round=2)
    bs = lgb.train({**p, "tree_layout": "sorted"},
                   lgb.Dataset(X, label=y), num_boost_round=2)
    assert _trees(bg) == _trees(bs)


def test_exact_accum_limit_single_source():
    """The quantized-accumulator guard and config validation share one
    helper (the old code had two diverging literals)."""
    from lambdagap_tpu.ops.hist_pallas import (MAX_QUANT_BINS,
                                               exact_accum_limit)
    assert exact_accum_limit("pallas") == 2**31 - 1
    assert exact_accum_limit("onehot") == 2**24
    assert MAX_QUANT_BINS == 127
