"""Piece-wise linear leaves (ISSUE 11): the TPU-native model class.

The acceptance matrix: fused-learner linear trees BIT-IDENTICAL to
serial-learner linear trees (same batched moment accumulation + stacked
solve, ops/linear.py); tensor-engine linear predictions ``array_equal`` to
the scan oracle across ragged buckets, NaN/default-left routing, and
categorical passthrough; SIGKILL + resume=auto byte-identity under
fused+linear (the PR 6 f64/f32 drift class); and a linear model serving
through ModelRegistry + Router + TCP frontend bit-identically to device
predict (the old serve/cache.py rejection is gone).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lambdagap_tpu as lgb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE = {"objective": "regression", "num_leaves": 6, "learning_rate": 0.4,
        "min_data_in_leaf": 20, "verbose": -1, "linear_tree": True,
        "linear_lambda": 1e-3}


def _data(n=1200, seed=5, nan=False, cat=False):
    rng = np.random.RandomState(seed)
    X = (rng.rand(n, 5) * 4).astype(np.float32)
    if cat:
        X[:, 4] = rng.randint(0, 6, n)
    if nan:
        X[::13, 0] = np.nan
        X[::29, 2] = np.nan
    base = np.nan_to_num(X, nan=1.0)
    y = (2.0 * base[:, 0] - 1.5 * base[:, 1]
         + np.where(base[:, 2] > 2, 3.0, 0.0)
         + (base[:, 4] % 3 if cat else 0.0)
         + 0.05 * rng.randn(n)).astype(np.float32)
    return X, y


def _train(X, y, fused, extra=None):
    params = {**BASE, "tpu_fused_learner": "1" if fused else "0"}
    if extra:
        params.update(extra)
    cats = [4] if extra and extra.pop("_cat", False) else "auto"
    ds = lgb.Dataset(X, label=y, categorical_feature=cats, params=params)
    return lgb.train(params, ds, num_boost_round=6)


def _trees(booster) -> str:
    return booster.model_to_string().split("end of trees")[0]


# -- fused == serial ----------------------------------------------------
@pytest.mark.parametrize("extra", [
    None,
    {"bagging_fraction": 0.7, "bagging_freq": 1},
    {"data_sample_strategy": "goss", "top_rate": 0.3, "other_rate": 0.2},
    {"_nan": True},
    {"_cat": True},
    {"max_depth": 3, "lambda_l2": 1.0},
])
def test_fused_serial_linear_bit_identical(extra):
    ex = dict(extra or {})
    nan = ex.pop("_nan", False)
    cat = ex.get("_cat", False)
    X, y = _data(nan=nan, cat=cat)
    bs = _train(X, y, fused=False, extra=dict(ex))
    bf = _train(X, y, fused=True, extra=dict(ex))
    assert any(getattr(t, "is_linear", False)
               for t in bs._booster.host_models)
    assert _trees(bs) == _trees(bf), \
        "fused linear trees must be byte-identical to serial ones"
    assert np.array_equal(bs.predict(X), bf.predict(X))


# -- tensor == scan on linear forests -----------------------------------
def test_tensor_scan_engines_array_equal_on_linear_forest():
    X, y = _data(nan=True, cat=True)
    b = _train(X, y, fused=True, extra={"_cat": True})
    text = b.model_to_string()
    outs = {}
    for eng in ("tensor", "scan"):
        bb = lgb.Booster(model_str=text, params={"predict_engine": eng,
                                                 "verbose": -1})
        # ragged sizes exercise every padding bucket/tile tail
        outs[eng] = [bb.predict(X[:n], raw_score=True)
                     for n in (1, 3, 37, 200, len(X))]
    for a, c in zip(outs["tensor"], outs["scan"]):
        assert np.array_equal(a, c), \
            "tensor engine must match the scan oracle exactly"
    # NaN rows fell back to constant leaves, not to garbage
    assert all(np.isfinite(o).all() for o in outs["tensor"])


def test_predict_matches_host_linear_replay():
    """The device engines' linear outputs agree with the host float64
    leaf-model evaluation (the training/replay path) to f32 rounding."""
    from lambdagap_tpu.models.tree import linear_leaf_outputs
    X, y = _data(nan=True)
    b = _train(X, y, fused=True)
    got = b.predict(X, raw_score=True)
    leaf = b.predict(X, pred_leaf=True)
    host = np.zeros(len(X))
    for i, t in enumerate(b._booster.host_models):
        host += linear_leaf_outputs(t, X.astype(np.float64), leaf[:, i])
    np.testing.assert_allclose(got, host, rtol=1e-5, atol=1e-6)


# -- SIGKILL + resume byte-identity under fused + linear ----------------
def _cli(args, tmp_path, faults=""):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if faults:
        env["LAMBDAGAP_FAULTS"] = faults
    else:
        env.pop("LAMBDAGAP_FAULTS", None)
    return subprocess.run([sys.executable, "-m", "lambdagap_tpu", *args],
                          cwd=str(tmp_path), env=env, capture_output=True,
                          text=True, timeout=300)


def test_sigkill_resume_fused_linear_byte_identical(tmp_path):
    """ISSUE 11 acceptance: snapshot/resume byte-identity under
    fused+linear — resume replays each linear tree's float64 outputs
    rounded to f32 PER TREE, the exact addition order training used (the
    PR 6 f64-materialization drift class, now guarded for linear)."""
    X, y = _data(600, seed=9)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    args = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=6", "snapshot_freq=1", "min_data_in_leaf=20",
            "num_leaves=6", "linear_tree=true", "linear_lambda=0.001",
            "verbose=1", "resume=auto", "tpu_fused_learner=1"]
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path,
             faults="crash_at_iter=3")
    assert r.returncode == -9, f"expected SIGKILL, got {r.returncode}: " \
        f"{r.stdout}\n{r.stderr}"
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resumed from snapshot" in r.stdout + r.stderr
    r = _cli(args + ["output_model=m_ref.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    resumed = (tmp_path / "m_crash.txt").read_text()
    ref = (tmp_path / "m_ref.txt").read_text()
    split = "end of trees"
    assert "is_linear=1" in ref
    assert resumed.split(split)[0] == ref.split(split)[0], \
        "fused+linear resumed model must be byte-identical"


# -- serve: registry / router / frontend round trip ---------------------
def test_linear_serves_bit_identical_through_fleet_paths():
    from lambdagap_tpu.serve import (FrontendClient, LocalReplica, Router,
                                     ServeFrontend)
    X, y = _data(nan=True)
    b = _train(X, y, fused=True)
    ref = b.predict(X[:600])
    with b.as_server(buckets=(1, 8, 64), warmup=True) as s:
        outs, lo = [], 0
        for n in (1, 3, 8, 11, 64, 100, 129):
            outs.append(s.predict(X[lo:lo + n]))
            lo += n
        assert np.array_equal(np.concatenate(outs), ref[:lo]), \
            "served linear outputs must be bit-identical to device predict"
        got_named = np.concatenate([s.predict(X[i:i + 37], model="default",
                                              tenant="parity")
                                    for i in range(0, 111, 37)])
        assert np.array_equal(got_named, ref[:111])
        with Router([LocalReplica("a", s)]) as router:
            got_routed = np.concatenate([router.predict(X[i:i + 29],
                                                        timeout=30)
                                         for i in range(0, 87, 29)])
        assert np.array_equal(got_routed, ref[:87])
        with ServeFrontend(s) as fe:
            with FrontendClient("127.0.0.1", fe.port) as client:
                got_wire = np.concatenate([client.predict(X[i:i + 41])
                                           for i in range(0, 123, 41)])
        assert np.array_equal(got_wire, np.asarray(ref[:123], np.float32))


def test_linear_model_registry_swap_and_readmission():
    """A linear model rides the registry like any other: evict, re-admit,
    swap — parity held throughout (the rejection would have made all of
    this impossible)."""
    X, y = _data()
    b = _train(X, y, fused=True)
    b2 = _train(X, y, fused=False, extra={"num_leaves": 4})
    ref, ref2 = b.predict(X[:128]), b2.predict(X[:128])
    with b.as_server(buckets=(64,)) as s:
        s.add_model("lin2", b2._booster)
        assert np.array_equal(s.predict(X[:128], model="lin2"), ref2)
        assert np.array_equal(s.predict(X[:128]), ref)


# -- continued training round-trip (satellite) --------------------------
def test_linear_resume_refit_roundtrip_with_raw_retaining_dataset():
    """Satellite: resume_from/refit on a linear model works whenever raw
    data is retained — including a Dataset that requested linear_tree via
    its OWN params while the booster config dropped the flag (constant
    continuation from a linear init model)."""
    X, y = _data(900, seed=11)
    b5 = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=5)
    const_params = {k: v for k, v in BASE.items()
                    if k not in ("linear_tree", "linear_lambda")}
    ds = lgb.Dataset(X, label=y, params={"linear_tree": True})
    resumed = lgb.train(const_params, ds, num_boost_round=3, init_model=b5)
    assert np.isfinite(resumed.predict(X)).all()
    assert len(resumed._booster.models) == 8
    # refit still drops the linear payload loudly
    b_ref = b5.refit(X, y)
    assert not any(getattr(t, "is_linear", False)
                   for t in b_ref._booster.host_models)
    # genuinely absent raw data still fails fast
    with pytest.raises(RuntimeError, match="raw"):
        lgb.train(const_params, lgb.Dataset(X, label=y), num_boost_round=2,
                  init_model=b5)


# -- unsupported combos fall back loudly --------------------------------
def test_linear_dart_rejected_at_config_time():
    X, y = _data()
    with pytest.raises(RuntimeError, match="linear_tree.*boosting"):
        lgb.train({**BASE, "boosting": "dart"}, lgb.Dataset(X, label=y),
                  num_boost_round=2)


def test_linear_quantized_falls_back_to_full_precision(caplog):
    X, y = _data()
    import logging
    with caplog.at_level(logging.WARNING, logger="lambdagap_tpu"):
        b = lgb.train({**BASE, "use_quantized_grad": True, "verbose": 0,
                       "tpu_fused_learner": "1"},
                      lgb.Dataset(X, label=y), num_boost_round=3)
    assert any("use_quantized_grad" in r.message for r in caplog.records)
    assert any(getattr(t, "is_linear", False)
               for t in b._booster.host_models)


def test_linear_stream_falls_back_to_hbm(caplog):
    X, y = _data()
    import logging
    with caplog.at_level(logging.WARNING, logger="lambdagap_tpu"):
        b = lgb.train({**BASE, "data_residency": "stream", "verbose": 0,
                       "tpu_fused_learner": "1"},
                      lgb.Dataset(X, label=y), num_boost_round=3)
    assert any("data_residency=stream" in r.message
               for r in caplog.records)
    assert any(getattr(t, "is_linear", False)
               for t in b._booster.host_models)


# -- SHAP coefficient-attribution split ---------------------------------
def test_linear_pred_contrib_sum_invariant_with_nans():
    X, y = _data(nan=True)
    b = _train(X, y, fused=True)
    phi = b.predict(X, pred_contrib=True)
    assert phi.shape == (len(X), X.shape[1] + 1)
    np.testing.assert_allclose(phi.sum(axis=1),
                               b.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-5)
