"""Continuous learning (ISSUE 20): snapshot retention, the tailing
trainer, and the promotion controller's state machine — promote, reject,
rollback, and the mid-promote crash window, every fault point proven
live. The end-to-end version under real subprocesses and SIGKILL lives
in tools/loop_gate.py.
"""
import os
import time

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.data.tail import SequenceTail, write_batch
from lambdagap_tpu.guard.faults import FaultPlan, InjectedFault
from lambdagap_tpu.guard.snapshot import (STATE_VERSION, SnapshotError,
                                          compose_snapshot, latest_snapshot,
                                          list_snapshots, prune_snapshots,
                                          read_snapshot, snapshot_path,
                                          write_training_snapshot)
from lambdagap_tpu.loop import PromotionController, TailingTrainer
from lambdagap_tpu.obs import events as obs_events
from lambdagap_tpu.obs import trace as obs_trace
from lambdagap_tpu.serve import Autonomics, LocalReplica, Router
from lambdagap_tpu.serve.delta import split_model_text

PARAMS = {"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbose": -1, "tpu_fast_predict_rows": 0}


def _fake_snapshot(family: str, iteration: int, epoch: int,
                   torn: bool = False) -> str:
    """A schema-valid (or deliberately torn) snapshot file without the
    cost of training — retention logic only reads the sidecar."""
    state = {"version": STATE_VERSION, "iteration": iteration,
             "candidate_epoch": epoch}
    data = compose_snapshot(f"tree\n(fake model {iteration})\n", state)
    if torn:
        data = data[: len(data) // 2]
    path = snapshot_path(family, iteration)
    with open(path, "w") as f:
        f.write(data)
    return path


def _train_base(rounds: int = 4, seed: int = 0):
    rng = np.random.RandomState(seed)
    X = rng.randn(400, 5).astype(np.float32)
    y = (X[:, 0] * 2.0 - X[:, 1]).astype(np.float32)
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=rounds)
    return b, X


def _continue_from(base_path: str, rounds: int, seed: int = 1):
    rng = np.random.RandomState(seed)
    X = rng.randn(400, 5).astype(np.float32)
    y = (X[:, 0] * 2.0 - X[:, 1]).astype(np.float32)
    return lgb.train(PARAMS, lgb.Dataset(X, label=y),
                     num_boost_round=rounds, init_model=base_path)


# ---------------------------------------------------------------------------
# retention (guard_snapshot_keep)
# ---------------------------------------------------------------------------
def test_prune_keeps_newest_k(tmp_path):
    fam = str(tmp_path / "m.txt")
    for i in range(1, 6):
        _fake_snapshot(fam, i * 3, i)
    removed = prune_snapshots(fam, keep=2)
    assert len(removed) == 3
    assert list_snapshots(fam) == [snapshot_path(fam, 15),
                                   snapshot_path(fam, 12)]
    # idempotent once at the floor
    assert prune_snapshots(fam, keep=2) == []


def test_prune_never_deletes_newest_valid_under_torn_head(tmp_path):
    """The file resume will actually use must survive any keep value:
    with the newest-by-iteration snapshot torn, latest_snapshot falls
    back to the newest VALID one — pruning to keep=1 must keep THAT file
    (plus the newest by sort), not strand resume on a corrupt head."""
    fam = str(tmp_path / "m.txt")
    _fake_snapshot(fam, 3, 1)
    good = _fake_snapshot(fam, 6, 2)
    torn = _fake_snapshot(fam, 9, 3, torn=True)
    prune_snapshots(fam, keep=1)
    left = list_snapshots(fam)
    assert good in left and torn in left
    assert snapshot_path(fam, 3) not in left
    path, _text, state = latest_snapshot(fam)
    assert path == good and state["candidate_epoch"] == 2


def test_candidate_torn_fault_point_is_live(tmp_path):
    """`candidate_torn=K` tears the K-th CANDIDATE write on its own
    counter: the torn file fails read_snapshot, latest_snapshot skips
    it, and the plain (non-candidate) snapshot path is untouched."""
    fam = str(tmp_path / "cand.txt")
    base, _X = _train_base(rounds=4)
    faults = FaultPlan("candidate_torn=1")
    p1 = write_training_snapshot(base._booster, fam, faults=faults,
                                 candidate=True,
                                 extra_state={"candidate_epoch": 1})
    with pytest.raises(SnapshotError):
        read_snapshot(p1)
    assert latest_snapshot(fam) is None
    # the fault is one-shot: the next candidate write lands valid
    p2 = write_training_snapshot(base._booster, fam, faults=faults,
                                 candidate=True,
                                 extra_state={"candidate_epoch": 2})
    assert p2 == p1                      # same iteration, now valid
    assert latest_snapshot(fam)[2]["candidate_epoch"] == 2


def test_write_training_snapshot_applies_keep(tmp_path):
    fam = str(tmp_path / "m.txt")
    for i in range(1, 4):
        _fake_snapshot(fam, i, i)
    base, _X = _train_base(rounds=4)      # iter_ = 4, the newest
    write_training_snapshot(base._booster, fam, keep=2)
    assert list_snapshots(fam) == [snapshot_path(fam, 4),
                                   snapshot_path(fam, 3)]


# ---------------------------------------------------------------------------
# the tailing trainer
# ---------------------------------------------------------------------------
def _write_fold(dirpath, name, rows=150, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(rows, 4)
    y = X[:, 0] * 1.5 + 0.05 * rng.randn(rows)
    write_batch(str(dirpath), name, X, y)


def test_tailing_trainer_epochs_extend_trees(tmp_path):
    """Each fold emits one tagged candidate; epoch and iteration are
    monotone, and a later epoch's model text EXTENDS the earlier one's
    trees byte-identically (continuation, not retrain — the bin mappers
    are adopted through reference=, never recomputed)."""
    batches = tmp_path / "batches"
    batches.mkdir()
    fam = str(tmp_path / "cand.txt")
    _write_fold(batches, "batch_0000", seed=0)
    tr = TailingTrainer(dict(PARAMS), SequenceTail(str(batches)), fam,
                        iters_per_fold=2)
    rec1 = tr.fold_once()
    assert rec1["epoch"] == 1 and rec1["iteration"] == 2
    assert tr.fold_once() is None        # no new data -> no fold
    _write_fold(batches, "batch_0001", seed=1)
    rec2 = tr.fold_once()
    assert rec2["epoch"] == 2 and rec2["iteration"] == 4
    text1 = read_snapshot(rec1["path"])[0]
    text2 = read_snapshot(rec2["path"])[0]
    t1, t2 = split_model_text(text1)[1], split_model_text(text2)[1]
    assert len(t2) == 4 and t2[:2] == t1


def test_tailing_trainer_resumes_from_latest_valid(tmp_path):
    """A fresh TailingTrainer over an existing family adopts its epoch/
    iteration (the restarted-process case), and its first fold runs even
    without NEW batches — a restart continues immediately."""
    batches = tmp_path / "batches"
    batches.mkdir()
    fam = str(tmp_path / "cand.txt")
    _write_fold(batches, "batch_0000", seed=0)
    tr = TailingTrainer(dict(PARAMS), SequenceTail(str(batches)), fam,
                        iters_per_fold=2)
    rec1 = tr.fold_once()
    tr2 = TailingTrainer(dict(PARAMS), SequenceTail(str(batches)), fam,
                         iters_per_fold=2)
    assert tr2.epoch == 1 and tr2.total_iters == 2
    rec2 = tr2.fold_once()               # same rows, continued training
    assert rec2["epoch"] == 2 and rec2["iteration"] == 4
    t1 = split_model_text(read_snapshot(rec1["path"])[0])[1]
    t2 = split_model_text(read_snapshot(rec2["path"])[0])[1]
    assert t2[:2] == t1


# ---------------------------------------------------------------------------
# the promotion controller
# ---------------------------------------------------------------------------
def _fleet(base, n=2):
    servers = [base.as_server() for _ in range(n)]
    router = Router([LocalReplica(f"r{i}", s)
                     for i, s in enumerate(servers)], own_replicas=True)
    auto = Autonomics(router)            # never started: the actuator only
    router.attach_autonomics(auto)
    return router, auto


def _fill_window(ctl, router, X, n=8, timeout_s=10.0):
    """Drive n live requests and tick until the shadow window compared
    them all (the mirror pool is async)."""
    for i in range(n):
        router.predict(X[i:i + 1])
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        snap = router.shadow_snapshot()
        if snap is not None and snap["compared"] >= n:
            return snap
        time.sleep(0.05)
    raise AssertionError(f"shadow window never filled: "
                         f"{router.shadow_snapshot()}")


def _candidate_on_disk(tmp_path, base_path, epoch, rounds):
    cand = _continue_from(base_path, rounds=rounds)
    fam = str(tmp_path / "cand.txt")
    return fam, write_training_snapshot(
        cand._booster, fam, candidate=True,
        extra_state={"candidate_epoch": epoch}), cand


def test_controller_promotes_within_threshold(tmp_path):
    base, X = _train_base(rounds=4)
    base_path = str(tmp_path / "base.txt")
    base.save_model(base_path)
    fam, _path, cand = _candidate_on_disk(tmp_path, base_path, 1, rounds=6)
    router, auto = _fleet(base)
    try:
        ctl = PromotionController(router, auto, fam, sample=1.0,
                                  min_requests=8, threshold=1e9,
                                  base_source=base_path,
                                  watch_min_requests=4)
        assert router.loop_status()["state"] == "idle"   # self-attached
        ctl.tick()
        assert ctl.status()["state"] == "shadowing"
        _fill_window(ctl, router, X, n=8)
        ctl.tick()                       # decide -> promoting
        ctl.tick()                       # rollout + commit -> watching
        st = ctl.status()
        assert st["state"] == "watching" and st["promoted_epoch"] == 1
        assert auto.counters["delta_rollouts"] == 1
        want = split_model_text(cand._booster.save_model_to_string())[1]
        for name in router.replica_names(live_only=True):
            got = router.replica(name).server.registry.model_text("default")
            assert split_model_text(got)[1] == want
        for _ in range(6):               # fill the watch window
            router.predict(X[:1])
        ctl.tick()
        assert ctl.status()["state"] == "idle"
        assert ctl.counters["rollbacks"] == 0
    finally:
        router.close()


def test_controller_rejects_and_never_retries(tmp_path):
    base, X = _train_base(rounds=4)
    base_path = str(tmp_path / "base.txt")
    base.save_model(base_path)
    fam, _path, _c = _candidate_on_disk(tmp_path, base_path, 1, rounds=6)
    router, auto = _fleet(base, n=1)
    try:
        ctl = PromotionController(router, auto, fam, sample=1.0,
                                  min_requests=8, threshold=0.0,
                                  base_source=base_path)
        ctl.tick()
        _fill_window(ctl, router, X, n=8)
        ctl.tick()
        st = ctl.status()
        assert st["state"] == "idle" and st["promoted_epoch"] == 0
        assert ctl.counters["rejections"] == 1
        assert router.shadow_snapshot() is None   # disarmed
        ctl.tick()                       # the rejected epoch is remembered
        assert ctl.status()["state"] == "idle"
        assert ctl.counters["candidates_seen"] == 1
        got = router.replica("r0").server.registry.model_text("default")
        want = split_model_text(base.model_to_string())[1]
        assert split_model_text(got)[1] == want   # live fleet untouched
    finally:
        router.close()


def test_promote_crash_at_commit_does_not_double_rollout(tmp_path):
    """`promote_crash_at=commit` is live: the crash lands AFTER the
    rollout, and the retry tick must commit WITHOUT re-applying it."""
    base, X = _train_base(rounds=4)
    base_path = str(tmp_path / "base.txt")
    base.save_model(base_path)
    fam, _path, _c = _candidate_on_disk(tmp_path, base_path, 1, rounds=6)
    router, auto = _fleet(base)
    try:
        ctl = PromotionController(router, auto, fam, sample=1.0,
                                  min_requests=4, threshold=1e9,
                                  base_source=base_path,
                                  faults=FaultPlan("promote_crash_at=commit"))
        ctl.tick()
        _fill_window(ctl, router, X, n=4)
        ctl.tick()                       # -> promoting
        ctl.tick()                       # rollout lands, commit crashes
        assert ctl.counters["promote_crashes"] == 1
        assert ctl.status()["state"] == "promoting"
        assert auto.counters["delta_rollouts"] == 1
        ctl.tick()                       # retry: commit only
        assert ctl.status()["state"] == "watching"
        assert ctl.counters["promotions"] == 1
        assert auto.counters["delta_rollouts"] == 1   # never re-applied
    finally:
        router.close()


def test_post_promote_regression_rolls_back(tmp_path):
    base, X = _train_base(rounds=4)
    base_path = str(tmp_path / "base.txt")
    base.save_model(base_path)
    fam, _path, _c = _candidate_on_disk(tmp_path, base_path, 1, rounds=6)
    router, auto = _fleet(base)
    try:
        ctl = PromotionController(router, auto, fam, sample=1.0,
                                  min_requests=4, threshold=1e9,
                                  base_source=base_path,
                                  watch_min_requests=10,
                                  regression_threshold=0.05)
        ctl.tick()
        _fill_window(ctl, router, X, n=4)
        ctl.tick()
        ctl.tick()
        assert ctl.status()["state"] == "watching"
        # script the watch window: 20 requests, 30% bad
        base_counters = ctl._watch_base
        ctl._fleet_counters = lambda: {
            "routed": base_counters["routed"] + 20,
            "bad": base_counters["bad"] + 6}
        ctl.tick()
        st = ctl.status()
        assert st["state"] == "idle"
        assert ctl.counters["rollbacks"] == 1
        assert st["promoted_epoch"] == 0
        want = split_model_text(base.model_to_string())[1]
        for name in router.replica_names(live_only=True):
            got = router.replica(name).server.registry.model_text("default")
            assert split_model_text(got)[1] == want   # back on base
    finally:
        router.close()


def test_loop_events_schema_valid(tmp_path):
    """One full promote cycle's JSONL stream passes the observability
    schema validator (run_header first, every loop_* record typed)."""
    out = str(tmp_path / "events.jsonl")
    rec = obs_trace.SpanRecorder().configure(sample=1.0, out=out)
    base, X = _train_base(rounds=4)
    base_path = str(tmp_path / "base.txt")
    base.save_model(base_path)
    fam, _path, _c = _candidate_on_disk(tmp_path, base_path, 1, rounds=6)
    router, auto = _fleet(base, n=1)
    try:
        ctl = PromotionController(router, auto, fam, sample=1.0,
                                  min_requests=4, threshold=1e9,
                                  base_source=base_path,
                                  watch_min_requests=2, recorder=rec)
        ctl.tick()
        _fill_window(ctl, router, X, n=4)
        ctl.tick()
        ctl.tick()
        for _ in range(4):
            router.predict(X[:1])
        ctl.tick()
    finally:
        router.close()
        rec.close()
    assert obs_events.validate_file(out) == []
    records, _trunc = obs_events.read_file(out)
    seen = {r.get("event") for r in records if r.get("type") == "event"}
    for required in ("loop_candidate", "loop_shadow_start",
                     "loop_shadow_window", "loop_rollout", "loop_promote",
                     "loop_watch_clear"):
        assert required in seen, f"missing {required} in {sorted(seen)}"
    spans = {r.get("name") for r in records if r.get("type") == "span"}
    assert {"loop_promote:resolve", "loop_promote:rollout",
            "loop_promote:commit"} <= spans
