"""The fused 2-D data x feature program + its stream composition
(ISSUE 15).

The acceptance surface, all runnable on the conftest's 8-virtual-device
CPU mesh:

- ``make_mesh`` accepts ``dd>1 && ff>1`` and the fused 2-D learner
  trains on it: quantized-path trees BIT-IDENTICAL across the
  1x8 / 2x4 / 4x2 / 8x1 grids AND to the 1-device fused serial learner;
- ``data_residency=stream`` composes with the mesh: streamed 2-D trees
  are bit-identical to resident 2-D trees on the same grid, including
  under GOSS window compaction, with the h2d_prefetch/chunk_wait ring
  phases live and zero steady-state recompiles;
- ``mesh_shape`` validation: wildcard forms ("0x4"/"2x0") resolve
  against the device count with a clear error naming ``mesh_shape``
  when it does not divide;
- elastic resume across grid shapes: train on 4x2, SIGKILL, resume=auto
  on 2x4 and on 8x1 — final trees byte-identical to an uninterrupted
  run (quantized path; the sidecar ``mesh`` block carries the grid).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.parallel.fused_parallel import Fused2DTreeLearner
from lambdagap_tpu.parallel.sharding import (make_mesh, parse_mesh_shape,
                                             resolve_mesh_shape)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trees(booster) -> str:
    return booster.model_to_string().split("end of trees")[0]


def _data(n=4001, d=6, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = (X[:, 0] - 0.4 * X[:, 1] + np.sin(X[:, 2]) + 0.2 * rng.randn(n)
         > 0).astype(np.float32)
    return X, y


def _train(X, y, extra, rounds=4):
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 10, "tpu_fused_learner": "1"}
    params.update(extra)
    return lgb.train(params, lgb.Dataset(X, label=y, params=params),
                     num_boost_round=rounds)


# -- mesh_shape resolution ----------------------------------------------
def test_make_mesh_accepts_2d_grids():
    for shape, want in (("4x2", (4, 2)), ("2x4", (2, 4)), ("1x8", (1, 8)),
                        ("8x1", (8, 1)), ("0x4", (2, 4)), ("4x0", (4, 2)),
                        ("0x8", (1, 8))):
        m = make_mesh(mesh_shape=shape)
        assert dict(m.shape) == {"data": want[0], "feature": want[1]}, shape
        assert tuple(m.axis_names) == ("data", "feature")


def test_mesh_shape_wildcards_and_rejections_name_the_knob():
    # wildcards resolve against the device count
    assert resolve_mesh_shape("0x4", 8) == (2, 4)
    assert resolve_mesh_shape("2x0", 8) == (2, 4)
    assert resolve_mesh_shape("", 8) is None
    # non-divisible wildcard, capacity overflow, 0x0, bad syntax — every
    # rejection names mesh_shape (the num_grad_quant_bins precedent)
    for shape, ndev in (("0x3", 8), ("3x0", 8), ("4x4", 8), ("0x16", 8),
                        ("0x0", 8)):
        with pytest.raises(ValueError, match="mesh_shape"):
            resolve_mesh_shape(shape, ndev)
    with pytest.raises(ValueError, match="mesh_shape"):
        parse_mesh_shape("axb")
    with pytest.raises(ValueError, match="mesh_shape"):
        parse_mesh_shape("2x2x2")


# -- the fused 2-D program (hbm) ----------------------------------------
def test_quantized_trees_bit_identical_across_grids():
    """The tentpole contract: one program for every dd x ff grid, and on
    the quantized path the integer data-psum + feature-blocked argmax
    make the trees grid-invariant — bit-identical across 1x8 / 2x4 /
    4x2 / 8x1 AND to the 1-device fused serial learner."""
    X, y = _data()
    quant = {"use_quantized_grad": True, "stochastic_rounding": False}
    ref = _trees(_train(X, y, {"tree_learner": "serial", **quant}))
    ref_t = ref.split("Tree=0")[1]
    for grid in ("1x8", "2x4", "4x2", "8x1"):
        b = _train(X, y, {"tree_learner": "data", "mesh_shape": grid,
                          **quant})
        lr = b._booster.learner
        assert isinstance(lr, Fused2DTreeLearner), type(lr).__name__
        assert (lr.dd, lr.ff) == tuple(int(v) for v in grid.split("x"))
        assert _trees(b).split("Tree=0")[1] == ref_t, grid


def test_2d_grid_zero_steady_recompiles_and_telemetry():
    X, y = _data(n=3000)
    b = _train(X, y, {"tree_learner": "data", "mesh_shape": "2x2",
                      "use_quantized_grad": True,
                      "stochastic_rounding": False,
                      "telemetry": True, "telemetry_warmup": 3},
               rounds=6)
    tel = b._booster.telemetry
    steady = [(r["iter"], r["compiles"]["total"]) for r in tel.records
              if r.get("iter", 0) >= 3
              and (r.get("compiles") or {}).get("total", 0)]
    assert steady == [], steady


def test_2d_bagging_and_feature_fraction_match_serial_quant():
    """Sampling masks ride the row shards and the feature mask is drawn
    at the REAL feature count then padded — neither may perturb the
    grid-invariance of the quantized path."""
    X, y = _data(seed=11)
    extra = {"bagging_fraction": 0.6, "bagging_freq": 1,
             "feature_fraction": 0.8, "use_quantized_grad": True,
             "stochastic_rounding": False}
    ref = _trees(_train(X, y, {"tree_learner": "serial", **extra}))
    got = _trees(_train(X, y, {"tree_learner": "data", "mesh_shape": "2x3",
                               **extra}))
    assert got.split("Tree=0")[1] == ref.split("Tree=0")[1]


def test_2d_requires_fused_learner():
    X, y = _data(n=1200)
    with pytest.raises(Exception, match="2-D data x feature"):
        _train(X, y, {"tree_learner": "data", "mesh_shape": "2x2",
                      "tpu_fused_learner": "0"})


# -- stream x 2-D composition -------------------------------------------
@pytest.mark.parametrize("grid", ["2x4", "4x2"])
def test_stream_matches_resident_on_2d_grid(grid):
    """The composed out-of-core path: host shards pumped through the
    mesh-sharded ring build trees bit-identical to the resident 2-D
    program on the same grid (the same-grid mirror contract)."""
    X, y = _data()
    base = {"tree_learner": "data", "mesh_shape": grid,
            "stream_shard_rows": 1024, "enable_bundle": False}
    a = _train(X, y, {**base, "data_residency": "hbm"})
    b = _train(X, y, {**base, "data_residency": "stream"})
    lr = b._booster.learner
    assert isinstance(lr, Fused2DTreeLearner) and lr.residency == "stream"
    assert lr.sdata.num_shards == 4      # 4001 rows -> ragged tail shard
    assert _trees(a) == _trees(b)


def test_stream_2d_goss_compaction_identical():
    """GOSS drives per-block window compaction: only in-bag rows cross
    the link per data shard; re-expansion keeps bit-identity with and
    without compaction."""
    X, y = _data(seed=13)
    base = {"tree_learner": "data", "mesh_shape": "2x2",
            "stream_shard_rows": 1024, "enable_bundle": False,
            "data_sample_strategy": "goss", "top_rate": 0.2,
            "other_rate": 0.1, "learning_rate": 0.5}
    a = _train(X, y, {**base, "data_residency": "hbm"}, rounds=5)
    b = _train(X, y, {**base, "data_residency": "stream"}, rounds=5)
    c = _train(X, y, {**base, "data_residency": "stream",
                      "stream_goss_compact": False}, rounds=5)
    assert _trees(a) == _trees(b)
    assert _trees(a) == _trees(c)


def test_stream_2d_ring_phases_and_zero_recompiles():
    X, y = _data(n=3000)
    b = _train(X, y, {"tree_learner": "data", "mesh_shape": "2x2",
                      "data_residency": "stream",
                      "stream_shard_rows": 1024, "enable_bundle": False,
                      "telemetry": True, "telemetry_warmup": 4},
               rounds=8)
    tel = b._booster.telemetry
    steady = [(r["iter"], r["compiles"]["total"]) for r in tel.records
              if r.get("iter", 0) >= 4
              and (r.get("compiles") or {}).get("total", 0)]
    assert steady == [], steady
    phases = set()
    for r in tel.records:
        phases.update((r.get("phases") or {}).keys())
    assert {"h2d_prefetch", "chunk_wait"} <= phases, sorted(phases)


def test_stream_2d_blocker_falls_back_to_hbm():
    """Options the composed stream subset does not replicate (quantized
    gradients here) fall back to resident 2-D training loudly — the
    demotion keeps the grid, not the residency."""
    X, y = _data(n=1500)
    b = _train(X, y, {"tree_learner": "data", "mesh_shape": "2x2",
                      "data_residency": "stream",
                      "use_quantized_grad": True,
                      "stochastic_rounding": False})
    lr = b._booster.learner
    assert isinstance(lr, Fused2DTreeLearner)
    assert lr.residency == "hbm"
    assert b.num_trees() > 0


# -- elastic resume across grid shapes ----------------------------------
def _cli(args, tmp_path, faults=""):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    if faults:
        env["LAMBDAGAP_FAULTS"] = faults
    else:
        env.pop("LAMBDAGAP_FAULTS", None)
    return subprocess.run([sys.executable, "-m", "lambdagap_tpu", *args],
                          cwd=str(tmp_path), env=env, capture_output=True,
                          text=True, timeout=600)


def test_elastic_resume_across_grid_shapes(tmp_path):
    """Train on 4x2, SIGKILL mid-run, resume=auto on 2x4 and (from a
    fresh crash) on 8x1: final trees byte-identical to an uninterrupted
    4x2 run on the quantized path, and the resume logs the grid change
    read from the sidecar's mesh block."""
    rng = np.random.RandomState(3)
    X = rng.randn(2200, 6)
    y = X[:, 0] - 0.4 * X[:, 1] + 0.2 * rng.randn(2200)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    base = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=6", "snapshot_freq=1", "min_data_in_leaf=5",
            "verbose=1", "resume=auto", "tpu_fused_learner=1",
            "tree_learner=data", "use_quantized_grad=true",
            "stochastic_rounding=false"]

    def crash_then_resume(resume_grid):
        for f in os.listdir(str(tmp_path)):
            if ".snapshot_iter_" in f:
                os.remove(str(tmp_path / f))
        r = _cli(base + ["mesh_shape=4x2", "output_model=m_crash.txt"],
                 tmp_path, faults="crash_at_iter=3")
        assert r.returncode == -9, f"expected SIGKILL, got " \
            f"{r.returncode}: {r.stdout}\n{r.stderr}"
        r = _cli(base + [f"mesh_shape={resume_grid}",
                         "output_model=m_crash.txt"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        out = r.stdout + r.stderr
        assert "Resumed from snapshot" in out
        assert "elastic resume across grid shapes" in out
        return (tmp_path / "m_crash.txt").read_text() \
            .split("end of trees")[0]

    m24 = crash_then_resume("2x4")
    m81 = crash_then_resume("8x1")
    r = _cli(base + ["mesh_shape=4x2", "output_model=m_ref.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    ref = (tmp_path / "m_ref.txt").read_text().split("end of trees")[0]
    assert m24 == ref
    assert m81 == ref


def test_sidecar_mesh_block_carries_grid_shape():
    from lambdagap_tpu.guard.snapshot import capture_state
    X, y = _data(n=1500)
    b = _train(X, y, {"tree_learner": "data", "mesh_shape": "2x4"})
    state = capture_state(b._booster)
    assert state["mesh"]["axes"] == ["data", "feature"]
    assert state["mesh"]["shape"] == [2, 4]
    assert state["mesh"]["n_devices"] == 8
    assert state["mesh"]["n_loc"] * 2 == state["mesh"]["n_pad"]
