"""Streaming Sequence construction, cv details, timers, plotting.

(reference: basic.py:903 Sequence + test_basic.py:139-234 Sequence cases;
engine.py cv; USE_TIMETAG timer table; plotting.py)
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _data(n=900, d=5, seed=2):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = X @ rng.randn(d) + 0.1 * rng.randn(n)
    return X, y


class _NpSequence(lgb.Sequence):
    batch_size = 128

    def __init__(self, arr):
        self.arr = arr

    def __getitem__(self, idx):
        return self.arr[idx]

    def __len__(self):
        return len(self.arr)


def test_sequence_matches_matrix():
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1}
    b_mat = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    seqs = [_NpSequence(X[:400]), _NpSequence(X[400:])]
    b_seq = lgb.train(params, lgb.Dataset(seqs, label=y), num_boost_round=5)
    np.testing.assert_allclose(b_seq.predict(X), b_mat.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_cv_sklearn_splitter_and_train_metric():
    pytest.importorskip("sklearn")
    from sklearn.model_selection import KFold
    X, y = _data()
    res = lgb.cv({"objective": "regression", "num_leaves": 7, "verbose": -1,
                  "metric": "l2"},
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=5, folds=KFold(n_splits=3),
                 eval_train_metric=True)
    assert "valid l2-mean" in res
    assert "train l2-mean" in res
    assert len(res["valid l2-mean"]) == 5
    # train error below valid error on average (sanity)
    assert np.mean(res["train l2-mean"]) <= np.mean(res["valid l2-mean"]) + 1e-9


def test_cv_early_stopping_uses_first_metric():
    X, y = _data()
    res = lgb.cv({"objective": "regression", "num_leaves": 7, "verbose": -1,
                  "metric": ["l2", "l1"], "early_stopping_round": 3},
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=30, nfold=3)
    # converged training stops early and truncates consistently
    lens = {len(v) for v in res.values()}
    assert len(lens) == 1


def test_timer_report(monkeypatch):
    from lambdagap_tpu.utils import timer as T
    monkeypatch.setattr(T, "_ENABLED", True)
    T.global_timer.reset()
    X, y = _data(n=300)
    lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
              lgb.Dataset(X, label=y), num_boost_round=3)
    rep = T.global_timer.report()
    assert "tree:" in rep and "boosting: gradients" in rep
    T.global_timer.reset()


def test_plot_importance_without_display():
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    X, y = _data()
    b = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    ax = lgb.plot_importance(b)
    assert len(ax.patches) > 0
    recorded = {}
    b2 = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1,
                    "metric": "l2"},
                   lgb.Dataset(X, label=y), num_boost_round=5,
                   valid_sets=[lgb.Dataset(X[:200], label=y[:200],
                                           reference=None)],
                   callbacks=[lgb.record_evaluation(recorded)])
    ax2 = lgb.plot_metric(recorded)
    assert ax2.get_lines()


def test_sequence_subsampled_binning_and_reference():
    # total rows > bin_construct_sample_cnt exercises the sampled-binning
    # path; a reference-aligned Sequence valid set must share bins
    X, y = _data(n=3000)
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1,
              "bin_construct_sample_cnt": 500}
    dtrain = lgb.Dataset(_NpSequence(X[:2500]), label=y[:2500], params=params)
    dvalid = lgb.Dataset(_NpSequence(X[2500:]), label=y[2500:],
                         reference=dtrain)
    rec = {}
    lgb.train(params, dtrain, num_boost_round=5, valid_sets=[dvalid],
              callbacks=[lgb.record_evaluation(rec)])
    vals = rec["valid_0"]["l2"]
    assert vals[-1] < vals[0]
    tds, vds = dtrain.construct(), dvalid.construct()
    assert tds.mappers is vds.mappers
