"""Streaming Sequence construction, cv details, timers, plotting.

(reference: basic.py:903 Sequence + test_basic.py:139-234 Sequence cases;
engine.py cv; USE_TIMETAG timer table; plotting.py)
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _data(n=900, d=5, seed=2):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = X @ rng.randn(d) + 0.1 * rng.randn(n)
    return X, y


class _NpSequence(lgb.Sequence):
    batch_size = 128

    def __init__(self, arr):
        self.arr = arr

    def __getitem__(self, idx):
        return self.arr[idx]

    def __len__(self):
        return len(self.arr)


def test_sequence_matches_matrix():
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1}
    b_mat = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    seqs = [_NpSequence(X[:400]), _NpSequence(X[400:])]
    b_seq = lgb.train(params, lgb.Dataset(seqs, label=y), num_boost_round=5)
    np.testing.assert_allclose(b_seq.predict(X), b_mat.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_cv_sklearn_splitter_and_train_metric():
    pytest.importorskip("sklearn")
    from sklearn.model_selection import KFold
    X, y = _data()
    res = lgb.cv({"objective": "regression", "num_leaves": 7, "verbose": -1,
                  "metric": "l2"},
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=5, folds=KFold(n_splits=3),
                 eval_train_metric=True)
    assert "valid l2-mean" in res
    assert "train l2-mean" in res
    assert len(res["valid l2-mean"]) == 5
    # train error below valid error on average (sanity)
    assert np.mean(res["train l2-mean"]) <= np.mean(res["valid l2-mean"]) + 1e-9


def test_cv_early_stopping_uses_first_metric():
    X, y = _data()
    res = lgb.cv({"objective": "regression", "num_leaves": 7, "verbose": -1,
                  "metric": ["l2", "l1"], "early_stopping_round": 3},
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=30, nfold=3)
    # converged training stops early and truncates consistently
    lens = {len(v) for v in res.values()}
    assert len(lens) == 1


def test_timer_report(monkeypatch):
    from lambdagap_tpu.utils import timer as T
    monkeypatch.setattr(T, "_ENABLED", True)
    T.global_timer.reset()
    X, y = _data(n=300)
    lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
              lgb.Dataset(X, label=y), num_boost_round=3)
    rep = T.global_timer.report()
    assert "tree:" in rep and "boosting: gradients" in rep
    T.global_timer.reset()


def test_plot_importance_without_display():
    mpl = pytest.importorskip("matplotlib")
    mpl.use("Agg")
    X, y = _data()
    b = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    ax = lgb.plot_importance(b)
    assert len(ax.patches) > 0
    recorded = {}
    b2 = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1,
                    "metric": "l2"},
                   lgb.Dataset(X, label=y), num_boost_round=5,
                   valid_sets=[lgb.Dataset(X[:200], label=y[:200],
                                           reference=None)],
                   callbacks=[lgb.record_evaluation(recorded)])
    ax2 = lgb.plot_metric(recorded)
    assert ax2.get_lines()


def test_sequence_subsampled_binning_and_reference():
    # total rows > bin_construct_sample_cnt exercises the sampled-binning
    # path; a reference-aligned Sequence valid set must share bins
    X, y = _data(n=3000)
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1,
              "bin_construct_sample_cnt": 500}
    dtrain = lgb.Dataset(_NpSequence(X[:2500]), label=y[:2500], params=params)
    dvalid = lgb.Dataset(_NpSequence(X[2500:]), label=y[2500:],
                         reference=dtrain)
    rec = {}
    lgb.train(params, dtrain, num_boost_round=5, valid_sets=[dvalid],
              callbacks=[lgb.record_evaluation(rec)])
    vals = rec["valid_0"]["l2"]
    assert vals[-1] < vals[0]
    tds, vds = dtrain.construct(), dvalid.construct()
    assert tds.mappers is vds.mappers


def test_predict_shape_check():
    """Fewer predict columns than the model needs must fail loudly, unless
    predict_disable_shape_check pads with NaN (reference:
    predict_disable_shape_check)."""
    import pytest
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 6)
    y = (X[:, 5] > 0).astype(float)     # force use of the last feature
    b = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 7},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    with pytest.raises(Exception):
        b.predict(X[:10, :3])
    b2 = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 7,
                    "predict_disable_shape_check": True},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    p = b2.predict(X[:10, :3])          # missing columns ride as NaN
    assert np.all(np.isfinite(p))


def test_auc_mu_weights_matrix():
    """auc_mu_weights reshapes into the KxK cost matrix and changes the
    pairwise separating directions (reference: config.cpp
    auc_mu_weights_matrix)."""
    from sklearn.datasets import make_classification
    X, y = make_classification(1200, 8, n_informative=5, n_classes=3,
                               random_state=0)
    base = {"objective": "multiclass", "num_class": 3, "metric": "auc_mu",
            "verbose": -1}
    res1, res2 = {}, {}
    ds = lgb.Dataset(X, label=y)
    lgb.train(base, ds, num_boost_round=5, valid_sets=[lgb.Dataset(X, label=y)],
              callbacks=[lgb.record_evaluation(res1)])
    w = [0, 1, 5, 1, 0, 1, 5, 1, 0]
    lgb.train({**base, "auc_mu_weights": w}, lgb.Dataset(X, label=y),
              num_boost_round=5, valid_sets=[lgb.Dataset(X, label=y)],
              callbacks=[lgb.record_evaluation(res2)])
    a1 = res1["valid_0"]["auc_mu"][-1]
    a2 = res2["valid_0"]["auc_mu"][-1]
    assert 0.5 < a1 <= 1.0 and 0.5 < a2 <= 1.0
    assert a1 != a2


def test_booster_api_parity():
    """Reference Booster surface: pickling/deepcopy via the text model,
    eval() on arbitrary data matching the training-loop metrics,
    lower/upper_bound, get/set_leaf_output, get_split_value_histogram,
    model_from_string, shuffle_models (reference: python-package basic.py
    Booster methods)."""
    import copy
    import pickle
    from sklearn.datasets import make_classification
    X, y = make_classification(800, 6, random_state=0)
    res = {}
    b = lgb.train({"objective": "binary", "metric": "auc", "verbose": -1,
                   "num_leaves": 7},
                  lgb.Dataset(X, label=y), num_boost_round=4,
                  valid_sets=[lgb.Dataset(X, label=y)],
                  callbacks=[lgb.record_evaluation(res)])
    p0 = b.predict(X[:20])
    b2 = pickle.loads(pickle.dumps(b))
    np.testing.assert_allclose(b2.predict(X[:20]), p0, rtol=1e-6)
    b3 = copy.deepcopy(b)
    np.testing.assert_allclose(b3.predict(X[:20]), p0, rtol=1e-6)
    assert b.lower_bound() < b.upper_bound()
    ev = b3.eval(lgb.Dataset(X, label=y), "extra")
    assert ev[0][0] == "extra"
    assert abs(ev[0][2] - res["valid_0"]["auc"][-1]) < 1e-5
    hist, edges = b3.get_split_value_histogram(0)
    assert hist.sum() >= 0 and len(edges) == len(hist) + 1
    v = b.get_leaf_output(0, 0)
    b.set_leaf_output(0, 0, v + 1.0)
    assert abs(b.get_leaf_output(0, 0) - (v + 1.0)) < 1e-12
    assert not np.allclose(b.predict(X[:20]), p0)
    # model_from_string replaces the model in place
    b.model_from_string(b3.model_to_string())
    np.testing.assert_allclose(b.predict(X[:20]), p0, rtol=1e-6)
    # shuffled tree order leaves gbdt predictions unchanged (order-free sum)
    b3.shuffle_models()
    np.testing.assert_allclose(b3.predict(X[:20]), p0, rtol=1e-6)


def test_parameter_docs_in_sync():
    """docs/Parameters.md is generated from the Config dataclass (the
    config_auto pattern, reference: src/io/config_auto.cpp:6); the
    checked-in artifact must match a fresh generation."""
    import subprocess, sys, os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "gen_params_doc.py"),
         "--check"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def test_parameter_docs_cover_all_fields():
    import dataclasses, re, os
    from lambdagap_tpu.config import Config
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = open(os.path.join(root, "docs", "Parameters.md")).read()
    documented = set(re.findall(r"^\| `(\w+)`", doc, re.M))
    missing = {f.name for f in dataclasses.fields(Config)} - documented
    assert not missing, missing
