"""Model save/load round-trip (reference analog: model string tests in
tests/python_package_test/test_basic.py and gbdt_model_text.cpp round trip)."""
import numpy as np
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb


def test_model_string_roundtrip_regression():
    X, y = make_regression(800, 8, noise=3.0, random_state=0)
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "num_leaves": 15}, lgb.Dataset(X, label=y),
                        num_boost_round=12)
    s = booster.model_to_string()
    assert s.startswith("tree\n")
    assert "end of trees" in s
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(loaded.predict(X), booster.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_model_file_roundtrip_binary(tmp_path):
    X, y = make_classification(800, 10, random_state=1)
    booster = lgb.train({"objective": "binary", "verbose": -1},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "model.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), booster.predict(X),
                               rtol=1e-5, atol=1e-5)
    # sigmoid conversion preserved
    assert np.all((loaded.predict(X) >= 0) & (loaded.predict(X) <= 1))


def test_model_roundtrip_multiclass():
    X, y = make_classification(900, 10, n_classes=3, n_informative=6,
                               random_state=2)
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "verbose": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=8)
    loaded = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), booster.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_model_roundtrip_categorical():
    rng = np.random.RandomState(3)
    n = 1500
    cat = rng.randint(0, 6, n).astype(float)
    X = np.column_stack([cat, rng.randn(n)])
    y = (cat == 3) * 2.0 + X[:, 1]
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "num_leaves": 15, "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y, categorical_feature=[0]),
                        num_boost_round=10)
    loaded = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), booster.predict(X),
                               rtol=1e-5, atol=1e-5)


def test_num_iteration_predict():
    X, y = make_regression(500, 6, random_state=4)
    booster = lgb.train({"objective": "regression", "verbose": -1},
                        lgb.Dataset(X, label=y), num_boost_round=20)
    p5 = booster.predict(X, num_iteration=5)
    p20 = booster.predict(X)
    assert not np.allclose(p5, p20)
    s = booster.model_to_string(num_iteration=5)
    loaded = lgb.Booster(model_str=s)
    np.testing.assert_allclose(loaded.predict(X), p5, rtol=1e-5, atol=1e-5)


def test_feature_importance():
    X, y = make_regression(800, 8, n_informative=3, random_state=5)
    booster = lgb.train({"objective": "regression", "verbose": -1},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    imp_split = booster.feature_importance("split")
    imp_gain = booster.feature_importance("gain")
    assert imp_split.shape == (8,)
    assert imp_split.sum() > 0
    assert imp_gain.sum() > 0


def test_host_predict_matches_device():
    """Tree.predict_row (host reference semantics) agrees with the batched
    device traversal."""
    X, y = make_regression(600, 6, random_state=6)
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "num_leaves": 15}, lgb.Dataset(X, label=y),
                        num_boost_round=5)
    gb = booster._booster
    device = booster.predict(X[:50], raw_score=True)
    host = np.zeros(50)
    for tree in gb.models:
        for i in range(50):
            host[i] += tree.predict_row(X[i])
    np.testing.assert_allclose(device, host, rtol=1e-5, atol=1e-5)
