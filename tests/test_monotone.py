"""Monotone constraints (basic method).

(reference: src/treelearner/monotone_constraints.hpp BasicLeafConstraints;
test model: tests/python_package_test/test_engine.py test_monotone_constraints)
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _data(n=1500, seed=7):
    rng = np.random.RandomState(seed)
    x_inc = rng.rand(n)          # want monotone increasing
    x_dec = rng.rand(n)          # want monotone decreasing
    x_free = rng.rand(n)
    y = (5 * x_inc + np.sin(10 * np.pi * x_inc)
         - 5 * x_dec - np.cos(10 * np.pi * x_dec)
         + np.sin(10 * np.pi * x_free) + 0.1 * rng.randn(n))
    return np.column_stack([x_inc, x_dec, x_free]), y


def _is_monotone(booster, feature, sign, base_row, lo=0.0, hi=1.0):
    grid = np.linspace(lo, hi, 200)
    rows = np.tile(base_row, (len(grid), 1))
    rows[:, feature] = grid
    pred = booster.predict(rows)
    diffs = np.diff(pred)
    return (diffs * sign >= -1e-10).all()


@pytest.mark.parametrize("fused", [False, True])
def test_monotone_basic(fused):
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 10, "learning_rate": 0.1, "verbose": -1,
              "monotone_constraints": [1, -1, 0],
              "tpu_fused_learner": "1" if fused else "0",
              "tpu_hist_impl": "onehot"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30)
    rng = np.random.RandomState(1)
    for _ in range(5):
        base = rng.rand(3)
        assert _is_monotone(b, 0, +1, base), "feature 0 must be increasing"
        assert _is_monotone(b, 1, -1, base), "feature 1 must be decreasing"
    # the model still learns something
    resid = y - b.predict(X)
    assert np.var(resid) < 0.6 * np.var(y)


def test_unconstrained_violates():
    # sanity: without constraints the same data does wiggle (otherwise the
    # monotone assertions above prove nothing)
    X, y = _data()
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 10, "learning_rate": 0.1, "verbose": -1,
              "tpu_hist_impl": "onehot"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30)
    rng = np.random.RandomState(1)
    violated = any(not _is_monotone(b, 0, +1, rng.rand(3)) for _ in range(5))
    assert violated


def test_monotone_on_categorical_fatal():
    rng = np.random.RandomState(0)
    X = np.column_stack([rng.randint(0, 5, 300), rng.rand(300)])
    y = rng.rand(300)
    params = {"objective": "regression", "num_leaves": 7, "verbose": -1,
              "monotone_constraints": [1, 0]}
    with pytest.raises(Exception):
        lgb.train(params, lgb.Dataset(X, label=y, categorical_feature=[0]),
                  num_boost_round=2)


def test_monotone_intermediate_holds_and_beats_basic():
    """Intermediate method (reference: monotone_constraints.hpp:516
    IntermediateLeafConstraints): the property still holds, and the looser
    bounds recover accuracy vs basic on the same task."""
    X, y = _data(n=3000)
    common = {"objective": "regression", "num_leaves": 63,
              "min_data_in_leaf": 5, "learning_rate": 0.1, "verbose": -1,
              "monotone_constraints": [1, -1, 0],
              "tpu_hist_impl": "onehot"}
    basic = lgb.train({**common, "monotone_constraints_method": "basic"},
                      lgb.Dataset(X, label=y), num_boost_round=40)
    inter = lgb.train({**common,
                       "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=40)
    rng = np.random.RandomState(2)
    for _ in range(5):
        base = rng.rand(3)
        assert _is_monotone(inter, 0, +1, base)
        assert _is_monotone(inter, 1, -1, base)
    mse_basic = np.mean((y - basic.predict(X)) ** 2)
    mse_inter = np.mean((y - inter.predict(X)) ** 2)
    assert mse_inter <= mse_basic * 1.001, (mse_inter, mse_basic)
    # over-constraining differs: models should not be identical
    assert inter.model_to_string() != basic.model_to_string()


@pytest.mark.parametrize("fused", [False, True])
def test_monotone_penalty_pushes_splits_down(fused):
    """monotone_penalty >= depth+1 forbids monotone splits at that depth
    (reference: ComputeMonotoneSplitGainPenalty) — with penalty 2, levels
    0 and 1 must split on the unconstrained feature."""
    X, y = _data(n=2000)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 5, "learning_rate": 0.1, "verbose": -1,
              "monotone_constraints": [1, -1, 0],
              "monotone_penalty": 2.0,
              "tpu_fused_learner": "1" if fused else "0",
              "tpu_hist_impl": "onehot"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    dump = b.dump_model()
    for ti in dump["tree_info"]:
        def walk(node, depth):
            if "split_feature" not in node:
                return
            if depth < 2:
                assert node["split_feature"] == 2, \
                    f"monotone split at depth {depth}"
            walk(node["left_child"], depth + 1)
            walk(node["right_child"], depth + 1)
        walk(ti["tree_structure"], 0)
    # monotonicity still enforced
    rng = np.random.RandomState(3)
    assert _is_monotone(b, 0, +1, rng.rand(3))


def test_monotone_advanced_holds_and_beats_intermediate():
    """Advanced method (reference: monotone_constraints.hpp:858
    AdvancedLeafConstraints — re-designed here as per-leaf bin-space boxes
    + dense per-threshold bound arrays instead of recursive tree walks):
    monotonicity still holds, and the per-threshold granularity recovers
    accuracy the leaf-wide intermediate bounds give up."""
    rng = np.random.RandomState(7)
    n = 3000
    X = rng.rand(n, 3)
    # interaction between the constrained feature and x2 makes cross-leaf
    # constraints bind differently across x0 regions: exactly where
    # per-threshold bounds are looser than leaf-wide ones
    y = (2.0 * X[:, 0] + np.sin(3 * X[:, 1])
         + 0.7 * (X[:, 2] > 0.5) * X[:, 0] + 0.05 * rng.randn(n))
    common = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 20, "verbose": -1,
              "monotone_constraints": [1, 0, 0],
              "tpu_hist_impl": "onehot"}
    fit = lambda m: lgb.train({**common, "monotone_constraints_method": m},
                              lgb.Dataset(X, label=y), num_boost_round=15)
    inter = fit("intermediate")
    adv = fit("advanced")
    rng2 = np.random.RandomState(2)
    for _ in range(8):
        base = rng2.rand(3)
        assert _is_monotone(adv, 0, +1, base)
    mse_inter = np.mean((y - inter.predict(X)) ** 2)
    mse_adv = np.mean((y - adv.predict(X)) ** 2)
    assert mse_adv <= mse_inter * 1.001, (mse_adv, mse_inter)
    assert adv.model_to_string() != inter.model_to_string()


@pytest.mark.parametrize("method", ["basic", "intermediate", "advanced"])
@pytest.mark.parametrize("fused", [False, True])
def test_monotone_grid_sweep_all_methods(method, fused):
    """Constraint-violation sweep for every method on both learner routes
    (the fused route sends non-basic methods to the host-orchestrated
    learner — the user-facing parameter combination must hold either
    way): predictions over a dense grid of the constrained features must
    be monotone for random draws of the free feature."""
    X, y = _data(n=2000)
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 10, "verbose": -1,
              "monotone_constraints": [1, -1, 0],
              "monotone_constraints_method": method,
              "tpu_fused_learner": "1" if fused else "0",
              "tpu_hist_impl": "onehot"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    rng = np.random.RandomState(5)
    for _ in range(6):
        base = rng.rand(3)
        assert _is_monotone(b, 0, +1, base), (method, fused)
        assert _is_monotone(b, 1, -1, base), (method, fused)


def test_fused_intermediate_matches_host():
    """monotone_constraints_method=intermediate now runs INSIDE the fused
    whole-tree program (sibling-output child bounds + the vectorized
    cross-leaf propagation + eager re-scans of tightened leaves) and must
    reproduce the host learner's walk exactly (reference:
    monotone_constraints.hpp:560-850 IntermediateLeafConstraints)."""
    from lambdagap_tpu.models.fused_learner import FusedTreeLearner
    rng = np.random.RandomState(0)
    X = rng.randn(1500, 6)
    y = (2 * X[:, 0] + np.sin(X[:, 1] * 2) + 0.5 * X[:, 2] * X[:, 0]
         + 0.2 * rng.randn(1500))
    base = {"objective": "regression", "num_leaves": 31, "verbose": -1,
            "monotone_constraints": [1, 0, 0, 0, 0, 0],
            "monotone_constraints_method": "intermediate",
            "min_data_in_leaf": 5, "tpu_hist_impl": "onehot"}
    bh = lgb.train({**base, "tpu_fused_learner": "0"},
                   lgb.Dataset(X, label=y), num_boost_round=8)
    bf = lgb.train({**base, "tpu_fused_learner": "1"},
                   lgb.Dataset(X, label=y), num_boost_round=8)
    assert isinstance(bf._booster.learner, FusedTreeLearner)
    assert not isinstance(bh._booster.learner, FusedTreeLearner)
    ph, pf = bh.predict(X), bf.predict(X)
    close = np.isclose(ph, pf, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, float(close.mean())


def test_intermediate_distributed_and_voting():
    """Intermediate monotone rides the fused distributed programs: the
    data-parallel learner must build the same model on 1 and 8 shards
    (the propagation state is replicated-by-construction), and the fused
    voting learner's re-scan loop (collectives inside a while_loop with
    replicated trip counts) must train a monotone model."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.RandomState(1)
    X = rng.randn(1600, 5)
    y = 1.5 * X[:, 0] - X[:, 1] + 0.4 * rng.randn(1600)
    base = {"objective": "regression", "num_leaves": 15, "verbose": -1,
            "monotone_constraints": [1, -1, 0, 0, 0],
            "monotone_constraints_method": "intermediate",
            "min_data_in_leaf": 10, "tree_learner": "data"}
    b1 = lgb.train({**base, "tpu_num_devices": 1},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    b8 = lgb.train({**base, "tpu_num_devices": 8},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    close = np.isclose(b1.predict(X), b8.predict(X), rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, float(close.mean())
    bv = lgb.train({**base, "tree_learner": "voting", "top_k": 3,
                    "tpu_num_devices": 8},
                   lgb.Dataset(X, label=y), num_boost_round=8)
    base_row = np.full(5, 0.3)
    assert _is_monotone(bv, 0, +1, base_row)
    assert _is_monotone(bv, 1, -1, base_row)


def test_advanced_demotions_are_loud_and_routed():
    """advanced stays host-only on tree_learner=serial (warned demotion to
    the host-driven learner) and demotes to in-program 'intermediate' on
    the fused distributed learners (warned)."""
    from lambdagap_tpu.models.fused_learner import FusedTreeLearner
    from lambdagap_tpu.parallel.fused_parallel import \
        FusedDataParallelTreeLearner
    X, y = _data(n=1200)
    base = {"objective": "regression", "num_leaves": 15, "verbose": -1,
            "monotone_constraints": [1, -1, 0],
            "monotone_constraints_method": "advanced",
            "min_data_in_leaf": 10}
    b = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=3)
    assert not isinstance(b._booster.learner, FusedTreeLearner)
    bd = lgb.train({**base, "tree_learner": "data", "tpu_num_devices": 2},
                   lgb.Dataset(X, label=y), num_boost_round=3)
    lrn = bd._booster.learner
    assert isinstance(lrn, FusedDataParallelTreeLearner)
    assert bd._booster.config.monotone_constraints_method == "intermediate"
