"""Two-process distributed smoke test (DistributedMockup analog).

The reference tests distributed training by launching CLI subprocesses on
localhost (reference: tests/distributed/_test_distributed.py:53-120
DistributedMockup). Here two JAX processes join one runtime over a local
coordinator and run the core distributed primitive — a cross-process
histogram psum over a global mesh — verifying the DCN communication
backend end to end. (Full multi-device training parity is covered on the
virtual 8-device mesh in test_distributed.py.)
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import skip_unless_multiprocess

_CHILD = r"""
import os, sys
import numpy as np
sys.path.insert(0, os.getcwd())
# distributed init MUST precede any backend initialization (so before the
# package import, whose module-level jnp constants touch the backend)
import jax

rank = int(sys.argv[1])
port = sys.argv[2]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4          # 2 processes x 2 local cpu devices
from lambdagap_tpu.parallel.multiprocess import global_array_from_local

import jax.numpy as jnp
from lambdagap_tpu.parallel.sharding import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from lambdagap_tpu.ops.histogram import histogram_from_rows

mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
# every process holds its own 8-row block of the 16-row dataset
rng = np.random.RandomState(0)
full_bins = rng.randint(0, 8, (16, 3)).astype(np.uint8)
full_g = rng.randn(16).astype(np.float32)
lo, hi = rank * 8, rank * 8 + 8
x = global_array_from_local(full_bins[lo:hi], mesh, P("data", None))
g = global_array_from_local(full_g[lo:hi], mesh, P("data"))
h = global_array_from_local(np.ones(8, np.float32), mesh, P("data"))
m = global_array_from_local(np.ones(8, bool), mesh, P("data"))

def hist(x_l, g_l, h_l, m_l):
    local = histogram_from_rows(x_l, g_l, h_l, m_l, 8, 4096, "f32")
    return jax.lax.psum(local, "data")

op = jax.jit(shard_map(hist, mesh=mesh,
                       in_specs=(P("data", None), P("data"), P("data"),
                                 P("data")),
                       out_specs=P()))
out = np.asarray(op(x, g, h, m))
# verify against the full-data histogram computed locally
expect = np.zeros((3, 8, 3), np.float32)
for f in range(3):
    for r in range(16):
        b = full_bins[r, f]
        expect[f, b, 0] += full_g[r]
        expect[f, b, 1] += 1.0
        expect[f, b, 2] += 1.0
np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)
print(f"RANK{rank}_OK")
"""


def test_two_process_histogram_psum(tmp_path):
    skip_unless_multiprocess()
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    # strip the axon TPU-tunnel shim (PYTHONPATH site hook + env): the
    # children must run stock multi-process CPU jax
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen([sys.executable, str(script), str(r), port],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=os.getcwd(), env=env)
             for r in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process smoke test timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-2000:]}"
        assert f"RANK{r}_OK" in out


_CHILD_TRAIN = r"""
import os, sys
import numpy as np
sys.path.insert(0, os.getcwd())
import jax

rank = int(sys.argv[1])
port = sys.argv[2]
workdir = sys.argv[3]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)

import lambdagap_tpu as lgb
from lambdagap_tpu.config import Config
from lambdagap_tpu.parallel.multiprocess import load_pre_partitioned

quant = len(sys.argv) > 4 and sys.argv[4] == "quant"
cfg = Config.from_params({
    "objective": "binary", "tree_learner": "data", "num_leaves": 15,
    "min_data_in_leaf": 5, "verbose": -1, "pre_partition": True,
    "num_machines": 2, "bin_construct_sample_cnt": 2000,
    # quantized path: global |grad|/hess maxima are psum-agreed before
    # scale computation, so ranks histogram in identical integer units
    "use_quantized_grad": quant, "stochastic_rounding": False})
ds = load_pre_partitioned(os.path.join(workdir, f"part{rank}.tsv"), cfg)
assert ds.process_sharded and ds.global_num_data == 1600, ds.global_num_data

# drive the GBDT directly on the pre-partitioned dataset
from lambdagap_tpu.models.dart import create_boosting
g = create_boosting(cfg, ds)
for _ in range(5):
    g.train_one_iter()
model = g.save_model_to_string()
with open(os.path.join(workdir, f"model{rank}.txt"), "w") as f:
    f.write(model)
Xt = np.loadtxt(os.path.join(workdir, "test.tsv"))[:, 1:]
np.savetxt(os.path.join(workdir, f"pred{rank}.txt"), g.predict(Xt))
print(f"RANK{rank}_OK")
"""


@pytest.mark.parametrize("quant", [False, True])
def test_two_process_pre_partitioned_training(tmp_path, quant):
    """pre_partition=true end to end: two processes load DISJOINT files,
    sync bin mappers from allgathered samples, and train identical models
    over the multi-process mesh that match a single-process run
    (reference: dataset_loader.cpp:1072 + tests/distributed mockup).
    The quantized variant checks the global-scale agreement: int8
    gradient histograms psum only when every rank quantizes with the
    same (globally-maxed) scales."""
    skip_unless_multiprocess()
    import socket
    rng = np.random.RandomState(3)
    X = rng.randn(1600, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    Xt = rng.randn(400, 6)
    yt = (Xt[:, 0] + 0.5 * Xt[:, 1] > 0).astype(float)
    full = np.column_stack([y, X])
    np.savetxt(tmp_path / "part0.tsv", full[:800], delimiter="\t")
    np.savetxt(tmp_path / "part1.tsv", full[800:], delimiter="\t")
    np.savetxt(tmp_path / "full.tsv", full, delimiter="\t")
    np.savetxt(tmp_path / "test.tsv", np.column_stack([yt, Xt]),
               delimiter="\t")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "child_train.py"
    script.write_text(_CHILD_TRAIN)
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), port, str(tmp_path)]
        + (["quant"] if quant else []),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.getcwd(), env=env) for r in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("pre-partitioned training timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
        assert f"RANK{r}_OK" in out

    # both ranks must build the IDENTICAL model (identical mappers +
    # psum-reduced histograms)
    m0 = (tmp_path / "model0.txt").read_text()
    m1 = (tmp_path / "model1.txt").read_text()
    assert m0 == m1
    p0 = np.loadtxt(tmp_path / "pred0.txt")
    p1 = np.loadtxt(tmp_path / "pred1.txt")
    np.testing.assert_allclose(p0, p1, rtol=1e-6)

    # and it matches a single-process model on the same data (bin mappers
    # come from different samples, so exact equality is not expected)
    import lambdagap_tpu as lgb
    from sklearn.metrics import roc_auc_score
    single = lgb.train({"objective": "binary", "num_leaves": 15,
                        "min_data_in_leaf": 5, "verbose": -1},
                       lgb.Dataset(X, label=y), num_boost_round=5)
    auc_s = roc_auc_score(yt, single.predict(Xt))
    auc_d = roc_auc_score(yt, p0)
    assert auc_d > 0.9, auc_d
    # int8 quantization shifts individual splits; compare quality only
    assert abs(auc_s - auc_d) < (0.05 if quant else 0.03), (auc_s, auc_d)


def test_cli_pre_partitioned_training(tmp_path):
    """The full CLI flow: `python -m lambdagap_tpu pre_partition=true
    num_machines=2 machine_rank=R machines=...` — the distributed runtime
    joins BEFORE the package import touches the backend (__main__ early
    init), mappers sync, both ranks save identical models (reference: the
    distributed CLI mockup, tests/distributed/_test_distributed.py)."""
    skip_unless_multiprocess()
    import socket
    rng = np.random.RandomState(4)
    X = rng.randn(1200, 5)
    y = (X[:, 0] - 0.5 * X[:, 2] > 0).astype(float)
    full = np.column_stack([y, X])
    np.savetxt(tmp_path / "part0.tsv", full[:600], delimiter="\t")
    np.savetxt(tmp_path / "part1.tsv", full[600:], delimiter="\t")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.getcwd()
    procs = []
    for r in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "lambdagap_tpu",
             f"data={tmp_path}/part{r}.tsv", "task=train",
             "objective=binary", "num_leaves=15", "min_data_in_leaf=5",
             "num_iterations=4", "verbose=-1", "pre_partition=true",
             "num_machines=2", f"machine_rank={r}",
             f"machines=127.0.0.1:{port}", "tree_learner=data",
             f"output_model={tmp_path}/model{r}.txt"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(), env=env))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("CLI pre-partitioned training timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
    m0 = (tmp_path / "model0.txt").read_text()
    m1 = (tmp_path / "model1.txt").read_text()
    assert m0.split("\nparameters")[0] == m1.split("\nparameters")[0]


def test_train_cluster_single_call():
    """The Dask-module analog (reference: python-package/lightgbm/dask.py
    _train — machine list, ports, per-worker training driven
    automatically): one library call partitions the matrix, launches the
    workers, and returns the (rank-identical) model."""
    skip_unless_multiprocess()
    import lambdagap_tpu as lgb
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(8)
    X = rng.randn(1600, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    booster = lgb.train_cluster(
        {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbose": -1, "bin_construct_sample_cnt": 2000},
        X, y, num_workers=2, num_boost_round=5,
        worker_env={**env, "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                    "PYTHONPATH": ""})
    pred = booster.predict(X)
    assert roc_auc_score(y, pred) > 0.95
    # the multi-host recipe is exposed for operators
    assert len(booster.cluster_commands) == 2
    assert "machine_rank=1" in booster.cluster_commands[1]


def test_train_cluster_rank_groups():
    """Query-aligned partitioning: lambdarank over a cluster keeps every
    query on one rank."""
    skip_unless_multiprocess()
    import lambdagap_tpu as lgb
    rng = np.random.RandomState(9)
    n_q, per = 40, 30
    X = rng.randn(n_q * per, 5)
    y = rng.randint(0, 3, n_q * per).astype(float)
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    booster = lgb.train_cluster(
        {"objective": "lambdarank", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1, "bin_construct_sample_cnt": 1000},
        X, y, group=np.full(n_q, per), num_workers=2, num_boost_round=3,
        worker_env={**env, "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                    "PYTHONPATH": ""})
    assert booster.num_trees() == 3


def test_train_cluster_multihost_recipe(tmp_path):
    """The multi-host configuration the recipe documents: 2 coordinated
    processes EACH holding 4 virtual devices — an 8-device global mesh
    where the histogram psum crosses both the intra-process (ICI analog)
    and inter-process (DCN analog) boundaries (reference: the dask
    multi-worker tests, python-package/lightgbm/dask.py:375-415). Rank
    models must be identical, and with full-data bin samples the model
    must match single-process training."""
    skip_unless_multiprocess()
    import lambdagap_tpu as lgb
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(11)
    X = rng.randn(1600, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    booster = lgb.train_cluster(
        {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
         "verbose": -1, "bin_construct_sample_cnt": 1600},
        X, y, num_workers=2, num_boost_round=5,
        workdir=str(tmp_path), keep_files=True,
        worker_env={**env, "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                    "PYTHONPATH": ""})
    # every rank built the identical model over the 2x4 global mesh
    m0 = (tmp_path / "model0.txt").read_text()
    m1 = (tmp_path / "model1.txt").read_text()
    assert m0.split("\nparameters")[0] == m1.split("\nparameters")[0]
    # with sample_cnt == n each rank samples its full block without
    # replacement, so the allgathered sample is a permutation of the full
    # data and the equal-count mappers match single-process exactly
    single = lgb.train({"objective": "binary", "num_leaves": 15,
                        "min_data_in_leaf": 5, "verbose": -1,
                        "bin_construct_sample_cnt": 1600},
                       lgb.Dataset(X, label=y), num_boost_round=5)
    p_c, p_s = booster.predict(X), single.predict(X)
    assert roc_auc_score(y, p_c) > 0.95
    close = np.isclose(p_c, p_s, rtol=5e-3, atol=5e-3)
    assert close.mean() > 0.99, float(close.mean())
