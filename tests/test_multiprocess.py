"""Two-process distributed smoke test (DistributedMockup analog).

The reference tests distributed training by launching CLI subprocesses on
localhost (reference: tests/distributed/_test_distributed.py:53-120
DistributedMockup). Here two JAX processes join one runtime over a local
coordinator and run the core distributed primitive — a cross-process
histogram psum over a global mesh — verifying the DCN communication
backend end to end. (Full multi-device training parity is covered on the
virtual 8-device mesh in test_distributed.py.)
"""
import os
import subprocess
import sys

import numpy as np
import pytest

_CHILD = r"""
import os, sys
import numpy as np
sys.path.insert(0, os.getcwd())
# distributed init MUST precede any backend initialization (so before the
# package import, whose module-level jnp constants touch the backend)
import jax

rank = int(sys.argv[1])
port = sys.argv[2]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4          # 2 processes x 2 local cpu devices
from lambdagap_tpu.parallel.multiprocess import global_array_from_local

import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from lambdagap_tpu.ops.histogram import histogram_from_rows

mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
# every process holds its own 8-row block of the 16-row dataset
rng = np.random.RandomState(0)
full_bins = rng.randint(0, 8, (16, 3)).astype(np.uint8)
full_g = rng.randn(16).astype(np.float32)
lo, hi = rank * 8, rank * 8 + 8
x = global_array_from_local(full_bins[lo:hi], mesh, P("data", None))
g = global_array_from_local(full_g[lo:hi], mesh, P("data"))
h = global_array_from_local(np.ones(8, np.float32), mesh, P("data"))
m = global_array_from_local(np.ones(8, bool), mesh, P("data"))

def hist(x_l, g_l, h_l, m_l):
    local = histogram_from_rows(x_l, g_l, h_l, m_l, 8, 4096, "f32")
    return jax.lax.psum(local, "data")

op = jax.jit(shard_map(hist, mesh=mesh,
                       in_specs=(P("data", None), P("data"), P("data"),
                                 P("data")),
                       out_specs=P()))
out = np.asarray(op(x, g, h, m))
# verify against the full-data histogram computed locally
expect = np.zeros((3, 8, 3), np.float32)
for f in range(3):
    for r in range(16):
        b = full_bins[r, f]
        expect[f, b, 0] += full_g[r]
        expect[f, b, 1] += 1.0
        expect[f, b, 2] += 1.0
np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)
print(f"RANK{rank}_OK")
"""


def test_two_process_histogram_psum(tmp_path):
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    # strip the axon TPU-tunnel shim (PYTHONPATH site hook + env): the
    # children must run stock multi-process CPU jax
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen([sys.executable, str(script), str(r), port],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              cwd=os.getcwd(), env=env)
             for r in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process smoke test timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-2000:]}"
        assert f"RANK{r}_OK" in out
