"""lambdagap_tpu.obs (graftscope): phase spans, ring buffer, JSONL schema,
recompile watchdog, Prometheus export, serve `stats` line, timer shim.

The ISSUE-4 acceptance surface: per-iteration phase spans must tile the
measured iteration wall (±10%), the emitted JSONL must validate against
the documented schema, the telemetry-off path must add zero records and
zero jax.monitoring hooks, and the watchdog must fire on a forced
steady-state recompile.
"""
import io
import json
import os
import re

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.obs import events, prom
from lambdagap_tpu.obs.telemetry import NULL_TELEMETRY, TrainTelemetry


def _data(n=500, d=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X[:, 0] + 0.2 * rng.randn(n) > 0).astype(np.float32)
    return X, y


def _train(extra=None, n=500, rounds=8, valid=False):
    X, y = _data(n)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1,
              **(extra or {})}
    kwargs = {}
    if valid:
        Xv, yv = _data(200, seed=1)
        kwargs["valid_sets"] = [lgb.Dataset(Xv, label=yv)]
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds, **kwargs)


# -- phase spans --------------------------------------------------------
def test_phase_spans_sum_to_iteration_wall():
    b = _train({"telemetry": True}, rounds=8)
    tel = b._booster.telemetry
    recs = list(tel.records)
    assert len(recs) == 8
    # skip iteration 0: boost-from-average + compiles land in untracked
    # gaps there; steady-state iterations must tile the wall within 10%
    for rec in recs[1:]:
        span_sum = sum(v for k, v in rec["phases"].items() if k != "eval")
        wall = rec["wall_s"]
        # phases are sub-intervals of the wall window, so the sum can
        # never meaningfully exceed it; the lower bound is the ±10% gate
        assert span_sum <= wall * 1.05 + 1e-3, (rec, span_sum)
        assert span_sum >= wall * 0.90 - 1e-3, (rec, span_sum)


def test_phase_records_cover_expected_phases():
    b = _train({"telemetry": True}, valid=True)
    rec = list(b._booster.telemetry.records)[-1]
    # serial learner on CPU: sub-phases recorded inside the tree span
    for phase in ("gradients", "sampling", "histogram", "split",
                  "partition", "tree", "score_update", "eval",
                  "device_wait"):
        assert phase in rec["phases"], rec["phases"]
    assert rec["iter"] == 7


# -- ring buffer --------------------------------------------------------
def test_ring_buffer_eviction():
    b = _train({"telemetry": True, "telemetry_ring": 4}, rounds=10)
    tel = b._booster.telemetry
    assert tel.iterations == 10
    recs = list(tel.records)
    assert len(recs) == 4
    assert [r["iter"] for r in recs] == [6, 7, 8, 9]


# -- JSONL schema -------------------------------------------------------
def test_jsonl_schema_roundtrip(tmp_path):
    out = str(tmp_path / "run.jsonl")
    _train({"telemetry_out": out}, rounds=5)
    lines = [ln for ln in open(out) if ln.strip()]
    objs = [json.loads(ln) for ln in lines]       # every record parses
    assert objs[0]["type"] == "run_header"
    assert objs[0]["schema_version"] == events.SCHEMA_VERSION
    assert objs[0]["params"]["num_leaves"] == 7
    iters = [o for o in objs if o["type"] == "iteration"]
    assert [o["iter"] for o in iters] == list(range(5))
    for o in iters:
        assert set(o) >= {"iter", "phases", "compiles", "transfers",
                          "wall_s"}
        assert o["compiles"]["total"] >= 0
    assert events.validate_file(out) == []


def test_jsonl_validator_rejects_bad_records(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"type":"iteration","iter":0}\nnot json\n')
    errs = events.validate_file(str(p))
    assert any("run_header" in e for e in errs)
    assert any("not JSON" in e for e in errs)
    assert any("missing" in e for e in errs)
    assert events.validate_file.__module__ == "lambdagap_tpu.obs.events"


# -- telemetry-off path -------------------------------------------------
def test_off_path_no_records_no_hooks():
    from jax._src import monitoring as m
    before = (len(m.get_event_listeners()),
              len(m.get_event_duration_listeners()))
    b = _train(rounds=3)
    tel = b._booster.telemetry
    assert not tel.enabled
    assert len(tel.records) == 0 and tel.iterations == 0
    after = (len(m.get_event_listeners()),
             len(m.get_event_duration_listeners()))
    assert before == after
    # and the enabled path unhooks again at close (engine.train closes)
    b2 = _train({"telemetry": True}, rounds=3)
    assert b2._booster.telemetry.enabled
    final = (len(m.get_event_listeners()),
             len(m.get_event_duration_listeners()))
    assert final == before


# -- Prometheus ---------------------------------------------------------
_PROM_HEADER = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")
_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$")


def test_prometheus_output_parses_line_by_line():
    from lambdagap_tpu.serve.stats import ServeStats
    b = _train({"telemetry": True}, rounds=4)
    stats = ServeStats()
    stats.record_request(0.001, 0.002, 0.004, rows=3)
    stats.record_cache(True, bucket=8)
    # labeled per-model/per-tenant + registry forms (ISSUE 9) must pass
    # the same line grammar
    stats.record_request(0.001, 0.001, 0.003, rows=2, model="default",
                         tenant="acme corp")
    stats.record_timeout(model="default", tenant="acme corp")
    stats.record_eviction(model="default")
    stats.record_readmission(model="default")
    snapshot = stats.snapshot()
    snapshot["registry"] = {"registered_models": 2, "resident_models": 1,
                            "hbm_bytes_resident": 4096,
                            "hbm_budget_bytes": 8192,
                            "models": {"default": {"resident": True},
                                       "b": {"resident": False}}}
    text = prom.render(telemetry=b._booster.telemetry,
                       serve_snapshot=snapshot)
    lines = [ln for ln in text.splitlines() if ln]
    assert len(lines) > 40
    for ln in lines:
        if ln.startswith("#"):
            assert _PROM_HEADER.match(ln), f"bad header line: {ln!r}"
            continue
        m = _PROM_SAMPLE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        float(m.group(3))            # value parses as a float
        if m.group(2):               # labels parse as key="value" pairs
            assert re.fullmatch(
                r'\{([a-zA-Z_][a-zA-Z0-9_]*="[^"]*")'
                r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\}', m.group(2))
    # spot-check names and a labeled sample
    assert "lambdagap_train_phase_seconds_total{phase=\"tree\"}" in text
    assert "lambdagap_serve_requests_total 2" in text
    assert "lambdagap_serve_latency_ms{quantile=\"p99\"}" in text
    # the ISSUE-9 labeled forms
    assert 'lambdagap_serve_model_requests_total{model="default"} 1' in text
    assert 'lambdagap_serve_tenant_shed_total{tenant="acme corp"} 1' in text
    assert ('lambdagap_serve_tenant_latency_ms{quantile="p50",'
            'tenant="acme corp"}') in text
    assert "lambdagap_serve_evictions_total 1" in text
    assert 'lambdagap_serve_registry_model_resident{model="b"} 0' in text
    assert "lambdagap_serve_registry_hbm_budget_bytes 8192" in text


_PROM_LABELS_ESCAPED = re.compile(
    r'\{([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*")'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*")*\}')


def test_prometheus_hostile_label_values_escaped():
    """Model/tenant names are user-supplied strings; the exposition must
    escape backslash/quote/newline per the format spec, so a hostile name
    can neither break a sample line nor inject one (ISSUE 12)."""
    from lambdagap_tpu.serve.stats import ServeStats
    stats = ServeStats()
    evil_model = 'm"x\\y\nz'
    evil_tenant = '\\"end\n# HELP fake_metric injected'
    stats.record_request(0.001, 0.002, 0.003, rows=1, model=evil_model,
                         tenant=evil_tenant)
    stats.record_timeout(model=evil_model, tenant=evil_tenant)
    snapshot = stats.snapshot()
    snapshot["registry"] = {"registered_models": 1, "resident_models": 1,
                            "hbm_bytes_resident": 1, "hbm_budget_bytes": 0,
                            "models": {evil_model: {"resident": True}}}
    text = prom.render_serve(snapshot)
    for ln in [ln for ln in text.splitlines() if ln]:
        if ln.startswith("#"):
            assert _PROM_HEADER.match(ln), f"bad header line: {ln!r}"
            continue
        m = _PROM_SAMPLE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        float(m.group(3))
        if m.group(2):
            assert _PROM_LABELS_ESCAPED.fullmatch(m.group(2)), \
                f"label values not exposition-escaped: {ln!r}"
    # escaped forms present; the injection attempt never starts a line
    assert '\\"end\\n# HELP' in text
    assert not any(ln.startswith("# HELP fake_metric")
                   for ln in text.splitlines())


def test_prometheus_router_exposition_parses_and_labels():
    snap = {"failovers": 3, "rejected_no_replica": 1,
            "replicas": {"r0": {"routed": 10, "inflight": 2,
                                "health": "ok", "dead": False},
                         "r1": {"routed": 4, "inflight": 0,
                                "health": "dead", "dead": True}}}
    text = prom.render_router(snap)
    for ln in [ln for ln in text.splitlines() if ln]:
        if ln.startswith("#"):
            assert _PROM_HEADER.match(ln), ln
        else:
            assert _PROM_SAMPLE.match(ln), ln
    assert "lambdagap_router_failovers_total 3" in text
    assert 'lambdagap_router_replica_routed_total{replica="r0"} 10' in text
    assert ('lambdagap_router_replica_health{replica="r1",state="dead"} 1'
            in text)
    assert ('lambdagap_router_replica_health{replica="r1",state="ok"} 0'
            in text)


# -- recompile watchdog -------------------------------------------------
def test_watchdog_fires_on_steady_state_recompile():
    import jax
    import jax.numpy as jnp
    tel = TrainTelemetry(enabled=True, warmup=1)
    try:
        tel.begin_iteration(5)                  # > warmup: steady state
        with tel.phase("tree"):
            # a brand-new jitted callable forces a fresh backend compile
            jax.jit(lambda x: x * 3 + 1)(jnp.ones(13, jnp.float32))
        tel.end_iteration()
    finally:
        tel.close()
    rec = list(tel.records)[-1]
    assert rec["compiles"]["total"] >= 1
    assert rec["compiles"]["steady"] >= 1
    assert rec["compiles"]["by_phase"].get("tree", 0) >= 1
    assert tel.watchdog.steady_compiles >= 1


def test_watchdog_quiet_during_warmup():
    import jax
    import jax.numpy as jnp
    tel = TrainTelemetry(enabled=True, warmup=10)
    try:
        tel.begin_iteration(0)
        jax.jit(lambda x: x - 7)(jnp.ones(11, jnp.float32))
        tel.end_iteration()
    finally:
        tel.close()
    rec = list(tel.records)[-1]
    assert rec["compiles"]["total"] >= 1
    assert rec["compiles"]["steady"] == 0


# -- serve stats line ---------------------------------------------------
def test_serve_loop_stats_lines():
    from lambdagap_tpu.serve import serve_loop
    b = _train(rounds=3)
    X, _ = _data(4)
    server = b.as_server()
    try:
        lines = ["\t".join(str(v) for v in X[0]),
                 "stats", "stats json",
                 "\t".join(str(v) for v in X[1])]
        out, stats = io.StringIO(), io.StringIO()
        n = serve_loop(server, lines, out, stats_stream=stats)
    finally:
        server.close()
    assert n == 2
    text = stats.getvalue()
    assert "lambdagap_serve_requests_total" in text
    # the JSON snapshot rides the same stream after the exposition
    snap = json.loads(text[text.index("\n{") + 1:])
    assert "latency_ms" in snap and "generation" in snap
    # predictions untouched by the stats lines
    assert len(out.getvalue().strip().splitlines()) == 2


# -- utils.timer shim (use-time enablement) -----------------------------
def test_timer_enablement_is_use_time(monkeypatch):
    from lambdagap_tpu.utils import timer as T
    monkeypatch.delenv("LAMBDAGAP_TIMETAG", raising=False)
    monkeypatch.setattr(T, "_ENABLED", False)
    assert not T.timer_enabled()
    # flipping the env var AFTER import takes effect immediately
    monkeypatch.setenv("LAMBDAGAP_TIMETAG", "1")
    assert T.timer_enabled()
    T.global_timer.reset()
    with T.global_timer.scope("probe"):
        pass
    assert T.global_timer.counts["probe"] == 1
    T.global_timer.reset()


def test_timer_shim_receives_telemetry_phases(monkeypatch):
    from lambdagap_tpu.utils import timer as T
    monkeypatch.setattr(T, "_ENABLED", True)
    T.global_timer.reset()
    _train(rounds=3)
    rep = T.global_timer.report()
    # legacy scope names survive via the deprecation shim
    assert "tree:" in rep and "boosting: gradients" in rep
    T.global_timer.reset()


# -- shared reservoir ---------------------------------------------------
def test_reservoir_shared_between_obs_and_serve():
    from lambdagap_tpu.obs.reservoir import Reservoir
    from lambdagap_tpu.serve import stats as serve_stats
    assert serve_stats._Reservoir is Reservoir
    r = Reservoir(cap=10, seed=3)
    for i in range(1000):
        r.add(float(i))
    assert len(r.vals) == 10 and r.seen == 1000
    p = r.percentiles()
    assert 0.0 <= p["p50"] <= 999.0 and p["max"] <= 999.0


def test_null_telemetry_is_inert():
    assert not NULL_TELEMETRY.enabled
    NULL_TELEMETRY.begin_iteration(0)
    with NULL_TELEMETRY.phase("tree"):
        pass
    NULL_TELEMETRY.end_iteration()
    NULL_TELEMETRY.close()
    assert len(NULL_TELEMETRY.records) == 0
    assert NULL_TELEMETRY.summary() == {"enabled": False}


# -- profiler window knobs ---------------------------------------------
def test_profile_window_toggles(tmp_path):
    from lambdagap_tpu.obs.profile import ProfileWindow
    pw = ProfileWindow(start_iter=2, n_iters=2, out_dir=str(tmp_path))
    assert pw.enabled
    assert pw.on_iteration_start(0) is None
    assert pw.on_iteration_start(2) == "start"
    assert pw.on_iteration_start(3) is None
    assert pw.on_iteration_start(4) == "stop"
    assert pw.done
    # and the whole window rides an actual training run without error
    b = _train({"profile_start_iter": 1, "profile_n_iters": 1,
                "profile_dir": str(tmp_path / "t")}, rounds=4)
    assert b._booster.telemetry.enabled


def test_telemetry_off_by_default_in_config():
    from lambdagap_tpu.config import Config
    cfg = Config()
    assert not cfg.telemetry and cfg.telemetry_out == ""
    cfg2 = Config.from_params({"telemetry": "true", "telemetry_ring": 8})
    assert cfg2.telemetry and cfg2.telemetry_ring == 8
    with pytest.raises(RuntimeError):
        Config.from_params({"telemetry_ring": 0})
