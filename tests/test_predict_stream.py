"""predict_stream — out-of-core batch scoring (ISSUE 18, infer/stream.py).

The tier-1 acceptance surface, all on CPU:

- streamed scores are BIT-IDENTICAL (``array_equal``) to the resident
  predict on every engine (compiled/tensor/scan), every window
  raggedness, memmap-backed inputs/outputs, NaN + categorical features,
  multiclass, linear leaves, and every virtual mesh grid (1x8/2x4/8x1 —
  conftest.py forces 8 virtual CPU devices);
- file and ShardedBinnedDataset sources parse/traverse to the same bits
  as the resident paths;
- ``pred_contrib`` tiles match the resident SHAP matrix exactly and rows
  sum to the raw prediction;
- the pumped pass is compile-free in steady state (pow2 bucket pre-warm)
  with the ``d2h_scores`` phase live in the telemetry;
- the co-tenant throttle backs off under a scripted pressure signal and
  recovers when it clears.
"""
import os
import tempfile

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.data.stream import ShardedBinnedDataset
from lambdagap_tpu.guard.backoff import Backoff
from lambdagap_tpu.infer.stream import CoTenantThrottle, _pow2_bucket

ROWS = 1603          # ragged against every window size used below


def _data(n=ROWS, d=10, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    X[rng.rand(n, d) < 0.05] = np.nan          # missing values live
    X[:, 3] = rng.randint(0, 7, n)             # categorical column
    y = (np.nan_to_num(X[:, 0]) + 0.5 * (X[:, 3] % 3)
         + 0.1 * rng.randn(n))
    return X, y


def _train(X, y, extra=None, rounds=6, objective="regression"):
    params = {"objective": objective, "num_leaves": 15,
              "min_data_in_leaf": 10, "learning_rate": 0.2, "verbose": -1,
              "tpu_fast_predict_rows": 0, "deterministic": True}
    if extra:
        params.update(extra)
    ds = lgb.Dataset(X, label=y, categorical_feature=[3], params=params)
    return lgb.train(params, ds, num_boost_round=rounds)


@pytest.fixture(scope="module")
def reg():
    X, y = _data()
    return _train(X, y), X


@pytest.fixture(scope="module")
def multi():
    X, _ = _data(seed=13)
    rng = np.random.RandomState(13)
    y = rng.randint(0, 3, ROWS)
    return _train(X, y, {"num_class": 3}, objective="multiclass"), X


# -- engine x raggedness parity ------------------------------------------
@pytest.mark.parametrize("engine", ["tensor", "scan", "compiled"])
@pytest.mark.parametrize("window_rows", [256, 512, 1 << 16])
def test_engine_parity_bit_identical(reg, engine, window_rows):
    bst, X = reg
    gb = bst._booster
    gb.config.predict_engine = engine
    gb.invalidate_predict_cache()
    try:
        ref = gb.predict_raw(X)
        got = gb.predict_stream(X, raw_score=True, window_rows=window_rows)
        assert np.array_equal(ref, got)
    finally:
        gb.config.predict_engine = "tensor"
        gb.invalidate_predict_cache()


# -- mesh grids ----------------------------------------------------------
@pytest.mark.parametrize("grid", ["1x8", "2x4", "8x1"])
def test_mesh_grid_parity_bit_identical(multi, grid):
    bst, X = multi
    gb = bst._booster
    ref = gb.predict_raw(X)
    gb.config.mesh_shape = grid
    gb._pstream_cache = None
    try:
        got = gb.predict_stream(X, raw_score=True, window_rows=256)
        assert np.array_equal(ref, got)
    finally:
        gb.config.mesh_shape = ""
        gb._pstream_cache = None


# -- sources -------------------------------------------------------------
def test_memmap_source_and_memmap_out(reg, tmp_path):
    bst, X = reg
    gb = bst._booster
    ref = gb.predict_raw(X)
    mp = tmp_path / "x.mm"
    mm = np.memmap(mp, dtype=np.float32, mode="w+", shape=X.shape)
    mm[:] = X
    mm.flush()
    om = np.memmap(tmp_path / "scores.mm", dtype=np.float32, mode="w+",
                   shape=(ROWS,))
    r = gb.predict_stream(mm, raw_score=True, window_rows=512, out=om)
    assert r is om
    assert np.array_equal(ref, np.asarray(om))


def test_file_source_csv_parity(reg, tmp_path):
    bst, X = reg
    # file parse must equal Booster.predict(path): NaN-free matrix (csv
    # text round-trips finite doubles exactly at %.17g)
    Xf = np.nan_to_num(np.asarray(X, np.float64))
    y = np.zeros(len(Xf))
    p = str(tmp_path / "rows.csv")
    np.savetxt(p, np.concatenate([y[:, None], Xf], axis=1),
               delimiter=",", fmt="%.17g")
    ref = bst.predict(p, raw_score=True)
    got = bst.predict_stream(p, raw_score=True, window_rows=256)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_file_source_libsvm_parity(reg, tmp_path):
    bst, X = reg
    Xf = np.nan_to_num(np.asarray(X, np.float64))
    p = str(tmp_path / "rows.svm")
    with open(p, "w") as f:
        for row in Xf:
            feats = " ".join(f"{j}:{v:.17g}" for j, v in enumerate(row)
                             if v != 0.0)
            f.write(f"0 {feats}\n")
    ref = bst.predict(p, raw_score=True)
    got = bst.predict_stream(p, raw_score=True, window_rows=256)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_sharded_binned_source_parity(reg):
    bst, X = reg
    gb = bst._booster
    ref = gb.predict_raw(X)
    sds = ShardedBinnedDataset.from_dataset(gb.train_set, shard_rows=1024)
    got = gb.predict_stream(sds, raw_score=True, window_rows=512)
    assert np.array_equal(ref, got)


# -- payload shapes ------------------------------------------------------
def test_multiclass_and_converted_output(multi):
    bst, X = multi
    gb = bst._booster
    ref_raw = gb.predict_raw(X)
    got_raw = gb.predict_stream(X, raw_score=True, window_rows=512)
    assert np.array_equal(ref_raw, got_raw)
    # objective conversion (softmax) parity with the resident device path
    ref = np.asarray(bst.predict(X))
    got = np.asarray(gb.predict_stream(X, window_rows=512))
    assert np.array_equal(ref.astype(np.float32), got.astype(np.float32))


def test_linear_leaf_parity():
    rng = np.random.RandomState(5)
    X = rng.randn(ROWS, 8).astype(np.float32)
    y = (X[:, 0] * 2.0 - X[:, 1]).astype(np.float32)
    params = {"objective": "regression", "linear_tree": True,
              "num_leaves": 10, "verbose": -1, "tpu_fast_predict_rows": 0}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=4)
    gb = bst._booster
    ref = gb.predict_raw(X)
    got = gb.predict_stream(X, raw_score=True, window_rows=256)
    assert np.array_equal(ref, got)


def test_pred_contrib_matches_resident_and_sums(multi):
    bst, X = multi
    gb = bst._booster
    sub = X[:700]
    ref = gb.predict_contrib(sub)
    got = gb.predict_stream(sub, pred_contrib=True, window_rows=256)
    assert np.array_equal(ref, got)
    # rows sum exactly to the raw prediction, per class
    raw = np.asarray(gb.predict_raw(sub), np.float64)
    K, F1 = 3, sub.shape[1] + 1
    sums = got.reshape(len(sub), K, F1).sum(axis=2)
    np.testing.assert_allclose(sums, raw, rtol=1e-5, atol=1e-6)


# -- overlap telemetry + compile discipline ------------------------------
def test_zero_steady_compiles_and_d2h_phase(reg):
    bst, X = reg
    gb = bst._booster
    gb._pstream_cache = None
    stats = {}
    got = gb.predict_stream(X, raw_score=True, window_rows=256,
                            stats_out=stats)
    assert np.array_equal(gb.predict_raw(X), got)
    assert stats["windows"] == -(-ROWS // 256)
    assert stats["rows"] == ROWS
    # ragged tail padded to its own pow2 bucket; steady window + tail
    assert set(stats["buckets"]) == {256, _pow2_bucket(ROWS % 256, 256, 1)}
    # both transfer directions measured
    assert stats["phases"].get("h2d_prefetch", 0.0) > 0.0
    assert "d2h_scores" in stats["phases"]
    # the pumped pass never compiles inside a window record (buckets are
    # pre-warmed before the pump opens)
    steady = sum(r.get("compiles", {}).get("steady", 0)
                 for r in stats["records"] if r.get("type") == "iteration")
    assert steady == 0


def test_scorer_cache_replays_across_calls(reg):
    bst, X = reg
    gb = bst._booster
    gb._pstream_cache = None
    a = gb.predict_stream(X, raw_score=True, window_rows=512)
    cache = gb._pstream_cache
    b = gb.predict_stream(X, raw_score=True, window_rows=512)
    assert gb._pstream_cache is cache       # same scorer object replayed
    assert np.array_equal(a, b)


# -- co-tenant throttle --------------------------------------------------
def _sig(margin, frac=0.99):
    return {"goodput": {"knee_rps": 100.0, "knee_margin": margin,
                        "good_fraction": frac, "good_ratio": 0.9}}


def test_throttle_backs_off_and_recovers():
    # 4 pressured checks then healthy forever: delays double, then one
    # healthy check resets the backoff clock
    sigs = iter([_sig(0.02)] * 4 + [_sig(0.5)] * 100)
    slept = []
    th = CoTenantThrottle(
        lambda: next(sigs),
        backoff=Backoff(base_s=0.01, factor=2.0, max_s=10.0, jitter=0.0,
                        seed=1),
        sleep=slept.append)
    for _ in range(8):
        th()
    assert slept == [0.01, 0.02, 0.04, 0.08]
    assert th.waits == 4 and th.checks == 8
    assert not th.engaged                    # recovered
    # fresh pressure after recovery starts over at the base delay
    sigs2 = iter([_sig(0.02)])
    th._source = lambda: next(sigs2)
    th()
    assert slept[-1] == 0.01


def test_throttle_pressure_on_low_goodput():
    th = CoTenantThrottle(lambda: _sig(0.5, frac=0.5), sleep=lambda s: None)
    th()
    assert th.engaged and th.waits == 1


def test_throttle_gates_window_issue_and_scores_stay_exact(reg):
    bst, X = reg
    gb = bst._booster
    ref = gb.predict_raw(X)
    sigs = iter([_sig(0.02)] * 3 + [_sig(0.5)] * 100)
    slept = []
    th = CoTenantThrottle(
        lambda: next(sigs),
        backoff=Backoff(base_s=0.01, factor=2.0, max_s=0.1, jitter=0.0,
                        seed=1),
        sleep=slept.append)
    got = gb.predict_stream(X, raw_score=True, window_rows=128, throttle=th)
    assert np.array_equal(ref, got)          # throttling never changes bits
    assert th.waits == 3 and slept == [0.01, 0.02, 0.04]
    assert not th.engaged


def test_throttle_off_knob_disarms(reg):
    bst, X = reg
    gb = bst._booster
    gb.config.predict_stream_throttle = "off"
    try:
        calls = []
        th = CoTenantThrottle(lambda: calls.append(1) or _sig(0.02),
                              sleep=lambda s: None)
        gb.predict_stream(X, raw_score=True, window_rows=512, throttle=th)
        assert not calls                     # gate never consulted
    finally:
        gb.config.predict_stream_throttle = "auto"


def test_dead_signal_source_never_kills_the_job(reg):
    bst, X = reg
    gb = bst._booster

    def broken():
        raise RuntimeError("signal plane gone")

    th = CoTenantThrottle(broken, sleep=lambda s: None)
    got = gb.predict_stream(X, raw_score=True, window_rows=512, throttle=th)
    assert np.array_equal(gb.predict_raw(X), got)
    assert th.waits == 0


# -- API surface ---------------------------------------------------------
def test_booster_level_wrapper(reg):
    bst, X = reg
    ref = bst.predict(X, raw_score=True)
    got = bst.predict_stream(X, raw_score=True, window_rows=512)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_empty_model_scores_zeros(reg):
    bst, X = reg
    gb = bst._booster
    got = gb.predict_stream(X, raw_score=True, num_iteration=0)
    assert got.shape == (ROWS,)
    assert not got.any()


def test_pow2_bucketing():
    assert _pow2_bucket(1, 1 << 16, 1) == 1
    assert _pow2_bucket(67, 512, 1) == 128
    assert _pow2_bucket(512, 512, 1) == 512
    assert _pow2_bucket(700, 512, 1) == 512       # capped at the window
    assert _pow2_bucket(67, 512, 8) == 128        # already a multiple
    assert _pow2_bucket(2, 512, 8) == 8           # rounded to the grid
