"""Tensorized traversal engine parity (ISSUE 3 acceptance gate).

The tensorized [rows x trees] engine (ops/predict_tensor.py) must be
BIT-IDENTICAL to the sequential per-tree oracle (ops/predict.py) — not
close, equal: the engine contract is the same f32 accumulation order, so
every assertion here is ``array_equal``. Coverage: ragged tree tiles (tree
counts that don't divide the tile), NaN/default-left routing, zero-missing,
categorical bitset splits (single- and multi-word), multiclass tree->class
routing, early-stop margins, and both binned and raw-float inputs.
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb

jnp = pytest.importorskip("jax.numpy")

from lambdagap_tpu.ops.predict import (forest_to_arrays, predict_forest,
                                       predict_forest_leaf)
from lambdagap_tpu.ops.predict_tensor import (predict_forest_leaf_tensor,
                                              predict_forest_tensor)


def _forest_of(booster, binned=False):
    gb = booster._booster
    trees = gb.host_models
    K = gb.num_tree_per_iteration
    tc = jnp.asarray([i % K for i in range(len(trees))], jnp.int32)
    if binned:
        forest, depth = forest_to_arrays(trees, feature_meta=gb._meta,
                                         use_inner_feature=True)
        x = jnp.asarray(gb.train_set.binned)
    else:
        forest, depth = forest_to_arrays(trees, use_inner_feature=False)
        x = None
    return gb, forest, depth, tc, K, x


def _assert_engine_parity(booster, X, tiles=(5, 64), es=(0, 0.0)):
    """predict_forest_tensor == predict_forest bit-for-bit, raw AND binned,
    across ragged tile sizes."""
    es_freq, es_margin = es
    gb, forest, depth, tc, K, _ = _forest_of(booster)
    xr = jnp.asarray(np.asarray(X, np.float32))
    ref = np.asarray(predict_forest(xr, forest, tc, K, depth, binned=False,
                                    early_stop_freq=es_freq,
                                    early_stop_margin=es_margin))
    for tile in tiles:
        got = np.asarray(predict_forest_tensor(
            xr, forest, tc, K, depth, binned=False, early_stop_freq=es_freq,
            early_stop_margin=es_margin, tree_tile=tile))
        assert np.array_equal(ref, got), \
            f"raw parity broke at tree_tile={tile}"
    gb, forest_b, depth_b, tc, K, xb = _forest_of(booster, binned=True)
    ref_b = np.asarray(predict_forest(xb, forest_b, tc, K, depth_b,
                                      binned=True, early_stop_freq=es_freq,
                                      early_stop_margin=es_margin))
    for tile in tiles:
        got_b = np.asarray(predict_forest_tensor(
            xb, forest_b, tc, K, depth_b, binned=True,
            early_stop_freq=es_freq, early_stop_margin=es_margin,
            tree_tile=tile))
        assert np.array_equal(ref_b, got_b), \
            f"binned parity broke at tree_tile={tile}"


def test_binary_nan_default_left_parity():
    rng = np.random.RandomState(0)
    X = rng.randn(2500, 10).astype(np.float32)
    X[::7, 2] = np.nan                       # NaN-missing routing
    X[::5, 4] = 0.0                          # zero bin
    y = (X[:, 0] - 0.4 * X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 31, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=13)   # 13 % tile
    _assert_engine_parity(b, X[:600])


def test_zero_as_missing_parity():
    rng = np.random.RandomState(1)
    X = rng.randn(2000, 8).astype(np.float32)
    X[rng.rand(2000, 8) < 0.3] = 0.0
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "zero_as_missing": True},
                  lgb.Dataset(X, label=y), num_boost_round=9)
    _assert_engine_parity(b, X[:500])


def test_categorical_bitset_parity():
    rng = np.random.RandomState(2)
    X = rng.randn(3000, 6).astype(np.float32)
    # single-word (values < 256) and multi-word (values up to ~900,
    # exercising the W > 8 raw-category bitsets) categorical columns
    X[:, 4] = rng.randint(0, 40, 3000)
    X[:, 5] = rng.choice([3, 17, 256, 511, 899], 3000)
    y = (X[:, 0] + (X[:, 4] % 3 == 0) + (X[:, 5] > 300)).astype(np.float32)
    b = lgb.train({"objective": "regression", "num_leaves": 31,
                   "verbose": -1, "categorical_feature": [4, 5],
                   "max_cat_to_onehot": 2},
                  lgb.Dataset(X, label=y), num_boost_round=11)
    Xq = X[:600].copy()
    Xq[::9, 4] = np.nan                      # NaN category -> dummy bin
    Xq[::13, 5] = 1234.0                     # unseen category
    _assert_engine_parity(b, Xq)


def test_multiclass_routing_parity():
    rng = np.random.RandomState(3)
    X = rng.randn(2400, 9).astype(np.float32)
    y = (np.abs(X[:, 0]) + X[:, 1] > 1).astype(int) + \
        (X[:, 2] > 0.5).astype(int)
    b = lgb.train({"objective": "multiclass", "num_class": 3,
                   "num_leaves": 15, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=7)   # 21 trees
    _assert_engine_parity(b, X[:500], tiles=(5, 64))


def test_early_stop_margin_parity():
    rng = np.random.RandomState(4)
    X = rng.randn(2600, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=24)
    # freq=5 does not divide the tile sizes: the accumulation scan must
    # reproduce the oracle's exact check points
    _assert_engine_parity(b, X[:400], tiles=(4, 64), es=(5, 0.6))


def test_leaf_index_parity():
    rng = np.random.RandomState(5)
    X = rng.randn(2000, 10).astype(np.float32)
    X[::6, 1] = np.nan
    y = rng.randn(2000).astype(np.float32)
    b = lgb.train({"objective": "regression", "num_leaves": 31,
                   "verbose": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=10)
    gb, forest, depth, tc, K, _ = _forest_of(b)
    xr = jnp.asarray(X[:300])
    ref = np.asarray(predict_forest_leaf(xr, forest, depth, binned=False))
    for tile in (3, 64):
        got = np.asarray(predict_forest_leaf_tensor(
            xr, forest, depth, binned=False, tree_tile=tile))
        assert np.array_equal(ref, got)
    gb, forest_b, depth_b, tc, K, xb = _forest_of(b, binned=True)
    ref_b = np.asarray(predict_forest_leaf(xb[:300], forest_b, depth_b,
                                           binned=True))
    got_b = np.asarray(predict_forest_leaf_tensor(
        xb[:300], forest_b, depth_b, binned=True, tree_tile=4))
    assert np.array_equal(ref_b, got_b)


def test_booster_engine_switch_bit_identical():
    """End-to-end: predict_engine=tensor and =scan agree bit-for-bit on
    the device path (native small-batch route disabled), including
    pred_early_stop and multiclass output layout."""
    rng = np.random.RandomState(6)
    X = rng.randn(2200, 12).astype(np.float32)
    X[::8, 3] = np.nan
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(int) + (X[:, 2] > 1)
    for params, es in (
            ({"objective": "binary", "num_leaves": 31}, False),
            ({"objective": "binary", "num_leaves": 31,
              "pred_early_stop": True, "pred_early_stop_freq": 3,
              "pred_early_stop_margin": 0.5}, True),
            ({"objective": "multiclass", "num_class": 3,
              "num_leaves": 15}, False)):
        b = lgb.train({**params, "verbose": -1},
                      lgb.Dataset(X, label=(y > 0) if params[
                          "objective"] == "binary" else y),
                      num_boost_round=10)
        gb = b._booster
        gb.config.tpu_fast_predict_rows = 0     # force the device path
        outs = {}
        for eng in ("scan", "tensor"):
            gb.config.predict_engine = eng
            gb.invalidate_predict_cache()
            outs[eng] = b.predict(X[:700])
        assert np.array_equal(outs["scan"], outs["tensor"]), \
            f"engine mismatch for {params} (early_stop={es})"


def test_serve_tensor_engine_bit_identical_and_reported():
    """The serving path under the tensor engine matches the one-shot
    device predict bit-for-bit, and the stats snapshot reports which
    engine served plus its measured device us/row."""
    rng = np.random.RandomState(7)
    X = rng.randn(2000, 8).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 31, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    gb = b._booster
    assert gb.config.predict_engine == "tensor"     # the serving default
    fast = gb.config.tpu_fast_predict_rows
    gb.config.tpu_fast_predict_rows = 0
    ref = b.predict(X[:600])
    gb.config.tpu_fast_predict_rows = fast
    server = b.as_server()
    try:
        got = np.concatenate([server.predict(X[i:i + 37])
                              for i in range(0, 592, 37)])
        assert np.array_equal(got, ref[:592])
        snap = server.stats_snapshot()
        assert snap["engine"] == "tensor"
        assert snap["device_us_per_row"] > 0.0
    finally:
        server.close()


def test_binned_replay_paths_use_engine():
    """resume_from / add_valid_set replay through the configured engine;
    a resumed booster's scores must match continued training under the
    scan engine exactly."""
    rng = np.random.RandomState(8)
    X = rng.randn(1500, 6).astype(np.float32)
    y = rng.randn(1500).astype(np.float32)
    scores = {}
    for eng in ("scan", "tensor"):
        params = {"objective": "regression", "num_leaves": 15,
                  "verbose": -1, "predict_engine": eng}
        b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
        b2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=4,
                       init_model=b)
        scores[eng] = b2.predict(X[:400])
    assert np.array_equal(scores["scan"], scores["tensor"])
