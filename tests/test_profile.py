"""obs/profile.py — bounded jax.profiler capture windows.

The ISSUE-19 satellite surface: window start/stop boundaries for every
unit (training iteration, serve request, stream window), a short run's
``close()`` stopping a window left open, and the disabled path staying a
complete no-op (no profiler import, no trace started).
"""
import threading

import pytest

from lambdagap_tpu.obs.profile import ProfileWindow


class _FakeProfiler:
    """Stands in for jax.profiler: records start/stop without tracing."""

    def __init__(self):
        self.starts = []
        self.stops = 0

    def install(self, monkeypatch):
        import jax.profiler
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda out_dir: self.starts.append(out_dir))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: setattr(self, "stops", self.stops + 1))


# -- boundaries ---------------------------------------------------------
def test_window_start_stop_boundaries(monkeypatch, tmp_path):
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    pw = ProfileWindow(start_iter=3, n_iters=2, out_dir=str(tmp_path))
    assert pw.enabled
    toggles = {i: pw.on_iteration_start(i) for i in range(8)}
    # starts exactly AT start_iter, stops exactly n_iters later
    assert toggles == {0: None, 1: None, 2: None, 3: "start", 4: None,
                      5: "stop", 6: None, 7: None}
    assert fake.starts == [str(tmp_path)]
    assert fake.stops == 1
    assert pw.done and not pw.active


def test_window_units_drive_on_tick(monkeypatch, tmp_path):
    # the serve/stream units use the same boundary machinery via on_tick
    for unit in ("serve_request", "stream_window"):
        fake = _FakeProfiler()
        fake.install(monkeypatch)
        pw = ProfileWindow(start_iter=1, n_iters=1, out_dir=str(tmp_path),
                           unit=unit)
        assert pw.on_tick(0) is None
        assert pw.on_tick(1) == "start"
        assert pw.on_tick(2) == "stop"
        assert pw.on_tick(3) is None           # one window per run
        assert fake.starts and fake.stops == 1


def test_self_counting_tick(monkeypatch, tmp_path):
    # serve submits have no natural index: tick() counts calls itself
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    pw = ProfileWindow(start_iter=2, n_iters=1, out_dir=str(tmp_path),
                       unit="serve_request")
    got = [pw.tick() for _ in range(5)]
    assert got == [None, None, "start", "stop", None]


def test_concurrent_ticks_start_once(monkeypatch, tmp_path):
    # many serve workers race the same window: exactly one start/stop
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    pw = ProfileWindow(start_iter=0, n_iters=1, out_dir=str(tmp_path),
                       unit="serve_request")
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        for _ in range(20):
            pw.tick()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(fake.starts) == 1
    assert fake.stops == 1


# -- short runs ---------------------------------------------------------
def test_close_stops_short_run_window(monkeypatch, tmp_path):
    # run ends INSIDE the window: close() must stop the open trace
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    pw = ProfileWindow(start_iter=1, n_iters=100, out_dir=str(tmp_path))
    pw.on_iteration_start(0)
    pw.on_iteration_start(1)
    assert pw.active and fake.starts
    pw.close(2)
    assert not pw.active and pw.done
    assert fake.stops == 1
    pw.close(3)                                # idempotent
    assert fake.stops == 1


def test_close_without_start_is_noop(monkeypatch, tmp_path):
    fake = _FakeProfiler()
    fake.install(monkeypatch)
    pw = ProfileWindow(start_iter=50, n_iters=1, out_dir=str(tmp_path))
    pw.on_iteration_start(0)
    pw.close(1)
    assert fake.starts == [] and fake.stops == 0


# -- disabled path ------------------------------------------------------
@pytest.mark.parametrize("kwargs", [
    {},                                        # both defaults off
    {"start_iter": 5},                         # no out_dir
    {"out_dir": "/tmp/x"},                     # no start_iter
    {"start_iter": -1, "out_dir": "/tmp/x"},   # explicit off
])
def test_disabled_window_is_inert(monkeypatch, kwargs):
    # the disabled path must never touch jax.profiler at all
    import jax.profiler

    def boom(*a, **k):  # pragma: no cover - failing is the assertion
        raise AssertionError("disabled ProfileWindow touched jax.profiler")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    pw = ProfileWindow(**kwargs)
    assert not pw.enabled
    for i in range(10):
        assert pw.on_iteration_start(i) is None
        assert pw.on_tick(i) is None
    pw.close(10)
    assert not pw.active and not pw.done
