"""Quantized-gradient training.

(reference: src/treelearner/gradient_discretizer.hpp GradientDiscretizer;
test model: tests/python_package_test/test_basic.py parametrized
use_quantized_grad cases)
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(s)); ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    np_, nn = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - np_ * (np_ + 1) / 2) / (np_ * nn)


def _data(n=4000, d=12, seed=9):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    logits = X @ rng.randn(d) * 0.8 + np.sin(X[:, 0] * 3) + rng.randn(n)
    return X, (logits > 0).astype(np.float64)


@pytest.mark.parametrize("qb,renew", [(64, False), (16, True)])
def test_quantized_close_to_fp32(qb, renew):
    Xa, ya = _data(n=6000)
    X, y = Xa[:4000], ya[:4000]
    Xv, yv = Xa[4000:], ya[4000:]
    base = {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
            "min_data_in_leaf": 20, "verbose": -1, "tpu_fused_learner": "1",
            "tpu_hist_impl": "onehot"}
    b_fp = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=30)
    b_q = lgb.train({**base, "use_quantized_grad": True,
                     "num_grad_quant_bins": qb,
                     "quant_train_renew_leaf": renew},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    auc_fp = _auc(yv, b_fp.predict(Xv))
    auc_q = _auc(yv, b_q.predict(Xv))
    assert auc_fp > 0.8
    assert auc_q > auc_fp - 0.02, (auc_fp, auc_q)


def test_quantized_regression_converges():
    rng = np.random.RandomState(3)
    X = rng.randn(2000, 8)
    y = X[:, 0] * 2 + np.abs(X[:, 1]) + 0.05 * rng.randn(2000)
    params = {"objective": "regression", "num_leaves": 31, "verbose": -1,
              "use_quantized_grad": True, "num_grad_quant_bins": 64,
              "tpu_fused_learner": "1", "tpu_hist_impl": "onehot"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=40)
    rmse = float(np.sqrt(np.mean((b.predict(X) - y) ** 2)))
    assert rmse < 0.35 * np.std(y)


def test_quantized_pallas_kernel_on_accelerator():
    # the int8 kernel itself (exercised in CI only when a TPU is attached;
    # the CPU suite covers the dequantized-onehot semantics above)
    import jax
    if jax.default_backend() == "cpu":
        pytest.skip("pallas int8 kernel needs a TPU backend")
    import jax.numpy as jnp
    from lambdagap_tpu.ops.hist_pallas import hist_pallas_q, pack_ghq8
    rng = np.random.RandomState(0)
    P, F, B = 4096, 6, 64
    bins = jnp.asarray(rng.randint(0, B, (P, F), dtype=np.uint8))
    gq = jnp.asarray(rng.randint(-50, 51, P), jnp.int8)
    hq = jnp.asarray(rng.randint(0, 100, P), jnp.int8)
    valid = jnp.asarray(rng.rand(P) < 0.8)
    out = np.asarray(hist_pallas_q(bins, pack_ghq8(gq, hq, valid), B))
    b_np = np.asarray(bins); v = np.asarray(valid)
    for f in (0, 3):
        for b in (0, 17):
            sel = (b_np[:, f] == b) & v
            assert out[f, b, 0] == np.asarray(gq)[sel].sum()
            assert out[f, b, 1] == np.asarray(hq)[sel].sum()
            assert out[f, b, 2] == sel.sum()
