"""Ranking objective/metric tests (reference analog: test_engine.py
lambdarank tests :736-835 + the fork's 18-target surface)."""
import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.config import LAMBDARANK_TARGETS


def _make_ltr(n_queries=60, docs_per_query=25, n_features=10, seed=0):
    """Synthetic LTR data: relevance depends on a few features."""
    rng = np.random.RandomState(seed)
    n = n_queries * docs_per_query
    X = rng.randn(n, n_features)
    util = 2.0 * X[:, 0] + X[:, 1] + 0.5 * rng.randn(n)
    labels = np.zeros(n)
    group = np.full(n_queries, docs_per_query)
    for q in range(n_queries):
        s = slice(q * docs_per_query, (q + 1) * docs_per_query)
        u = util[s]
        ranks = np.argsort(np.argsort(-u))
        lab = np.zeros(docs_per_query)
        lab[ranks < 3] = 2
        lab[(ranks >= 3) & (ranks < 8)] = 1
        labels[s] = lab
    return X, labels, group


def _ndcg_at(booster, X, labels, group, k=5):
    scores = booster.predict(X, raw_score=True)
    qb = np.concatenate([[0], np.cumsum(group)]).astype(int)
    vals = []
    for qi in range(len(group)):
        s, e = qb[qi], qb[qi + 1]
        order = np.argsort(-scores[s:e])
        l = labels[s:e][order].astype(int)
        disc = 1.0 / np.log2(2.0 + np.arange(len(l)))
        dcg = np.sum((2.0 ** l[:k] - 1) * disc[:k])
        li = np.sort(labels[s:e].astype(int))[::-1]
        mdcg = np.sum((2.0 ** li[:k] - 1) * disc[:k])
        vals.append(dcg / mdcg if mdcg > 0 else 1.0)
    return float(np.mean(vals))


def test_lambdarank_learns_ranking():
    X, labels, group = _make_ltr()
    ds = lgb.Dataset(X, label=labels, group=group)
    booster = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                         "eval_at": [5], "num_leaves": 15, "verbose": -1,
                         "min_data_in_leaf": 5},
                        ds, num_boost_round=40)
    ndcg = _ndcg_at(booster, X, labels, group)
    assert ndcg > 0.85


def test_lambdarank_ndcg_metric_reported():
    X, labels, group = _make_ltr(seed=1)
    ds = lgb.Dataset(X, label=labels, group=group)
    vs = ds.create_valid(X, label=labels, group=group)
    res = {}
    lgb.train({"objective": "lambdarank", "metric": "ndcg",
               "eval_at": [1, 3, 5], "verbose": -1, "min_data_in_leaf": 5},
              ds, num_boost_round=15, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert "ndcg@1" in res["valid_0"]
    assert "ndcg@5" in res["valid_0"]
    assert res["valid_0"]["ndcg@5"][-1] > res["valid_0"]["ndcg@5"][0] - 1e-9


@pytest.mark.parametrize("target", LAMBDARANK_TARGETS)
def test_all_lambdarank_targets_train(target):
    """Every one of the fork's 18 gradient targets produces a learning model
    (reference: rank_objective.hpp:22-41)."""
    X, labels, group = _make_ltr(n_queries=30, docs_per_query=15, seed=2)
    ds = lgb.Dataset(X, label=labels, group=group)
    booster = lgb.train({"objective": "lambdarank",
                         "lambdarank_target": target,
                         "lambdarank_truncation_level": 5,
                         "num_leaves": 7, "verbose": -1,
                         "min_data_in_leaf": 3},
                        ds, num_boost_round=15)
    assert booster.num_trees() > 0
    ndcg = _ndcg_at(booster, X, labels, group)
    assert ndcg > 0.6, f"target {target} failed to learn: ndcg={ndcg}"


def test_lambdagap_weight_changes_gradients():
    X, labels, group = _make_ltr(seed=3)
    preds = []
    for w in (0.1, 5.0):
        booster = lgb.train({"objective": "lambdarank",
                             "lambdarank_target": "lambdaloss-ndcg-plus-plus",
                             "lambdagap_weight": w, "verbose": -1,
                             "min_data_in_leaf": 5},
                            lgb.Dataset(X, label=labels, group=group),
                            num_boost_round=10)
        preds.append(booster.predict(X, raw_score=True))
    assert not np.allclose(preds[0], preds[1])


def test_rank_xendcg():
    X, labels, group = _make_ltr(seed=4)
    booster = lgb.train({"objective": "rank_xendcg", "verbose": -1,
                         "min_data_in_leaf": 5, "num_leaves": 15},
                        lgb.Dataset(X, label=labels, group=group),
                        num_boost_round=40)
    assert _ndcg_at(booster, X, labels, group) > 0.8


def test_query_ids_as_group():
    """Per-row query ids are accepted in place of group sizes."""
    X, labels, group = _make_ltr(n_queries=20, seed=5)
    qid = np.repeat(np.arange(20), 25)
    b1 = lgb.train({"objective": "lambdarank", "verbose": -1,
                    "min_data_in_leaf": 5},
                   lgb.Dataset(X, label=labels, group=group), num_boost_round=5)
    b2 = lgb.train({"objective": "lambdarank", "verbose": -1,
                    "min_data_in_leaf": 5},
                   lgb.Dataset(X, label=labels, group=qid), num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-5)


def test_position_bias():
    X, labels, group = _make_ltr(seed=6)
    pos = np.tile(np.arange(25), 60)
    booster = lgb.train({"objective": "lambdarank", "verbose": -1,
                         "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=labels, group=group, position=pos),
                        num_boost_round=10)
    obj = booster._booster.objective
    assert obj.pos_biases is not None
    assert obj.pos_biases.shape == (25,)
    # biases moved away from zero
    assert float(np.abs(np.asarray(obj.pos_biases)).sum()) > 0


def test_precision_metric():
    X, labels, group = _make_ltr(seed=7)
    ds = lgb.Dataset(X, label=labels, group=group)
    vs = ds.create_valid(X, label=labels, group=group)
    res = {}
    lgb.train({"objective": "lambdarank", "metric": "precision",
               "eval_at": [3, 5], "verbose": -1, "min_data_in_leaf": 5},
              ds, num_boost_round=10, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert "precision@3" in res["valid_0"]
    assert 0 <= res["valid_0"]["precision@3"][-1] <= 1


def test_map_metric():
    X, labels, group = _make_ltr(seed=8)
    ds = lgb.Dataset(X, label=(labels > 0).astype(float), group=group)
    vs = ds.create_valid(X, label=(labels > 0).astype(float), group=group)
    res = {}
    lgb.train({"objective": "lambdarank", "metric": "map", "eval_at": [5],
               "verbose": -1, "min_data_in_leaf": 5},
              ds, num_boost_round=10, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(res)])
    assert "map@5" in res["valid_0"]


@pytest.mark.parametrize("target", ["ndcg", "ranknet", "lambdagap-x",
                                    "arpk", "lambdaloss-ndcg-plus-plus"])
def test_tiled_pair_lattice_matches_dense(target):
    """The row-tiled long-query kernel computes EXACTLY the dense lattice's
    math (same pair windows, same normalization) — block sweeps only bound
    memory (reference handles arbitrary query lengths the same way,
    rank_objective.hpp:253-524)."""
    import jax.numpy as jnp
    from lambdagap_tpu.objectives.rank import _lambdarank_bucket
    rng = np.random.RandomState(7)
    nq, L = 3, 256
    scores = jnp.asarray(rng.randn(nq, L).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 4, (nq, L)).astype(np.float32))
    valid = jnp.asarray(np.arange(L)[None, :] < np.asarray([256, 200, 37])[:, None])
    inv_dcg = jnp.asarray(rng.rand(nq).astype(np.float32))
    inv_bdcg = jnp.asarray(rng.rand(nq).astype(np.float32))
    gains = jnp.asarray((2.0 ** np.arange(4) - 1).astype(np.float32))
    kw = dict(target=target, sigmoid=1.0, norm=True, truncation_level=20,
              lambdagap_weight=0.5)
    lam_d, hes_d, eff_d = _lambdarank_bucket(scores, labels, valid, inv_dcg,
                                             inv_bdcg, gains, tile=None, **kw)
    lam_t, hes_t, eff_t = _lambdarank_bucket(scores, labels, valid, inv_dcg,
                                             inv_bdcg, gains, tile=64, **kw)
    np.testing.assert_allclose(np.asarray(lam_d), np.asarray(lam_t),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(hes_d), np.asarray(hes_t),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(eff_d), np.asarray(eff_t),
                               rtol=1e-5)


def test_long_query_trains_without_truncation():
    """A query longer than any dense-lattice bound trains exactly: every
    doc can receive gradient mass (the pre-round-5 16,384-doc truncation is
    gone; click-log datasets routinely exceed it)."""
    rng = np.random.RandomState(3)
    n = 20000                      # ONE query, past the old 1<<14 cap
    X = rng.randn(n, 6)
    util = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n)
    ranks = np.argsort(np.argsort(-util))
    y = np.zeros(n)
    y[ranks < 50] = 2
    y[(ranks >= 50) & (ranks < 500)] = 1
    b = lgb.train({"objective": "lambdarank", "num_leaves": 15,
                   "lambdarank_truncation_level": 30, "verbose": -1,
                   "min_data_in_leaf": 20},
                  lgb.Dataset(X, label=y, group=[n]), num_boost_round=5)
    from lambdagap_tpu.objectives.rank import _QueryBuckets
    bk = _QueryBuckets(np.asarray([0, n]), n)
    assert bk.buckets[0][0] == 32768    # padded, not capped
    s = b.predict(X, raw_score=True)
    # the learned order must separate relevant docs (gradient mass reached
    # the whole query, not just a truncated prefix)
    top = np.argsort(-s)[:50]
    assert y[top].mean() > 0.5
