"""lambdagap_tpu.serve — batched, hot-swappable inference.

Covers the ISSUE-1 acceptance surface: padding-bucket outputs bit-identical
to the device ``Booster.predict`` path (incl. ragged chunks), micro-batcher
coalescing, cache hit accounting (compile-once forest), atomic hot-swap
under concurrent load (no dropped/torn responses), and the booster-side
device-forest cache reuse (ADVICE predict.py:313).
"""
import threading
import time

import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb
from lambdagap_tpu.serve import ForestServer


# tpu_fast_predict_rows=0 drops the native small-batch shortcut to its
# 512-row floor, so a >512-row Booster.predict takes the device path the
# serve cache must match bit-for-bit
DEVICE_PARAMS = {"verbose": -1, "tpu_fast_predict_rows": 0}


def _train_binary(rows=1500, feats=12, rounds=12, seed=0, **extra):
    X, y = make_classification(rows, feats, n_informative=6,
                               random_state=seed)
    X = X.astype(np.float32)
    X[::17, 3] = np.nan
    params = {"objective": "binary", "num_leaves": 15, **DEVICE_PARAMS,
              **extra}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds), X


def test_bucket_outputs_bit_identical_to_device_predict():
    b, X = _train_binary()
    ref = b.predict(X[:600])        # 600 > 512 rows -> device path
    with b.as_server(buckets=(1, 8, 64), warmup=True) as s:
        # every bucket + ragged sizes + chunking past the largest bucket
        sizes = [1, 3, 8, 11, 64, 100, 129]
        outs, lo = [], 0
        for n in sizes:
            outs.append(s.predict(X[lo:lo + n]))
            lo += n
        got = np.concatenate(outs)
        assert lo <= 600
        assert np.array_equal(got, ref[:lo]), \
            "serve outputs must be bit-identical"
        # ISSUE 9: the same rows through every FLEET path must stay
        # bit-identical to the device predict — the explicit registry
        # route, the health-aware router, and the socket frontend (JSON
        # floats carry shortest-roundtrip reprs; f32->f64->f32 is exact)
        got_named = np.concatenate([s.predict(X[i:i + 37], model="default",
                                              tenant="parity")
                                    for i in range(0, 111, 37)])
        assert np.array_equal(got_named, ref[:111])
        from lambdagap_tpu.serve import (FrontendClient, LocalReplica,
                                         Router, ServeFrontend)
        with Router([LocalReplica("a", s)]) as router:
            got_routed = np.concatenate([router.predict(X[i:i + 29],
                                                        timeout=30)
                                         for i in range(0, 87, 29)])
        assert np.array_equal(got_routed, ref[:87])
        with ServeFrontend(s) as fe:
            with FrontendClient("127.0.0.1", fe.port) as client:
                got_wire = np.concatenate([client.predict(X[i:i + 41])
                                           for i in range(0, 123, 41)])
        assert np.array_equal(got_wire, np.asarray(ref[:123], np.float32))


def test_multiclass_and_raw_score_match():
    X, y = make_classification(1200, 10, n_informative=6, n_classes=3,
                               random_state=3)
    X = X.astype(np.float32)
    b = lgb.train({"objective": "multiclass", "num_class": 3,
                   **DEVICE_PARAMS}, lgb.Dataset(X, label=y),
                  num_boost_round=6)
    ref = b.predict(X[:600])
    ref_raw = b.predict(X[:600], raw_score=True)
    with b.as_server(buckets=(8, 64)) as s:
        got = np.vstack([s.predict(X[i:i + 50]) for i in range(0, 600, 50)])
    with b.as_server(buckets=(64,), raw_score=True) as s:
        got_raw = np.vstack([s.predict(X[i:i + 60])
                             for i in range(0, 600, 60)])
    assert np.array_equal(got, ref)
    assert np.array_equal(got_raw, ref_raw)


def test_batcher_coalesces_concurrent_submits():
    b, X = _train_binary()
    ref = b.predict(X[:600])
    s = b.as_server(buckets=(1, 8, 64, 512), max_delay_ms=60.0,
                    max_batch=512)
    try:
        futs = [s.submit(X[i]) for i in range(128)]
        res = [f.result(timeout=30) for f in futs]
    finally:
        s.close()
    for i, r in enumerate(res):
        assert np.array_equal(r.values, ref[i:i + 1])
        assert r.generation == 0
    snap = s.stats_snapshot()
    assert snap["requests"] == 128
    # coalescing must have packed many batch-1 submits per dispatch
    assert snap["batches"]["count"] < 64
    assert snap["batches"]["mean_rows"] > 2.0


def test_cache_hit_accounting_and_warm_buckets():
    b, X = _train_binary()
    with b.as_server(buckets=(8, 64), warmup=True) as s:
        for _ in range(5):
            s.predict(X[:8])
        snap = s.stats_snapshot()
    cache = snap["cache"]
    assert cache["forest_builds"] == 1
    assert cache["bucket_compiles"] == 2      # one per bucket, at warmup
    assert cache["misses"] == 0               # warmup pre-compiled both
    assert cache["hits"] == 5
    assert cache["per_bucket"]["8"]["hits"] == 5


def test_booster_predict_reuses_cached_device_forest():
    """ADVICE predict.py:313: two consecutive predict calls must reuse the
    cached device forest instead of re-slicing/re-uploading it."""
    b, X = _train_binary()
    gb = b._booster
    first = b.predict(X[:600])
    cache1 = gb._forest_cache
    assert cache1 is not None
    second = b.predict(X[:600])
    assert gb._forest_cache is cache1         # no rebuild
    assert gb._forest_cache[1][0] is cache1[1][0]   # same TreeArrays object
    assert np.array_equal(first, second)
    # in-place leaf mutation must invalidate (generation bump)
    gen = gb.generation
    b.set_leaf_output(0, 0, 123.0)
    assert gb._forest_cache is None
    assert gb.generation == gen + 1
    changed = b.predict(X[:600])
    assert not np.array_equal(first, changed)


def test_hot_swap_atomic_under_concurrent_load(tmp_path):
    b_old, X = _train_binary(seed=0)
    b_new, _ = _train_binary(seed=7, rounds=9)
    new_path = str(tmp_path / "new_model.txt")
    b_new.save_model(new_path)

    ref_old = b_old.predict(X[:600])
    ref_new = b_new.predict(X[:600])
    assert not np.allclose(ref_old, ref_new)

    s = b_old.as_server(buckets=(1, 8, 64), max_delay_ms=1.0)
    results = {}
    errors = []
    stop = threading.Event()

    def client(cid):
        try:
            i = cid
            while not stop.is_set():
                r = s.submit(X[i % 600]).result(timeout=30)
                results.setdefault(i % 600, []).append(
                    (r.generation, r.values.copy()))
                i += 7
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.25)
    new_gen = s.swap(new_path)
    assert new_gen == 1
    # post-swap requests must be served by the new generation
    post = s.submit(X[0]).result(timeout=30)
    assert post.generation == 1
    time.sleep(0.25)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    s.close()
    assert not errors
    gens = set()
    n_responses = 0
    for row, obs in results.items():
        for gen, vals in obs:
            n_responses += 1
            gens.add(gen)
            expect = ref_old if gen == 0 else ref_new
            assert np.array_equal(vals, expect[row:row + 1]), \
                "response must match exactly one generation's forest"
    assert n_responses > 0
    assert gens == {0, 1}, "stream must span the swap"
    assert s.stats_snapshot()["swaps"] == 1
    # zero dropped: every recorded response resolved with a value
    assert s.stats_snapshot()["errors"] == 0


def test_swap_from_in_memory_booster_and_num_iteration():
    b, X = _train_binary()
    ref_5 = b.predict(X[:600], num_iteration=5)
    with ForestServer(b, buckets=(64,), num_iteration=5) as s:
        got = np.concatenate([s.predict(X[i:i + 64])
                              for i in range(0, 576, 64)])
    assert np.array_equal(got, ref_5[:576])


def test_serve_rejects_narrow_rows_and_serves_linear_trees():
    b, X = _train_binary()
    with b.as_server(buckets=(8,)) as s:
        fut = s.submit(X[0, :2])
        with pytest.raises(ValueError, match="features"):
            fut.result(timeout=30)
    # linear forests serve through the compiled buckets bit-identically to
    # device predict (ISSUE 11: the old ValueError rejection is gone)
    Xr, yr = make_regression(600, 6, noise=1.0, random_state=1)
    br = lgb.train({"objective": "regression", "linear_tree": True,
                    "verbose": -1}, lgb.Dataset(Xr, label=yr),
                   num_boost_round=3)
    ref = br.predict(Xr[:64])
    with br.as_server(buckets=(64,)) as s:
        got = s.predict(Xr[:64])
    assert np.array_equal(got, ref)


def test_cli_task_serve_roundtrip(tmp_path):
    from lambdagap_tpu.cli import main as cli_main
    b, X = _train_binary()
    model = str(tmp_path / "model.txt")
    b.save_model(model)
    req = tmp_path / "requests.tsv"
    with open(req, "w") as f:
        for i in range(40):
            f.write("\t".join(f"{v:.8g}" for v in X[i]) + "\n")
    out = str(tmp_path / "preds.tsv")
    stats = str(tmp_path / "stats.json")
    rc = cli_main([f"task=serve", f"input_model={model}", f"data={req}",
                   f"output_result={out}", f"serve_stats_file={stats}",
                   "verbose=-1"])
    assert rc == 0
    got = np.loadtxt(out)
    ref = b.predict(X[:600])[:40]
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-9)
    import json
    snap = json.load(open(stats))
    assert snap["requests"] == 40
    assert "p99" in snap["latency_ms"]


def test_batcher_submit_after_close_raises():
    """A post-close submit must fail fast, never enqueue onto the dead
    queue (the old behavior hung the caller's future forever)."""
    from lambdagap_tpu.serve.batcher import MicroBatcher

    def run(batch):
        for r in batch:
            r.future.set_result(r.x.sum())
    mb = MicroBatcher(run, max_batch=8, max_delay_ms=0.5)
    fut = mb.submit(np.ones((1, 3), np.float32))
    assert fut.result(timeout=10) == 3.0
    mb.close()
    with pytest.raises(RuntimeError, match="batcher closed"):
        mb.submit(np.ones((1, 3), np.float32))


def test_batcher_close_submit_race_never_hangs():
    """Hammer submit() from several threads while close() lands mid-burst:
    every submit either raises 'batcher closed' or returns a future that
    RESOLVES — no future may hang on the drained queue."""
    from lambdagap_tpu.serve.batcher import MicroBatcher

    def run(batch):
        for r in batch:
            r.future.set_result(float(r.x.sum()))

    for trial in range(10):
        mb = MicroBatcher(run, max_batch=4, max_delay_ms=0.2, workers=2)
        futures, raised = [], []
        barrier = threading.Barrier(4)

        def submitter():
            barrier.wait()
            for _ in range(50):
                try:
                    futures.append(mb.submit(np.ones((1, 2), np.float32)))
                except RuntimeError as e:
                    assert "batcher closed" in str(e)
                    raised.append(e)
                    return

        threads = [threading.Thread(target=submitter) for _ in range(3)]
        for t in threads:
            t.start()
        barrier.wait()                   # close lands inside the burst
        time.sleep(0.0005 * trial)
        mb.close()
        for t in threads:
            t.join(timeout=30)
        for f in futures:                # accepted => must resolve
            assert f.result(timeout=10) == 2.0


def test_lambdarank_tile_must_divide_bucket_length():
    """Satellite (ADVICE rank.py:478): a non-divisor tile fails loudly
    instead of silently misaligning rank indices."""
    import jax.numpy as jnp
    from lambdagap_tpu.objectives.rank import _lambdarank_bucket
    nq, L = 2, 96
    scores = jnp.zeros((nq, L), jnp.float32)
    labels = jnp.zeros((nq, L), jnp.int32)
    valid = jnp.ones((nq, L), bool)
    inv = jnp.ones(nq, jnp.float32)
    gains = jnp.asarray([0.0, 1.0, 3.0], jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        _lambdarank_bucket(scores, labels, valid, inv, inv, gains,
                           target="ndcg", sigmoid=1.0, norm=True,
                           truncation_level=30, lambdagap_weight=1.0,
                           tile=40)
