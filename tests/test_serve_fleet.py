"""Fleet-shaped serving (ISSUE 9): multi-model registry under an HBM
budget, weighted tenant fairness + admission quotas, the health-aware
replica router with failover, the newline-JSON socket frontend, and the
open-loop load generator.

The acceptance surface: LRU eviction re-admits with exactly ONE recompile
and the generation preserved; a hot tenant cannot starve the others; a
killed replica strands NO accepted future; malformed frontend frames
answer an error and the connection survives; and every fleet path stays
bit-identical to the device predict (the parity test in test_serve.py is
extended with the same guarantee).
"""
import json
import socket
import threading
import time

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lambdagap_tpu as lgb
from lambdagap_tpu.serve import (FairQueue, ForestServer, FrontendClient,
                                 LocalReplica, RemoteReplica,
                                 ReplicaUnavailable, Request, Router,
                                 ServeFrontend, ServeOverloaded,
                                 arrival_times, run_open_loop)
from lambdagap_tpu.serve.batcher import MicroBatcher

DEVICE_PARAMS = {"verbose": -1, "tpu_fast_predict_rows": 0}


def _train(rows=1200, feats=10, rounds=8, leaves=15, seed=0, **extra):
    X, y = make_classification(rows, feats, n_informative=6,
                               random_state=seed)
    X = X.astype(np.float32)
    params = {"objective": "binary", "num_leaves": leaves, **DEVICE_PARAMS,
              **extra}
    return lgb.train(params, lgb.Dataset(X, label=y),
                     num_boost_round=rounds), X


# -- registry: LRU eviction + re-admission ------------------------------
def test_registry_lru_eviction_and_readmission_under_budget():
    b, X = _train()
    b2, _ = _train(rounds=5, leaves=7, seed=3)
    ref = b.predict(X[:600])
    ref2 = b2.predict(X[:600])
    s = ForestServer(b, buckets=(8, 64))
    try:
        default_bytes = s.registry.entry("default").bytes
        assert default_bytes > 0
        # budget fits ~one forest: admitting m2 must evict default (LRU)
        s.registry.hbm_budget_bytes = default_bytes + 128
        s.add_model("m2", b2._booster)
        snap = s.registry.snapshot()
        assert snap["models"]["default"]["resident"] is False
        assert snap["models"]["m2"]["resident"] is True
        assert snap["hbm_bytes_resident"] <= s.registry.hbm_budget_bytes

        # touching the evicted model re-admits it (ONE recompile, the
        # generation preserved) and evicts the other side
        got = s.predict(X[:64])
        assert np.array_equal(got, ref[:64])
        entry = s.registry.entry("default")
        assert entry.generation == 0              # generation preserved
        assert entry.builds == 2                  # install + exactly 1 readmit
        stats = s.stats_snapshot()
        assert stats["evictions"] == 2            # default, then m2
        assert stats["readmissions"] == 1
        assert stats["registry"]["models"]["m2"]["resident"] is False

        # the ping-ponged model still serves bit-identically
        got2 = s.predict(X[:64], model="m2")
        assert np.array_equal(got2, ref2[:64])
        assert s.stats_snapshot()["readmissions"] == 2
    finally:
        s.close()


def test_registry_concurrent_readmission_single_flight():
    """Eight threads hitting an evicted model concurrently must trigger
    exactly ONE recompile (single-flight), not eight."""
    b, X = _train()
    b2, _ = _train(rounds=4, leaves=7, seed=5)
    ref = b.predict(X[:600])
    s = ForestServer(b, buckets=(8,))
    try:
        s.registry.hbm_budget_bytes = s.registry.entry("default").bytes + 128
        s.add_model("m2", b2._booster)            # evicts default
        assert not s.registry.entry("default").resident
        outs, errs = [None] * 8, []

        def hit(i):
            try:
                outs[i] = s.predict(X[8 * i:8 * i + 8])
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errs
        for i in range(8):
            assert np.array_equal(outs[i], ref[8 * i:8 * i + 8])
        assert s.registry.entry("default").builds == 2
        assert s.stats_snapshot()["readmissions"] == 1
    finally:
        s.close()


def test_registry_swap_non_default_model_and_unknown_model_errors():
    b, X = _train()
    b2, _ = _train(rounds=4, leaves=7, seed=7)
    ref2 = b2.predict(X[:600])
    s = ForestServer(b, buckets=(8,))
    try:
        with pytest.raises(ValueError, match="unknown serve model"):
            s.submit(X[:4], model="nope")
        s.add_model("m2", b._booster)
        gen = s.swap(b2._booster, model="m2")
        assert gen == 1
        got = s.predict(X[:8], model="m2")
        assert np.array_equal(got, ref2[:8])
        assert s.generation == 0                  # default untouched
        with pytest.raises(ValueError, match="already registered"):
            s.add_model("m2", b._booster)
    finally:
        s.close()


# -- tenant fairness + admission -----------------------------------------
def test_fair_queue_weighted_interleave_under_flood():
    x = np.zeros((1, 2), np.float32)
    q = FairQueue(maxsize=0)
    for _ in range(100):
        q.try_put(Request(x, tenant="hog"))
    for _ in range(10):
        q.try_put(Request(x, tenant="mouse"))
    order = [q.get_nowait().tenant for _ in range(110)]
    mouse_pos = [i for i, t in enumerate(order) if t == "mouse"]
    # equal weights: the flooded lane cannot push the mouse to the back —
    # its 10 requests all clear within the first ~21 dequeues (FIFO would
    # start them at 100)
    assert max(mouse_pos) <= 21, mouse_pos


def test_fair_queue_respects_weights():
    x = np.zeros((1, 2), np.float32)
    q = FairQueue(maxsize=0, weights={"gold": 3.0})
    for _ in range(90):
        q.try_put(Request(x, tenant="gold"))
        q.try_put(Request(x, tenant="base"))
    first = [q.get_nowait().tenant for _ in range(40)]
    gold = first.count("gold")
    # 3:1 weights -> ~30 of the first 40 dequeues are gold
    assert 26 <= gold <= 34, first


def test_tenant_admission_quota_rejects_hot_tenant_only():
    x = np.zeros((1, 2), np.float32)
    q = FairQueue(maxsize=10, max_share=0.5)
    for _ in range(5):
        assert q.try_put(Request(x, tenant="hot")) == "ok"
    assert q.try_put(Request(x, tenant="hot")) == "quota"
    for _ in range(5):                            # others still admitted
        assert q.try_put(Request(x, tenant="cold")) == "ok"
    assert q.try_put(Request(x, tenant="cold")) == "full"


def test_batcher_fairness_under_hot_tenant_flood():
    """Integration: a hot tenant floods a bounded batcher; the quiet
    tenant's requests are neither starved (fair dequeue) nor rejected
    (admission quota bounds the hog, not the fleet)."""
    served = []
    gate = threading.Event()

    def run(batch):
        gate.wait(10)
        time.sleep(0.001)
        for r in batch:
            served.append(r.tenant)
            r.future.set_result(0.0)

    mb = MicroBatcher(run, max_batch=1, max_delay_ms=0.0, workers=1,
                      max_queue=64, tenant_max_share=0.75)
    x = np.zeros((1, 2), np.float32)
    hog_futs, hog_rejected = [], 0
    for _ in range(60):
        try:
            hog_futs.append(mb.submit(x, tenant="hog"))
        except ServeOverloaded:
            hog_rejected += 1
    mouse_futs = [mb.submit(x, tenant="mouse") for _ in range(6)]
    gate.set()
    for f in mouse_futs + hog_futs:
        f.result(timeout=30)
    mb.close()
    assert hog_rejected > 0                       # quota charged the hog
    mouse_pos = [i for i, t in enumerate(served) if t == "mouse"]
    # fair dequeue: all mouse requests served within the first ~2x their
    # count + the hog's head start, nowhere near the flood's tail
    assert max(mouse_pos) <= 20, mouse_pos
    snap = mb.stats.snapshot() if mb.stats else None
    assert snap is None                           # raw batcher: no stats


def test_server_per_tenant_stats_and_prometheus_labels():
    b, X = _train()
    with b.as_server(buckets=(8,)) as s:
        s.predict(X[:8], tenant="acme")
        s.predict(X[:8], tenant="acme")
        s.predict(X[:8], tenant="zeta")
        snap = s.stats_snapshot()
        text = s.prometheus()
    assert snap["per_tenant"]["acme"]["requests"] == 2
    assert snap["per_tenant"]["zeta"]["rows"] == 8
    assert snap["per_model"]["default"]["requests"] == 3
    assert "p99" in snap["per_tenant"]["acme"]["latency_ms"]
    assert 'lambdagap_serve_tenant_requests_total{tenant="acme"} 2' in text
    assert 'lambdagap_serve_model_requests_total{model="default"} 3' in text
    assert 'lambdagap_serve_registry_model_resident{model="default"} 1' \
        in text


# -- router ---------------------------------------------------------------
def test_router_prefers_ok_over_degraded_and_skips_draining():
    b, X = _train()
    ref = b.predict(X[:600])
    s1, s2, s3 = (ForestServer(b, buckets=(8,)) for _ in range(3))
    r = Router([LocalReplica("a", s1), LocalReplica("b", s2),
                LocalReplica("c", s3)])
    try:
        s2.health.note_error()                    # b: degraded
        s3.close()                                # c: draining
        for i in range(6):
            got = r.predict(X[i:i + 1], timeout=30)
            assert np.array_equal(got, ref[i:i + 1])
        snap = r.snapshot()
        assert snap["replicas"]["a"]["routed"] == 6
        assert snap["replicas"]["b"]["routed"] == 0
        assert snap["replicas"]["c"]["routed"] == 0
        # no ok replica left: degraded serves rather than rejecting
        s1.close()
        got = r.predict(X[:1], timeout=30)
        assert np.array_equal(got, ref[:1])
        assert r.snapshot()["replicas"]["b"]["routed"] == 1
    finally:
        for s in (s1, s2, s3):
            s.close()
        r.close()


def test_router_kill_replica_mid_load_strands_nothing(tmp_path):
    """The R8 acceptance at fleet level: SIGKILL-equivalent death of a
    remote replica (socket torn mid-flight) must fail over or fail every
    accepted request — zero hangs — and the fleet keeps serving."""
    b, X = _train()
    ref = b.predict(X[:600])
    victim = ForestServer(b, buckets=(1, 8, 64), max_delay_ms=5.0)
    survivor = ForestServer(b, buckets=(1, 8, 64))
    fe = ServeFrontend(victim).start()
    r = Router([RemoteReplica("victim", "127.0.0.1", fe.port),
                LocalReplica("survivor", survivor)])
    try:
        futs = [r.submit(X[i % 600][None, :]) for i in range(50)]
        fe.close()                                # the kill, mid-load
        victim.close()
        results = [f.result(timeout=30) for f in futs]   # NOTHING hangs
        for i, res in enumerate(results):
            assert np.array_equal(res.values, ref[i % 600:i % 600 + 1])
        # post-kill requests route to the survivor
        got = r.predict(X[:8], timeout=30)
        assert np.array_equal(got, ref[:8])
        snap = r.snapshot()
        assert snap["replicas"]["victim"]["dead"] is True
        assert snap["replicas"]["survivor"]["routed"] >= 1
        assert snap["replicas"]["victim"]["inflight"] == 0
    finally:
        survivor.close()
        r.close()


def test_router_rejects_when_no_replica_lives():
    b, X = _train()
    s = ForestServer(b, buckets=(8,))
    r = Router([LocalReplica("only", s)])
    s.close()
    with pytest.raises(ReplicaUnavailable, match="no live replica"):
        r.submit(X[:1]).result(timeout=10)
    assert r.snapshot()["rejected_no_replica"] == 1
    r.close()


def test_router_fleet_surface_swap_stats_health(tmp_path):
    b, X = _train()
    b2, _ = _train(rounds=5, leaves=7, seed=9)
    ref2 = b2.predict(X[:600])
    path = str(tmp_path / "v2.txt")
    b2.save_model(path)
    s1, s2 = ForestServer(b, buckets=(8,)), ForestServer(b, buckets=(8,))
    r = Router([LocalReplica("a", s1), LocalReplica("b", s2)],
               own_replicas=True)
    try:
        assert r.health.state() == "ok"
        assert r.models() == ["default"]
        gen = r.swap(path)                        # fleet-wide rollout
        assert gen == 1
        for s in (s1, s2):
            assert s.generation == 1
        got = r.predict(X[:8], timeout=30)
        assert np.array_equal(got, ref2[:8])
        snap = r.stats_snapshot()
        assert set(snap["replicas"]) == {"a", "b"}
        assert snap["router"]["failovers"] == 0
        prom = r.prometheus()
        assert 'lambdagap_router_replica_health{replica="a",state="ok"} 1' \
            in prom
    finally:
        r.close()


# -- frontend wire protocol ----------------------------------------------
def test_frontend_roundtrip_predict_swap_stats_models(tmp_path):
    b, X = _train()
    b2, _ = _train(rounds=5, leaves=7, seed=11)
    ref = b.predict(X[:600])
    ref2 = b2.predict(X[:600])
    path = str(tmp_path / "v2.txt")
    b2.save_model(path)
    with ForestServer(b, buckets=(1, 8, 64)) as s, ServeFrontend(s) as fe:
        with FrontendClient("127.0.0.1", fe.port) as c:
            got = c.predict(X[:37])
            assert np.array_equal(got, np.asarray(ref[:37], np.float32))
            assert c.health() == "ok"
            assert c.models() == ["default"]
            st = c.stats()
            assert st["requests"] == 1
            assert "lambdagap_serve_requests_total" in c.prometheus()
            gen = c.swap(path)
            assert gen == 1
            got2 = c.predict(X[:8])
            assert np.array_equal(got2, np.asarray(ref2[:8], np.float32))


def test_frontend_malformed_frames_answer_errors_and_survive():
    b, X = _train()
    with ForestServer(b, buckets=(8,)) as s, ServeFrontend(s) as fe:
        sock = socket.create_connection(("127.0.0.1", fe.port), timeout=10)
        f = sock.makefile("rwb")

        def call(payload: bytes) -> dict:
            f.write(payload + b"\n")
            f.flush()
            return json.loads(f.readline())

        r = call(b"this is not json")
        assert r["ok"] is False and "malformed" in r["error"]
        r = call(b'{"op": "conjure", "id": 1}')
        assert r["ok"] is False and r["id"] == 1
        assert "unknown op" in r["error"]
        r = call(b'{"op": "predict", "id": 2}')   # no x
        assert r["ok"] is False and r["id"] == 2
        r = call(b'{"op": "predict", "id": 3, "x": "wat"}')
        assert r["ok"] is False and r["id"] == 3
        r = call(b'{"op": "predict", "id": 4, "x": [[0.5, 0.5]], '
                 b'"model": "ghost"}')
        assert r["ok"] is False and r["kind"] == "ValueError"
        # the connection survived all of it: a real request still serves
        row = json.dumps({"op": "predict", "id": 5,
                          "x": X[:1].tolist()}).encode()
        r = call(row)
        assert r["ok"] is True and r["id"] == 5
        assert r["generation"] == 0
        sock.close()


def test_frontend_client_dead_socket_resolves_pending():
    b, X = _train()
    s = ForestServer(b, buckets=(8,), max_delay_ms=50.0)
    fe = ServeFrontend(s).start()
    c = FrontendClient("127.0.0.1", fe.port)
    futs = [c.submit(X[i][None, :]) for i in range(4)]
    fe.close()                                    # socket dies under them
    for fut in futs:
        try:
            fut.result(timeout=10)                # value (already served)…
        except (ReplicaUnavailable, ConnectionError):
            pass                                  # …or the transport error
    with pytest.raises(ReplicaUnavailable):
        c.submit(X[:1])
    c.close()
    s.close()


def test_serve_loop_model_routing_and_health_verbs():
    import io
    from lambdagap_tpu.serve import serve_loop
    b, X = _train()
    b2, _ = _train(rounds=4, leaves=7, seed=13)
    ref = b.predict(X[:600])
    ref2 = b2.predict(X[:600])
    s = ForestServer(b, buckets=(1, 8))
    s.add_model("b", b2._booster)
    lines = ["\t".join(f"{v:.8g}" for v in X[0]),
             "model=b",
             "\t".join(f"{v:.8g}" for v in X[0]),
             "health",
             "model=",
             "\t".join(f"{v:.8g}" for v in X[0])]
    out, stats = io.StringIO(), io.StringIO()
    try:
        n = serve_loop(s, lines, out, stats_stream=stats)
    finally:
        s.close()
    assert n == 3
    rows = [float(ln) for ln in out.getvalue().splitlines()]
    # row 1 default, row 2 model b, row 3 default again — and the text
    # round-trip of the INPUT row costs precision, so compare against a
    # predict of the same parsed row, not the original matrix
    x_rt = np.array([[float(f"{v:.8g}") for v in X[0]]], np.float32)
    assert rows[0] == float(f"{b.predict(x_rt)[0]:.10g}") or np.isclose(
        rows[0], ref[0], rtol=1e-5)
    assert np.isclose(rows[1], ref2[0], rtol=1e-4)
    assert np.isclose(rows[2], rows[0])
    assert stats.getvalue().strip() == "ok"


# -- open-loop load generator --------------------------------------------
def test_arrival_times_deterministic_and_seeded():
    u = arrival_times(100.0, 5, kind="uniform")
    np.testing.assert_allclose(u, [0.01, 0.02, 0.03, 0.04, 0.05])
    p1 = arrival_times(100.0, 50, kind="poisson", seed=7)
    p2 = arrival_times(100.0, 50, kind="poisson", seed=7)
    np.testing.assert_array_equal(p1, p2)
    assert not np.array_equal(p1, arrival_times(100.0, 50, kind="poisson",
                                                seed=8))
    with pytest.raises(ValueError, match="unknown arrival"):
        arrival_times(10.0, 5, kind="exact")


def test_open_loop_goodput_counts_separate_from_throughput():
    """A submit that always succeeds but answers LATE must count toward
    throughput and not goodput — the two-number honesty the closed-loop
    bench could not express."""
    from concurrent.futures import Future

    def slow_submit(x, model=None, tenant=None):
        fut = Future()

        def later():
            time.sleep(0.05)                      # 50 ms > 10 ms deadline
            fut.set_result(type("R", (), {"values": np.zeros(1)})())
        threading.Thread(target=later, daemon=True).start()
        return fut

    X = np.zeros((4, 3), np.float32)
    res = run_open_loop(slow_submit, X, rate_rps=200.0, n_requests=30,
                        deadline_ms=10.0, arrival="uniform", seed=1)
    assert res["counts"]["ok"] == 30
    assert res["counts"]["good"] == 0
    assert res["counts"]["late"] == 30
    assert res["goodput_rps"] == 0.0
    assert res["throughput_rps"] > 0.0


def test_open_loop_against_live_server_tenant_breakdown():
    b, X = _train()
    with b.as_server(buckets=(1, 8, 64), max_delay_ms=1.0) as s:
        res = run_open_loop(s.submit, X, rate_rps=400.0, n_requests=160,
                            deadline_ms=250.0,
                            tenants={"gold": 3.0, "base": 1.0}, seed=5)
    c = res["counts"]
    assert c["ok"] == 160 and c["rejected"] == 0
    offered = {t: d["offered"] for t, d in res["per_tenant"].items()}
    assert offered["gold"] + offered["base"] == 160
    assert offered["gold"] > offered["base"] * 2   # seeded 3:1 mix
    assert res["per_tenant"]["gold"]["latency_ms"]["p99"] > 0
