"""Concurrency hammer for the serve layer (ISSUE-2 satellite, marked slow).

This is the dynamic counterpart of graftlint's R5 lock-discipline rule: a
seeded multi-thread submit/swap storm over ``MicroBatcher`` + the
``SwapController`` generation pointer. The invariant under attack is the
one R5 exists to protect statically — every response must be produced by
exactly ONE generation's forest (no torn reads of the ``active`` pointer
mid-dispatch, no result scattered across a swap). Each ``ServeResult``
carries its generation, so a torn read shows up as a bitwise mismatch
against that generation's reference predictions.
"""
import threading
import time

import numpy as np
import pytest
from sklearn.datasets import make_classification

import lambdagap_tpu as lgb

# device path (no native small-batch shortcut), as in test_serve.py
DEVICE_PARAMS = {"verbose": -1, "tpu_fast_predict_rows": 0}


def _train(seed, rounds, rows=900, feats=10):
    X, y = make_classification(rows, feats, n_informative=6,
                               random_state=seed)
    X = X.astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15, **DEVICE_PARAMS}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)
    return b, X


@pytest.mark.slow
def test_submit_swap_hammer_no_torn_generations():
    # two distinguishable models over one feature space; swaps alternate
    # between them, so generation parity identifies the serving forest
    b0, X = _train(seed=0, rounds=8)
    b1, _ = _train(seed=1, rounds=11)
    models = [b0, b1]
    expected = [np.asarray(m.predict(X)) for m in models]
    assert not np.array_equal(expected[0], expected[1])

    # warmup off: swap atomicity (not compile amortization) is under test,
    # and cold buckets make the swap cadence fast enough to overlap traffic
    server = b0.as_server(buckets=(1, 8, 64), max_delay_ms=1.0, workers=2,
                          warmup=False)
    swaps_done = threading.Event()
    failures = []
    n_clients, n_swaps, min_submits, max_submits = 4, 8, 40, 2000
    served = [0] * n_clients

    def swapper():
        try:
            for g in range(1, n_swaps + 1):
                new_gen = server.swap(models[g % 2])
                assert new_gen == g        # swaps serialize in call order
                time.sleep(0.01)           # let traffic land on each gen
        finally:
            swaps_done.set()

    def client(tid):
        rs = np.random.RandomState(1000 + tid)   # seeded: reproducible storm
        while served[tid] < max_submits and (
                served[tid] < min_submits or not swaps_done.is_set()):
            n = int(rs.choice([1, 3, 16]))
            i = int(rs.randint(0, X.shape[0] - n))
            res = server.submit(X[i:i + n]).result(timeout=120)
            served[tid] += 1
            exp = expected[res.generation % 2][i:i + n]
            got = np.atleast_1d(np.asarray(res.values))
            if not np.array_equal(got, exp):
                failures.append((tid, i, n, res.generation))

    sw = threading.Thread(target=swapper, daemon=True)
    clients = [threading.Thread(target=client, args=(t,))
               for t in range(n_clients)]
    for c in clients:
        c.start()
    sw.start()
    try:
        for c in clients:
            c.join(timeout=300)
            assert not c.is_alive(), "client thread hung (dropped future?)"
        sw.join(timeout=120)
        assert not sw.is_alive(), "swapper hung"
    finally:
        swaps_done.set()
        server.close()
    assert not failures, (
        f"{len(failures)} response(s) mixed generations (torn swap): "
        f"{failures[:5]}")
    assert server.generation == n_swaps
    assert server.stats_snapshot()["requests"] == sum(served)


@pytest.mark.slow
def test_close_under_load_never_drops_futures():
    b0, X = _train(seed=2, rounds=6)
    server = b0.as_server(buckets=(1, 8), max_delay_ms=0.5, workers=2)
    futs = [server.submit(X[i % 100:i % 100 + 1]) for i in range(200)]
    server.close()
    for f in futs:
        f.result(timeout=60)   # every queued request resolves, none hang
