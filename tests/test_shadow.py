"""Shadow evaluation (ISSUE 20) — the acceptance criterion: the mirror
is strictly OFF the reply path. A dead, failing, or wedged shadow
replica must never move a live answer by a bit or cost the live path a
request; everything it does is counted, and its re-scores join the
request's trace tree as ``shadow_predict`` spans.
"""
import threading
import time

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.guard.degrade import ReplicaUnavailable
from lambdagap_tpu.guard.faults import FaultPlan
from lambdagap_tpu.obs import trace as obs_trace
from lambdagap_tpu.serve import LocalReplica, Router, ShadowMirror
from lambdagap_tpu.serve.frontend import FrontendClient, ServeFrontend

PARAMS = {"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbose": -1, "tpu_fast_predict_rows": 0}


def _train(rounds=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(400, 5).astype(np.float32)
    y = (X[:, 0] * 2.0 - X[:, 1]).astype(np.float32)
    b = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=rounds)
    return b, X


def _router(base, n=1):
    return Router([LocalReplica(f"r{i}", base.as_server())
                   for i in range(n)], own_replicas=True)


class DeadReplica:
    """A shadow replica that died: every submit is a transport failure."""
    name = "shadow"

    def submit(self, x, model=None, tenant=None, trace=None):
        raise ReplicaUnavailable("shadow is dead")

    def close(self):
        pass


class GatedReplica:
    """A wedged shadow replica: submits block until released."""
    name = "shadow"

    def __init__(self, inner):
        self.inner = inner
        self.gate = threading.Event()

    def submit(self, x, model=None, tenant=None, trace=None):
        self.gate.wait(10.0)
        return self.inner.submit(x, model=model, tenant=tenant)

    def close(self):
        self.gate.set()


def _drain(mirror, timeout_s=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if mirror.snapshot()["pending"] == 0:
            return mirror.snapshot()
        time.sleep(0.02)
    raise AssertionError(f"mirror never drained: {mirror.snapshot()}")


# ---------------------------------------------------------------------------
# the acceptance criterion: bit-identical live answers, matched goodput
# ---------------------------------------------------------------------------
def test_dead_shadow_never_moves_a_live_answer():
    """sample=1.0 + a shadow that dies on every mirror: every live
    answer is BIT-identical to the unshadowed run, every live request
    succeeds (goodput match), and the sheds are counted."""
    base, X = _train()
    rows = [X[i:i + 3] for i in range(0, 30, 3)]
    router = _router(base)
    try:
        bare = [router.predict(r) for r in rows]
        before = router.snapshot()["replicas"]["r0"]["routed"]
        mirror = ShadowMirror(DeadReplica(), sample=1.0)
        router.arm_shadow(mirror)
        shadowed = [router.predict(r) for r in rows]
        snap = router.snapshot()
        for a, b in zip(bare, shadowed):
            assert np.array_equal(a, b)          # bit-identical, not close
        assert snap["replicas"]["r0"]["routed"] - before == len(rows)
        assert snap["failovers"] == 0 and snap["rejected_no_replica"] == 0
        ssnap = _drain(mirror)
        assert ssnap["dead"] is True
        assert ssnap["errors"] >= 1
        # everything after the death was shed silently, nothing dropped
        assert ssnap["shed"] + ssnap["compared"] + ssnap["errors"] \
            >= ssnap["mirrored"]
    finally:
        router.close()


def test_live_mirror_compares_bit_identical_candidate():
    """Sanity for the promote gate's signal: shadowing the SAME model
    yields exact-zero deltas on every compared request."""
    base, X = _train()
    router = _router(base)
    try:
        mirror = ShadowMirror(LocalReplica("shadow", base.as_server()),
                              sample=1.0)
        router.arm_shadow(mirror)
        for i in range(8):
            router.predict(X[i:i + 1])
        snap = _drain(mirror)
        assert snap["compared"] == 8 and snap["errors"] == 0
        assert snap["delta"]["max"] == 0.0
    finally:
        router.close()


def test_shadow_dispatch_fail_fault_point_is_live():
    """`shadow_dispatch_fail=K` raises inside the mirror worker: K sheds
    with errors counted, the live path untouched, the mirror NOT marked
    dead (an injected fault is not a transport indictment)."""
    base, X = _train()
    router = _router(base)
    try:
        mirror = ShadowMirror(LocalReplica("shadow", base.as_server()),
                              sample=1.0,
                              faults=FaultPlan("shadow_dispatch_fail=2"))
        router.arm_shadow(mirror)
        live = [router.predict(X[i:i + 1]) for i in range(6)]
        snap = _drain(mirror)
        assert snap["errors"] == 2 and snap["shed"] == 2
        assert snap["compared"] == 4
        assert snap["dead"] is False
        assert len(live) == 6            # every live request answered
    finally:
        router.close()


def test_wedged_shadow_sheds_on_bounded_queue():
    """A hung shadow RPC fills the bounded pending window; overflow is
    shed at hand-off — the live path never queues behind the shadow."""
    base, X = _train()
    inner = LocalReplica("inner", base.as_server())
    gated = GatedReplica(inner)
    router = _router(base)
    try:
        mirror = ShadowMirror(gated, sample=1.0, max_pending=2)
        router.arm_shadow(mirror)
        t0 = time.time()
        for i in range(10):
            router.predict(X[i:i + 1])
        live_wall = time.time() - t0
        assert live_wall < 5.0           # never convoyed behind the gate
        assert mirror.snapshot()["shed"] >= 8
        gated.gate.set()
        snap = _drain(mirror)
        assert snap["compared"] <= 2
    finally:
        router.close()
        inner.close()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
def test_shadow_spans_join_the_trace_tree(tmp_path):
    out = str(tmp_path / "events.jsonl")
    obs_trace.configure(sample=1.0, out=out)
    base, X = _train()
    router = _router(base)
    try:
        mirror = ShadowMirror(LocalReplica("shadow", base.as_server()),
                              sample=1.0)
        router.arm_shadow(mirror)
        for i in range(4):
            router.predict(X[i:i + 1])
        _drain(mirror)
    finally:
        router.close()
        obs_trace.RECORDER.close()
        obs_trace.configure(sample=0.0)
    from lambdagap_tpu.obs import events as obs_events
    records, _trunc = obs_events.read_file(out)
    spans = [r for r in records if r.get("type") == "span"]
    shadow = [s for s in spans if s["name"] == "shadow_predict"]
    assert len(shadow) == 4
    route_traces = {s["trace"] for s in spans if s["name"] == "route"}
    for s in shadow:
        assert s["trace"] in route_traces    # same tree as the live hop
        assert s["attrs"]["outcome"] == "compared"
        assert s["attrs"]["delta"] == 0.0


def test_router_snapshot_byte_identical_without_shadow():
    """Knob off -> schema untouched: no shadow/loop keys anywhere until
    a mirror is armed, and disarming removes them again."""
    base, X = _train()
    router = _router(base)
    try:
        snap = router.snapshot()
        assert "shadow" not in snap and "loop" not in snap
        mirror = ShadowMirror(LocalReplica("shadow", base.as_server()),
                              sample=1.0)
        router.arm_shadow(mirror)
        assert "shadow" in router.snapshot()
        final = router.disarm_shadow()
        assert final is not None
        assert "shadow" not in router.snapshot()
    finally:
        router.close()


# ---------------------------------------------------------------------------
# the wire surface (docs/serving.md)
# ---------------------------------------------------------------------------
def test_shadow_on_and_loop_status_over_the_wire(tmp_path):
    base, X = _train()
    base_path = str(tmp_path / "base.txt")
    base.save_model(base_path)
    router = _router(base)
    fe = ServeFrontend(router, port=0).start()
    client = FrontendClient("127.0.0.1", fe.port)
    try:
        assert client.loop_status() == {"state": "off"}  # no controller
        info = client.shadow_on(base_path, sample=1.0)
        assert info == {"armed": True, "sample": 1.0}
        vals = client.predict(X[:2])
        assert np.array_equal(vals, router.predict(X[:2]))
        stats = router.shadow_snapshot()
        assert stats is not None and stats["sample"] == 1.0
        off = client.shadow_on(None, sample=0.0)
        assert off["armed"] is False and "final" in off
        assert router.shadow_snapshot() is None
    finally:
        client.close()
        fe.close()
        router.close()
