"""SHAP pred_contrib and JSON model dump.

(reference: Tree::PredictContrib/TreeSHAP in src/io/tree.cpp;
GBDT::DumpModel in src/boosting/gbdt_model_text.cpp)
"""
import json

import numpy as np
import pytest

import lambdagap_tpu as lgb


def _data(n=500, d=6, seed=4, with_nan=False, with_cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    if with_cat:
        X[:, 0] = rng.randint(0, 9, n)
    if with_nan:
        X[rng.rand(n, d) < 0.1] = np.nan
    base = np.where(np.isnan(X), 0.0, X)
    y = base[:, 1] * 2 + np.sin(base[:, 2]) + \
        (base[:, 0] % 3 if with_cat else base[:, 3])
    return X, y


@pytest.mark.parametrize("kw", [{}, {"with_nan": True}, {"with_cat": True}])
def test_contrib_sums_to_raw(kw):
    X, y = _data(**kw)
    params = {"objective": "regression", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=[0] if kw.get("with_cat") else "auto")
    b = lgb.train(params, ds, num_boost_round=12)
    contrib = b.predict(X, pred_contrib=True)
    assert contrib.shape == (len(X), X.shape[1] + 1)
    raw = b.predict(X, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5, atol=1e-6)
    # features the model never splits on get zero contribution
    used = {f for t in b._booster.host_models
            for f in t.split_feature[:t.num_internal]}
    for f in range(X.shape[1]):
        if f not in used:
            np.testing.assert_allclose(contrib[:, f], 0.0, atol=1e-12)


def test_contrib_multiclass_shape_and_sum():
    rng = np.random.RandomState(0)
    X = rng.randn(400, 5)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbose": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    contrib = b.predict(X, pred_contrib=True)
    F1 = X.shape[1] + 1
    assert contrib.shape == (400, 3 * F1)
    raw = b.predict(X, raw_score=True)        # [N, 3]
    for k in range(3):
        np.testing.assert_allclose(contrib[:, k * F1:(k + 1) * F1].sum(axis=1),
                                   raw[:, k], rtol=1e-5, atol=1e-6)


def test_python_fallback_matches_native():
    from lambdagap_tpu.models.shap import (_tree_shap_python,
                                           tree_shap_accumulate)
    from lambdagap_tpu.native import get_lib
    if get_lib() is None:
        pytest.skip("no native lib; fallback is the only path")
    X, y = _data(n=60)
    b = lgb.train({"objective": "regression", "num_leaves": 7, "verbose": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    tree = b._booster.host_models[0]
    X64 = np.ascontiguousarray(X, np.float64)
    phi_n = np.zeros((60, X.shape[1] + 1))
    tree_shap_accumulate(tree, X64, phi_n)
    phi_p = np.zeros_like(phi_n)
    _tree_shap_python(tree, X64, phi_p)
    np.testing.assert_allclose(phi_n, phi_p, rtol=1e-9, atol=1e-12)


def test_dump_model_json():
    X, y = _data(with_cat=True)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbose": -1},
                  lgb.Dataset(X, label=y, categorical_feature=[0]),
                  num_boost_round=5)
    d = b.dump_model()
    s = json.dumps(d)                     # must be JSON-serializable
    d2 = json.loads(s)
    assert d2["num_class"] == 1
    assert len(d2["tree_info"]) == 5
    assert d2["max_feature_idx"] == 5
    t0 = d2["tree_info"][0]
    assert t0["num_leaves"] >= 2
    root = t0["tree_structure"]
    assert "split_feature" in root and "left_child" in root
    # find a categorical node: threshold is a "a||b" string
    def walk(nd):
        if "split_index" in nd:
            yield nd
            yield from walk(nd["left_child"])
            yield from walk(nd["right_child"])
    cats = [nd for ti in d2["tree_info"] for nd in walk(ti["tree_structure"])
            if nd["decision_type"] == "=="]
    assert cats and all("||" in nd["threshold"] or nd["threshold"].isdigit()
                        for nd in cats)
    # leaf count is preserved
    def leaves(nd):
        if "leaf_index" in nd:
            return 1
        return leaves(nd["left_child"]) + leaves(nd["right_child"])
    assert leaves(t0["tree_structure"]) == t0["num_leaves"]
