"""obs/signals.py edge cases the autonomics controller hits (ISSUE 13
satellite): empty scrape window, zero-offered-rate knee updates,
HealthTimeline ring wraparound, and a replica flapping ok->dead->ok
inside one scrape interval. The controller codes against
validate_signals' schema, so every edge tick must stay schema-valid.
"""
from lambdagap_tpu.obs.signals import (HealthTimeline, KneeEstimator,
                                       SignalPlane, validate_signals)


def _snap(now, requests=0, timeouts=0, rejected=0, errors=0,
          replicas=None, registry=None):
    merged = {"requests": requests, "timeouts": timeouts,
              "rejected": rejected, "errors": errors}
    if registry is not None:
        merged["registry"] = registry
    return {"merged": merged, "time_unix": now,
            "router": {"replicas": replicas or {}}}


def test_empty_scrape_window_first_tick_is_schema_valid():
    plane = SignalPlane()
    sig = plane.update({})               # an empty scrape: no merged block
    assert validate_signals(sig) == []
    assert sig["interval"] == {"dt_s": 0.0, "offered_rps": 0.0,
                               "good_fraction": 1.0}
    assert sig["goodput"]["knee_rps"] == 0.0
    assert sig["goodput"]["knee_margin"] == 0.0   # no evidence, no margin


def test_snapshot_before_first_tick_is_schema_valid():
    plane = SignalPlane()
    assert validate_signals(plane.snapshot()) == []


def test_zero_offered_rate_tick_never_divides_by_zero():
    plane = SignalPlane()
    plane.update(_snap(100.0, requests=50))
    sig = plane.update(_snap(101.0, requests=50))   # no new offers: 0 rps
    assert validate_signals(sig) == []
    assert sig["interval"]["offered_rps"] == 0.0
    assert sig["interval"]["good_fraction"] == 1.0  # 0/0 reads as good
    # and the knee estimator itself takes a (0, good) observation calmly
    knee = KneeEstimator()
    knee.observe(100.0, 1.0)
    assert knee.knee_rps > 0
    knee.observe(0.0, 1.0)
    # headroom grows as offered falls (EWMA-smoothed), never past 1
    assert 0.0 < knee.knee_margin <= 1.0
    for _ in range(50):
        knee.observe(0.0, 1.0)           # long idle: margin -> all headroom
    assert knee.knee_margin > 0.9


def test_counter_reset_reads_as_zero_interval_not_negative():
    """A replica death resets its counters; the merged sums can go
    BACKWARD across one scrape. The interval must clamp at zero, not
    report negative rates that would whipsaw the autoscaler."""
    plane = SignalPlane()
    plane.update(_snap(10.0, requests=1000, timeouts=50))
    sig = plane.update(_snap(11.0, requests=400, timeouts=10))
    assert sig["interval"]["offered_rps"] == 0.0
    assert sig["interval"]["good_fraction"] == 1.0
    assert validate_signals(sig) == []


def test_same_timestamp_tick_is_inert():
    plane = SignalPlane()
    plane.update(_snap(5.0, requests=10))
    before = plane.knee.ticks
    sig = plane.update(_snap(5.0, requests=20))     # dt == 0
    assert plane.knee.ticks == before    # no knee observation from dt=0
    assert validate_signals(sig) == []


def test_health_timeline_ring_wraparound():
    tl = HealthTimeline(ring=8)
    states = ["ok", "dead"]
    for i in range(25):                  # 25 TRANSITIONS through 1 replica
        tl.note("r0", states[i % 2], t=float(i))
    snap = tl.snapshot()
    assert len(snap["transitions"]) == 8             # bounded
    assert snap["transitions"][0]["t"] == 17.0       # oldest dropped
    assert snap["transitions"][-1]["t"] == 24.0
    assert snap["current"] == {"r0": states[24 % 2]}


def test_health_timeline_collapses_repeats_not_flaps():
    tl = HealthTimeline(ring=16)
    assert tl.note("r0", "ok") is True
    assert tl.note("r0", "ok") is False  # repeat: no transition
    # ok -> dead -> ok inside one scrape interval: every change recorded
    assert tl.note("r0", "dead", t=1.0) is True
    assert tl.note("r0", "ok", t=1.0) is True
    snap = tl.snapshot()
    assert [tr["state"] for tr in snap["transitions"]] == \
        ["ok", "dead", "ok"]
    assert snap["current"]["r0"] == "ok"


def test_flap_within_one_scrape_interval_through_the_plane():
    """The plane only sees scrape-edge states: a replica that died and
    revived BETWEEN scrapes looks steady-ok at the plane, while direct
    timeline notes (the revival path writes these) still record the
    flap. Both views must coexist in one schema-valid tick."""
    plane = SignalPlane()
    plane.update(_snap(1.0, replicas={"r0": {"health": "ok"}}))
    # mid-interval: the controller's revival path records the flap
    plane.health.note("r0", "dead", t=1.4)
    plane.health.note("r0", "ok", t=1.6)
    sig = plane.update(_snap(2.0, replicas={"r0": {"health": "ok"}}))
    assert validate_signals(sig) == []
    states = [tr["state"] for tr in sig["health"]["transitions"]]
    assert states == ["ok", "dead", "ok"]            # flap preserved
    assert sig["health"]["current"] == {"r0": "ok"}


def test_dead_replica_reaches_the_timeline_via_router_snapshot():
    plane = SignalPlane()
    plane.update(_snap(1.0, replicas={"r0": {"health": "ok"},
                                      "r1": {"health": "ok"}}))
    sig = plane.update(_snap(2.0, replicas={"r0": {"health": "dead"},
                                            "r1": {"health": "ok"}}))
    assert sig["health"]["current"]["r0"] == "dead"
    assert validate_signals(sig) == []


def test_knee_margin_bounded_and_decaying():
    knee = KneeEstimator(alpha=1.0, good_ratio=0.9, knee_decay=0.5)
    knee.observe(1000.0, 1.0)
    assert knee.knee_rps >= 1000.0
    m_at_peak = knee.knee_margin
    knee.observe(100.0, 1.0)             # traffic fell away
    assert knee.knee_margin <= 1.0       # schema bound
    knee.observe(100.0, 1.0)
    # the knee decays toward current offered: stale peaks stop vouching
    assert knee.knee_rps < 1000.0
    assert m_at_peak <= 1.0
