"""Out-of-core / sparse ingestion (reference: the sparse-bin memory story,
src/io/sparse_bin.hpp:73, and two-round loading,
src/io/dataset_loader.cpp:203 use_two_round_loading): scipy CSR input bins
chunk-wise through the streaming-sequence path, and ``two_round=true`` text
loading re-reads the file in bounded chunks — neither materializes the full
dense float matrix."""
import os
import subprocess
import sys

import numpy as np
import pytest

import lambdagap_tpu as lgb

sp = pytest.importorskip("scipy.sparse")


def _sparse_problem(n=4000, d=40, density=0.05, seed=0):
    rng = np.random.RandomState(seed)
    X = sp.random(n, d, density=density, format="csr", random_state=rng,
                  data_rvs=lambda k: rng.randn(k) * 2)
    dense = X.toarray()
    y = (dense[:, 0] + dense[:, 1] - 0.2 * dense[:, 2] > 0).astype(float)
    return X, dense, y


def test_csr_matches_dense():
    X, dense, y = _sparse_problem()
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    b_dense = lgb.train(params, lgb.Dataset(dense, label=y),
                        num_boost_round=8)
    b_csr = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=8)
    # identical rows -> identical sample -> identical mappers and bins
    np.testing.assert_allclose(b_dense.predict(dense), b_csr.predict(dense),
                               rtol=1e-6, atol=1e-8)
    # chunked CSR prediction agrees with dense prediction
    np.testing.assert_allclose(b_csr.predict(X), b_csr.predict(dense),
                               rtol=1e-6, atol=1e-8)


def test_csr_construct_memory_envelope():
    """Constructing from CSR must peak WELL below the dense float
    footprint. 400k x 500 f64 dense = 1.6 GB; the binned matrix is 200 MB.
    The check runs in a subprocess so other tests' allocations don't
    pollute maxrss."""
    code = r"""
import resource, sys
import numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import scipy.sparse as sp
import lambdagap_tpu as lgb
rng = np.random.RandomState(0)
n, d = 400_000, 500
nnz_per_row = 5                      # density 0.01
indptr = np.arange(0, n * nnz_per_row + 1, nnz_per_row, dtype=np.int64)
indices = rng.randint(0, d, n * nnz_per_row).astype(np.int32)
data = rng.randn(n * nnz_per_row).astype(np.float64)
X = sp.csr_matrix((data, indices, indptr), shape=(n, d))
y = rng.randint(0, 2, n).astype(float)
ds = lgb.Dataset(X, label=y, params={"max_bin": 63,
                                     "bin_construct_sample_cnt": 20000})
b = ds.construct()
assert b.num_data == n and b.binned.shape[0] == n
peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
print("PEAK_MB", peak_mb)
# dense f64 would be 1600 MB on top of everything else; peak memory is
# bounded by baseline + binned matrix (200 MB) + the bin-finding sample
# (20k x 500 f64 = 80 MB) + one 64k-row chunk (256 MB)
assert peak_mb < 1000, peak_mb
"""
    env = {k: v for k, v in os.environ.items()
           if "AXON" not in k and k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=os.getcwd(), env=env, timeout=540)
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-2000:])
    assert "PEAK_MB" in r.stdout


@pytest.mark.parametrize("fmt", ["tsv", "libsvm"])
def test_two_round_matches_one_shot(tmp_path, fmt):
    rng = np.random.RandomState(3)
    n = 3000
    X = rng.randn(n, 6)
    X[rng.rand(n) < 0.1, 2] = 0.0
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    path = str(tmp_path / f"data.{fmt}")
    if fmt == "tsv":
        np.savetxt(path, np.column_stack([y, X]), delimiter="\t")
    else:
        with open(path, "w") as f:
            for i in range(n):
                toks = [f"{int(y[i])}"] + [
                    f"{j}:{X[i, j]:.6g}" for j in range(6) if X[i, j] != 0]
                f.write(" ".join(toks) + "\n")
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    ds1 = lgb.Dataset(path, params=params).construct()
    ds2 = lgb.Dataset(path, params={**params, "two_round": True}).construct()
    assert ds1.num_data == ds2.num_data
    np.testing.assert_allclose(ds1.metadata.label, ds2.metadata.label,
                               rtol=1e-6)
    # identical sample seed -> identical mappers -> identical binned rows
    assert np.array_equal(ds1.binned, ds2.binned)

    b1 = lgb.train(params, lgb.Dataset(path, params=params),
                   num_boost_round=6)
    b2 = lgb.train({**params, "two_round": True},
                   lgb.Dataset(path, params={**params, "two_round": True}),
                   num_boost_round=6)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X), rtol=1e-6)


def test_two_round_rank_with_groups(tmp_path):
    rng = np.random.RandomState(4)
    n_q, per = 60, 25
    n = n_q * per
    X = rng.randn(n, 5)
    y = rng.randint(0, 3, n).astype(float)
    path = str(tmp_path / "rank.libsvm")
    with open(path, "w") as f:
        for i in range(n):
            toks = [f"{int(y[i])}", f"qid:{i // per + 1}"] + [
                f"{j}:{X[i, j]:.6g}" for j in range(5)]
            f.write(" ".join(toks) + "\n")
    ds = lgb.Dataset(path, params={"two_round": True,
                                   "objective": "lambdarank"}).construct()
    assert ds.metadata.query_boundaries is not None
    sizes = np.diff(ds.metadata.query_boundaries)
    assert (sizes == per).all()
    b = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                   "verbose": -1, "two_round": True, "min_data_in_leaf": 5},
                  lgb.Dataset(path, params={"two_round": True}),
                  num_boost_round=4)
    assert len(b._booster.models) == 4


def test_csc_and_coo_inputs():
    """CSC/COO inputs ride the same CSR adapter (reference: the CSC path
    of LGBM_DatasetCreateFromCSC, src/c_api.cpp)."""
    rng = np.random.RandomState(5)
    dense = np.where(rng.rand(1500, 8) < 0.2, rng.randn(1500, 8), 0.0)
    y = (dense[:, 0] + dense[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1}
    ref = lgb.train(params, lgb.Dataset(dense, label=y),
                    num_boost_round=5).predict(dense)
    for maker in (sp.csc_matrix, sp.coo_matrix):
        b = lgb.train(params, lgb.Dataset(maker(dense), label=y),
                      num_boost_round=5)
        np.testing.assert_allclose(b.predict(dense), ref, rtol=1e-6)
