"""Golden tests for split gain / leaf output math against hand-computed
values (SURVEY.md §7 order-of-construction step 1; mirrors the math of
reference feature_histogram.hpp:711-830)."""
import jax.numpy as jnp
import numpy as np
import pytest

from lambdagap_tpu.ops.split import (SplitParams, calculate_leaf_output,
                                     find_best_split, leaf_gain, threshold_l1)


def test_threshold_l1():
    assert float(threshold_l1(5.0, 2.0)) == 3.0
    assert float(threshold_l1(-5.0, 2.0)) == -3.0
    assert float(threshold_l1(1.0, 2.0)) == 0.0


def test_leaf_output_basic():
    p = SplitParams(lambda_l2=1.0)
    # -sum_g / (sum_h + l2)
    assert np.isclose(float(calculate_leaf_output(4.0, 3.0, p)), -1.0)


def test_leaf_output_max_delta_step():
    p = SplitParams(max_delta_step=0.5)
    assert np.isclose(float(calculate_leaf_output(10.0, 1.0, p)), -0.5)


def test_leaf_gain():
    p = SplitParams(lambda_l2=0.0)
    # g^2 / h
    assert np.isclose(float(leaf_gain(4.0, 2.0, p)), 8.0)


def _run_best(hist, parent, params, num_bins=None, missing=0, cat=False):
    F, B, _ = hist.shape
    nb = jnp.full((F,), B if num_bins is None else num_bins, jnp.int32)
    return find_best_split(
        jnp.asarray(hist, jnp.float32),
        jnp.float32(parent[0]), jnp.float32(parent[1]), jnp.float32(parent[2]),
        jnp.float32(0.0), nb,
        jnp.zeros(F, jnp.int32), jnp.full((F,), missing, jnp.int32),
        jnp.full((F,), cat), jnp.ones(F, bool), params,
        has_categorical=cat)


def test_obvious_split():
    """Two bins: all negative gradient in bin 0, positive in bin 1 —
    the split must separate them at threshold 0."""
    B = 8
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, 0] = [-10.0, 5.0, 50.0]
    hist[0, 1] = [+10.0, 5.0, 50.0]
    params = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=1e-3)
    res = _run_best(hist, (0.0, 10.0, 100.0), params)
    assert int(res.feature) == 0
    assert int(res.threshold) == 0
    # gain = 10^2/5 + 10^2/5 - 0 = 40
    assert np.isclose(float(res.gain), 40.0, rtol=1e-5)
    assert np.isclose(float(res.left_output), 2.0, rtol=1e-5)
    assert np.isclose(float(res.right_output), -2.0, rtol=1e-5)


def test_min_data_in_leaf_blocks_split():
    B = 8
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, 0] = [-10.0, 5.0, 5.0]
    hist[0, 1] = [+10.0, 5.0, 5.0]
    params = SplitParams(min_data_in_leaf=6)
    res = _run_best(hist, (0.0, 10.0, 10.0), params)
    assert not np.isfinite(float(res.gain))


def test_l2_reduces_gain():
    B = 4
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, 0] = [-10.0, 5.0, 50.0]
    hist[0, 1] = [10.0, 5.0, 50.0]
    g0 = _run_best(hist, (0.0, 10.0, 100.0), SplitParams(min_data_in_leaf=1))
    g1 = _run_best(hist, (0.0, 10.0, 100.0),
                   SplitParams(min_data_in_leaf=1, lambda_l2=5.0))
    assert float(g1.gain) < float(g0.gain)


def test_best_feature_chosen():
    B = 4
    hist = np.zeros((3, B, 3), np.float32)
    # feature 1 separates best
    hist[:, 0] = [-1.0, 5.0, 50.0]
    hist[:, 1] = [1.0, 5.0, 50.0]
    hist[1, 0] = [-20.0, 5.0, 50.0]
    hist[1, 1] = [20.0, 5.0, 50.0]
    res = _run_best(hist, (0.0, 10.0, 100.0), SplitParams(min_data_in_leaf=1))
    assert int(res.feature) == 1


def test_missing_nan_direction():
    """NaN bin content should flow to the better side via default_left."""
    B = 8
    nb = 4   # bins: 0,1,2 real; 3 = NaN bin
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, 0] = [-10.0, 5.0, 50.0]
    hist[0, 1] = [10.0, 5.0, 50.0]
    hist[0, 3] = [-5.0, 2.0, 20.0]   # NaN rows have negative grads (like bin 0)
    params = SplitParams(min_data_in_leaf=1)
    res = _run_best(hist, (-5.0, 12.0, 120.0), params, num_bins=nb, missing=2)
    assert int(res.threshold) == 0
    # best: NaN joins left (negative side)
    assert bool(res.default_left)
    assert np.isclose(float(res.left_sum_g), -15.0, atol=1e-4)


def test_categorical_onehot():
    B = 8
    nb = 4
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, 0] = [0.0, 1.0, 10.0]
    hist[0, 1] = [-9.0, 3.0, 30.0]    # category 1 is special
    hist[0, 2] = [3.0, 3.0, 30.0]
    hist[0, 3] = [3.0, 3.0, 30.0]
    params = SplitParams(min_data_in_leaf=1, cat_l2=0.0, cat_smooth=0.0,
                         max_cat_to_onehot=8)
    res = _run_best(hist, (-3.0, 10.0, 100.0), params, num_bins=nb, cat=True)
    assert bool(res.is_categorical)
    # bitset has exactly category-bin 1 going left
    assert int(res.cat_bitset[0]) == 2
