"""Out-of-core streaming training (``data_residency=stream``, ISSUE 7).

The acceptance surface, all runnable on CPU in tier-1:

- stream-residency training must produce trees BIT-IDENTICAL to the
  resident path — same windows through the same arithmetic in the same
  accumulation order — across serial + fused learners, both physical
  layouts, ragged final shards, bagging/GOSS masks (with and without the
  compacted-transfer path), and the Pallas histogram kernel;
- ``ShardedBinnedDataset`` builds streamingly (per-feature quantile
  sketches find bins without materializing the raw matrix; packed shards
  are written block-wise, optionally memory-mapped to disk);
- ``BinnedDataset.from_matrix`` no longer shadows the caller's matrix
  with a full float64 copy (peak transient memory ~1x packed output);
- SIGKILL + resume=auto under stream residency is byte-identical to an
  uninterrupted run (the guard sidecar carries the stream geometry).
"""
import os
import subprocess
import sys
import tracemalloc

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.config import Config
from lambdagap_tpu.data.binning import QuantileSketch
from lambdagap_tpu.data.dataset import BinnedDataset
from lambdagap_tpu.data.stream import ShardedBinnedDataset, stream_windows

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trees(booster) -> str:
    return booster.model_to_string().split("end of trees")[0]


def _data(n=3000, d=6, seed=11, cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    if cat:
        X[:, 0] = rng.randint(0, 9, n)
    y = (X[:, 1] + np.sin(X[:, 2] * 2)
         + ((X[:, 0] % 3) if cat else X[:, 3]) * 0.5 + 0.1 * rng.randn(n))
    return X, y


def _train(X, y, residency, fused, layout, extra=None, rounds=4,
           cat=False, shard_rows=1024):
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 10, "learning_rate": 0.2, "verbose": -1,
              "tpu_fused_learner": "1" if fused else "0",
              "tpu_hist_impl": "onehot", "tree_layout": layout,
              "data_residency": residency, "enable_bundle": False,
              "stream_shard_rows": shard_rows}
    if extra:
        params.update(extra)
    ds = lgb.Dataset(X, label=y,
                     categorical_feature=([0] if cat else "auto"),
                     params=params)
    return lgb.train(params, ds, num_boost_round=rounds)


# -- stream vs resident: bit-identical trees ----------------------------
# 3000 rows over shard_rows=1024 -> 3 shards with a ragged 952-row tail,
# and leaf slices cross shard boundaries from the first split on
@pytest.mark.parametrize("fused", [False, True])
@pytest.mark.parametrize("layout", ["gather", "sorted"])
def test_stream_matches_resident(fused, layout):
    X, y = _data()
    a = _train(X, y, "hbm", fused, layout)
    b = _train(X, y, "stream", fused, layout)
    assert _trees(a) == _trees(b)


@pytest.mark.parametrize("fused", [False, True])
@pytest.mark.parametrize("layout", ["gather", "sorted"])
def test_stream_goss_compaction_identical(fused, layout):
    """GOSS drives shard compaction: only in-bag rows cross the link per
    window; the device re-expands them into their lanes, and the masked
    lanes' exact-zero contributions keep the histograms bit-identical."""
    X, y = _data(seed=5)
    extra = {"data_sample_strategy": "goss", "top_rate": 0.2,
             "other_rate": 0.1, "learning_rate": 0.5}
    a = _train(X, y, "hbm", fused, layout, extra, rounds=5)
    b = _train(X, y, "stream", fused, layout, extra, rounds=5)
    c = _train(X, y, "stream", fused, layout,
               {**extra, "stream_goss_compact": False}, rounds=5)
    assert _trees(a) == _trees(b)
    assert _trees(a) == _trees(c)


def test_stream_bagging_and_categorical_identical():
    X, y = _data(seed=9, cat=True)
    extra = {"bagging_fraction": 0.6, "bagging_freq": 1}
    for fused in (False, True):
        a = _train(X, y, "hbm", fused, "gather", extra, cat=True)
        b = _train(X, y, "stream", fused, "gather", extra, cat=True)
        assert _trees(a) == _trees(b)


def test_stream_pallas_interpret_identical():
    """The Pallas kernel path (interpret mode on CPU) streams too: the
    uploaded window feeds the same hist_pallas call the resident chunk
    makes."""
    X, y = _data(n=1500)
    extra = {"tpu_hist_impl": "pallas"}
    a = _train(X, y, "hbm", True, "sorted", extra, rounds=2)
    b = _train(X, y, "stream", True, "sorted", extra, rounds=2)
    assert _trees(a) == _trees(b)


def test_stream_blocker_falls_back_to_hbm():
    """Options the fused stream subset does not replicate fall back to
    resident training loudly instead of silently changing semantics."""
    X, y = _data(n=1200)
    b = _train(X, y, "stream", True, "gather", {"extra_trees": True})
    learner = b._booster.learner
    assert learner.residency == "hbm"
    assert b.num_trees() > 0


@pytest.mark.parametrize("tree_learner", ["data", "voting", "feature"])
@pytest.mark.parametrize("fused", [True, False])
def test_stream_distributed_capability_matrix(tree_learner, fused, caplog):
    """ISSUE-15 satellite (flipping the ISSUE-8 cell): stream x
    distributed is now SUPPORTED for tree_learner=data on the fused 2-D
    learner — the composed out-of-core program streams host shards
    through the mesh with no demotion and no warning. Every other
    distributed learner (host-loop trio, fused voting/feature) still
    falls back to device-resident training with the documented WARNING,
    never silently and never by dying."""
    import logging
    X, y = _data(n=1500)
    supported = fused and tree_learner == "data"
    # verbose=0 keeps the package logger at WARNING: Config application
    # calls set_verbosity during train(), overriding caplog's level
    with caplog.at_level(logging.WARNING, logger="lambdagap_tpu"):
        b = _train(X, y, "stream", fused, "gather",
                   {"tree_learner": tree_learner, "tpu_num_devices": 2,
                    "verbose": 0})
    learner = b._booster.learner
    assert b.num_trees() > 0
    demotions = [r.message for r in caplog.records
                 if "data_residency=stream is not supported" in r.message]
    if supported:
        from lambdagap_tpu.parallel.fused_parallel import Fused2DTreeLearner
        assert isinstance(learner, Fused2DTreeLearner), type(learner).__name__
        assert learner.residency == "stream"
        assert (learner.dd, learner.ff) == (2, 1)
        assert demotions == [], demotions
    else:
        assert learner.residency == "hbm", type(learner).__name__
        assert any("falling back to data_residency=hbm" in m
                   for m in demotions), \
            [r.message for r in caplog.records]


def test_auto_residency_picks_stream_for_sharded_dataset():
    X, y = _data(n=2048)
    params = {"objective": "regression", "verbose": -1, "num_leaves": 7,
              "tpu_fused_learner": "1", "enable_bundle": False,
              "data_residency": "auto"}
    cfg = Config.from_params(params)
    sds = ShardedBinnedDataset.from_matrix(X, cfg, shard_rows=1024,
                                           label=y)
    booster = lgb.Booster(params=params, train_set=lgb.Dataset(sds))
    assert booster._booster.learner.residency == "stream"
    booster.update()
    # hbm is an explicit override even for a sharded dataset
    sds2 = ShardedBinnedDataset.from_matrix(X, cfg, shard_rows=1024,
                                            label=y)
    b2 = lgb.Booster(params=dict(params, data_residency="hbm"),
                     train_set=lgb.Dataset(sds2))
    assert b2._booster.learner.residency == "hbm"


# -- sharded construction ----------------------------------------------
def test_sharded_from_matrix_and_sequences_match_resident():
    X, _ = _data(n=2500)
    X[:, 2] = np.where(np.random.RandomState(0).rand(2500) < 0.4, 0.0,
                       X[:, 2])
    cfg = Config.from_params({"max_bin": 63, "verbose": -1})
    dm = BinnedDataset.from_matrix(X, cfg)

    class Seq:
        batch_size = 700

        def __len__(self):
            return len(X)

        def __getitem__(self, sl):
            return X[sl]

    dq = BinnedDataset.from_sequences([Seq()], cfg)
    for a, b in zip(dm.mappers, dq.mappers):
        assert a.bin_upper_bound == b.bin_upper_bound
    assert np.array_equal(dm.binned, dq.binned)

    sd = ShardedBinnedDataset.from_matrix(X, cfg, shard_rows=1024)
    assert sd.num_shards == 3
    assert sd.shards[-1].shape[0] == 2500 - 2 * 1024   # ragged tail
    assert np.array_equal(sd.binned, dm.binned)

    idx = np.random.RandomState(1).permutation(2500)[:333]
    assert np.array_equal(sd.gather_rows(idx), dm.binned[idx])
    assert np.array_equal(sd.gather_col(1, idx), dm.binned[idx, 1])
    assert np.array_equal(sd.row_block(900, 2100), dm.binned[900:2100])


def test_sharded_spill_dir_memmap(tmp_path):
    X, y = _data(n=2048)
    cfg = Config.from_params({"verbose": -1})
    sd = ShardedBinnedDataset.from_matrix(
        X, cfg, shard_rows=1024, spill_dir=str(tmp_path), label=y)
    assert all(isinstance(s, np.memmap) for s in sd.shards)
    assert len(list(tmp_path.glob("shard_*.bin"))) == sd.num_shards
    ref = BinnedDataset.from_matrix(X, cfg)
    assert np.array_equal(sd.binned, ref.binned)


def test_quantile_sketch_exact_below_budget():
    rng = np.random.RandomState(2)
    vals = np.concatenate([rng.randn(5000), [np.nan] * 37, [0.0] * 400])
    rng.shuffle(vals)
    sk = QuantileSketch(budget=1 << 16)
    for lo in range(0, len(vals), 517):          # ragged pushes
        sk.push(vals[lo:lo + 517])
    from lambdagap_tpu.data.binning import BinMapper
    ref = BinMapper.find_bin(
        vals[~np.isnan(vals) & (vals != 0.0)].tolist()
        + [np.nan] * 37, total_sample_cnt=len(vals), max_bin=255,
        min_data_in_bin=3)
    got = sk.to_mapper(max_bin=255, min_data_in_bin=3)
    assert got.bin_upper_bound == ref.bin_upper_bound
    assert got.missing_type == ref.missing_type


def test_quantile_sketch_compacts_beyond_budget():
    sk = QuantileSketch(budget=256)
    rng = np.random.RandomState(3)
    for _ in range(20):
        sk.push(rng.randn(10000))
    assert len(sk.distinct) <= 256
    m = sk.to_mapper(max_bin=63, min_data_in_bin=3)
    assert 2 <= m.num_bin <= 63
    bounds = [b for b in m.bin_upper_bound if np.isfinite(b)]
    assert bounds == sorted(bounds)


# -- from_matrix peak memory -------------------------------------------
def test_from_matrix_peak_memory():
    """The construction's transient allocations must be ~1x the packed
    output (plus bounded block temporaries), NOT a full float64 shadow of
    the caller's matrix."""
    n, d = 60000, 32
    X = np.random.RandomState(0).randn(n, d).astype(np.float32)
    cfg = Config.from_params({"verbose": -1, "max_bin": 63})
    raw_bytes = X.nbytes                     # 7.3 MB f32; f64 copy = 14.6
    tracemalloc.start()
    ds = BinnedDataset.from_matrix(X, cfg)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    packed = ds.binned.nbytes
    # pre-fix, construction shadowed the caller's matrix with a full
    # float64 copy (2x raw) held through find_bins + push — peak was
    # necessarily > 2x raw + packed. Post-fix the transients are the
    # packed output plus width-independent per-column temporaries
    # (~6 x n x 8 B), so peak stays under even ONE raw-matrix copy.
    assert peak < raw_bytes, (
        f"peak {peak / 2**20:.1f} MB vs raw {raw_bytes / 2**20:.1f} MB / "
        f"packed {packed / 2**20:.1f} MB — from_matrix is shadowing the "
        "input matrix again")


# -- the window pump ----------------------------------------------------
def test_stream_windows_order_and_depth():
    import jax.numpy as jnp
    fetched, consumed = [], []

    def fetch(c):
        fetched.append(c)
        return (np.full(4, c, np.float32),)

    def consume(c, buf):
        # every window must have been prefetched before it is consumed,
        # and with depth=2 the pump stays at most 2 ahead
        assert c in fetched
        assert len(fetched) - len(consumed) <= 2
        consumed.append(int(jnp.sum(buf)) // 4)

    stream_windows(7, fetch, consume, depth=2)
    assert consumed == list(range(7))
    assert fetched == list(range(7))


# -- SIGKILL + resume under stream residency ----------------------------
def _cli(args, tmp_path, faults=""):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    if faults:
        env["LAMBDAGAP_FAULTS"] = faults
    else:
        env.pop("LAMBDAGAP_FAULTS", None)
    return subprocess.run([sys.executable, "-m", "lambdagap_tpu", *args],
                          cwd=str(tmp_path), env=env, capture_output=True,
                          text=True, timeout=300)


def test_sigkill_resume_stream_identical_model(tmp_path):
    """SIGKILL a stream-residency CLI train mid-run, resume=auto, and
    require byte-identical trees vs an uninterrupted run: snapshots land
    at iteration boundaries where the shard cursor is at the start of the
    walk, and every RNG stream rides the sidecar as usual."""
    X, y = _data(2200, seed=3)
    np.savetxt(str(tmp_path / "train.csv"),
               np.column_stack([y, X]), delimiter=",", fmt="%.8g")
    args = ["task=train", "data=train.csv", "label_column=0",
            "objective=regression", "boost_from_average=false",
            "num_iterations=6", "snapshot_freq=1", "bagging_fraction=0.7",
            "bagging_freq=1", "min_data_in_leaf=5", "verbose=1",
            "resume=auto", "tpu_fused_learner=1", "enable_bundle=false",
            "data_residency=stream", "stream_shard_rows=1024"]
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path,
             faults="crash_at_iter=3")
    assert r.returncode == -9, f"expected SIGKILL, got {r.returncode}: " \
        f"{r.stdout}\n{r.stderr}"
    r = _cli(args + ["output_model=m_crash.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Resumed from snapshot" in r.stdout + r.stderr

    r = _cli(args + ["output_model=m_ref.txt"], tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    resumed = (tmp_path / "m_crash.txt").read_text()
    ref = (tmp_path / "m_ref.txt").read_text()
    assert resumed.split("end of trees")[0] == ref.split("end of trees")[0]


# -- block-wise file ingestion -----------------------------------------
def test_loader_blockwise_threshold_parity(tmp_path):
    """Files above stream_ingest_threshold_mb route through the bounded
    row-block sketch/push path (two_round machinery) and must bin
    identically to the eager single-parse (the sketch is exact at this
    scale)."""
    X, y = _data(16000, seed=13)
    path = tmp_path / "train.csv"
    np.savetxt(str(path), np.column_stack([y, X]), delimiter=",",
               fmt="%.8g")
    assert os.path.getsize(str(path)) > 1 << 20   # > the 1 MB threshold
    from lambdagap_tpu.data.loader import load_data_file
    a = load_data_file(str(path), Config.from_params(
        {"label_column": "0", "verbose": -1,
         "stream_ingest_threshold_mb": 10_000}))       # eager path
    b = load_data_file(str(path), Config.from_params(
        {"label_column": "0", "verbose": -1,
         "stream_ingest_threshold_mb": 1}))            # block-wise path
    assert np.array_equal(a.binned, b.binned)
    assert np.allclose(a.metadata.label, b.metadata.label)
    for ma, mb in zip(a.mappers, b.mappers):
        assert ma.bin_upper_bound == mb.bin_upper_bound
