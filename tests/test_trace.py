"""graftscope v2 (ISSUE 12): distributed request tracing, the fleet
metric plane, derived control signals, and the flight recorder.

The acceptance surface: one traced request through the serve stack must
produce a parent-linked span tree that tiles the client-observed wall
(schema-validated by ``obs.events.validate_file``); the fleet snapshot
over >= 2 replicas must equal the merge of the per-replica snapshots
(counter sums exact, reservoir quantiles consistent); sampling off must
add ZERO records; and a flight-recorder dump must be a valid JSONL the
postmortem tooling can render.
"""
import json
import os
import re
import time

import numpy as np
import pytest

import lambdagap_tpu as lgb
from lambdagap_tpu.obs import events, fleet, prom, signals, trace
from lambdagap_tpu.obs.reservoir import Reservoir, merge_states


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Every test starts from an empty, sampling-off process recorder."""
    trace.RECORDER.configure(sample=0.0)
    trace.RECORDER.reset()
    yield
    trace.RECORDER.configure(sample=0.0)
    trace.RECORDER.close()
    trace.RECORDER.reset()


@pytest.fixture(scope="module")
def booster():
    rng = np.random.RandomState(0)
    X = rng.randn(500, 8).astype(np.float32)
    y = (X[:, 0] - 0.3 * X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "tpu_fast_predict_rows": 0},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    return b, X


def _traced_submit(target, x, result_timeout=30.0):
    """Submit one traced request and record the client root span; returns
    the trace id."""
    ctx = trace.start_trace()
    t0_wall, t0 = time.time(), time.perf_counter()
    fut = target.submit(x, trace=ctx)
    fut.result(result_timeout)
    trace.RECORDER.record("client_request", ctx, t0_wall,
                          time.perf_counter() - t0,
                          span_id=ctx.span_id, parent="")
    return ctx.trace_id


# -- trace context ------------------------------------------------------
def test_trace_context_ids_wire_roundtrip():
    ctx = trace.start_trace()
    assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 16
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id
    wire = child.to_wire()
    back = trace.TraceContext.from_wire(wire)
    assert back.trace_id == ctx.trace_id
    assert back.span_id == child.span_id
    # hostile wire values degrade to untraced, never raise
    for bad in (None, "x", 7, [], {"id": 1}, {"id": "a"}, {"parent": "b"}):
        assert trace.TraceContext.from_wire(bad) is None


def test_sampling_knob():
    rec = trace.SpanRecorder(ring=64)
    rec.sample = 0.0
    assert rec.maybe_trace() is None
    rec.sample = 1.0
    ctx = rec.maybe_trace()
    assert ctx is not None and ctx.sampled


# -- in-process span tree ----------------------------------------------
def test_span_tree_tiles_served_request(booster):
    b, X = booster
    server = b.as_server(max_delay_ms=0.5)
    try:
        tid = _traced_submit(server, X[0])
    finally:
        server.close()
    spans = trace.RECORDER.spans(tid)
    names = {s["name"] for s in spans}
    assert {"client_request", "serve_request", "queue_wait",
            "registry_get", "dispatch"} <= names
    # parent-linked + containment + coverage within tolerance: the PR 4
    # span-sum≈wall discipline, applied to a request
    assert trace.validate_tree(spans, tid) == []
    # every span record passes the events schema
    for s in spans:
        assert events.validate_record(s) == []


def test_untraced_requests_add_zero_records(booster):
    b, X = booster
    server = b.as_server(max_delay_ms=0.5)
    try:
        for i in range(4):
            server.predict(X[i])
    finally:
        server.close()
    assert trace.RECORDER.tail() == []
    assert trace.RECORDER.n_spans == 0


def test_span_jsonl_schema_roundtrip(booster, tmp_path):
    b, X = booster
    out = str(tmp_path / "spans.jsonl")
    trace.RECORDER.configure(out=out)
    server = b.as_server(max_delay_ms=0.5)
    try:
        tid = _traced_submit(server, X[0])
    finally:
        server.close()
        trace.RECORDER.close()
    assert events.validate_file(out) == []
    recs, truncated = events.read_file(out)
    assert not truncated
    assert recs[0]["type"] == "run_header"
    spans = [r for r in recs if r["type"] == "span"]
    assert {s["trace"] for s in spans} == {tid}
    assert trace.validate_tree(spans, tid) == []


def test_registry_readmission_visible_per_request(booster):
    b, X = booster
    server = b.as_server(buckets=(8,), max_delay_ms=0.5)
    try:
        server.predict(X[:4])
        bytes0 = server.registry.entry("default").bytes
        server.registry.hbm_budget_bytes = int(1.5 * bytes0)
        server.add_model("b", b._booster)     # evicts "default"
        assert not server.registry.entry("default").resident
        tid = _traced_submit(server, X[0])    # pays the readmission
    finally:
        server.close()
    spans = trace.RECORDER.spans(tid)
    get_span = next(s for s in spans if s["name"] == "registry_get")
    assert get_span["attrs"].get("readmitted") is True
    assert get_span["attrs"]["build_s"] > 0
    # the nested compile share is its own span under registry_get
    readmit = next(s for s in spans if s["name"] == "registry_readmit")
    assert readmit["parent"] == get_span["span"]
    assert trace.validate_tree(spans, tid) == []


# -- over the wire ------------------------------------------------------
def test_frontend_trace_minting_and_cross_hop_tree(booster):
    from lambdagap_tpu.serve import FrontendClient, ServeFrontend
    b, X = booster
    server = b.as_server(max_delay_ms=0.5)
    fe = ServeFrontend(server).start()
    client = FrontendClient("127.0.0.1", fe.port)
    try:
        # minted at the FrontendClient per serve_trace_sample
        trace.RECORDER.configure(sample=1.0)
        client.predict(X[0])
        trace.RECORDER.configure(sample=0.0)
        time.sleep(0.2)                  # reply callbacks settle
        spans = trace.RECORDER.spans()
        tid = spans[0]["trace"]
        names = {s["name"] for s in spans}
        assert {"client_request", "frontend", "serve_request",
                "queue_wait", "dispatch", "encode"} <= names
        assert trace.validate_tree(spans, tid) == []
        root = next(s for s in spans if s["name"] == "client_request")
        assert root["parent"] is None
    finally:
        client.close()
        fe.close()
        server.close()


def test_routed_span_tree_carries_route_hop(booster):
    from lambdagap_tpu.serve import LocalReplica, Router
    b, X = booster
    servers = [b.as_server(max_delay_ms=0.5) for _ in range(2)]
    router = Router([LocalReplica(f"r{i}", s)
                     for i, s in enumerate(servers)], own_replicas=True)
    try:
        tid = _traced_submit(router, X[0])
    finally:
        router.close()
    spans = trace.RECORDER.spans(tid)
    names = {s["name"] for s in spans}
    assert {"client_request", "route", "serve_request", "queue_wait",
            "dispatch"} <= names
    route = next(s for s in spans if s["name"] == "route")
    assert route["attrs"]["replica"] in ("r0", "r1")
    assert route["attrs"]["failovers"] == 0
    assert trace.validate_tree(spans, tid) == []


# -- fleet metric plane -------------------------------------------------
def test_reservoir_state_and_merge_weight_correct():
    a, b = Reservoir(cap=100, seed=1), Reservoir(cap=100, seed=2)
    for v in (1.0, 2.0, 3.0):
        a.add(v)
    for v in (10.0, 20.0):
        b.add(v)
    m = merge_states([a.state(), b.state()])
    assert m.seen == 5
    p = m.percentiles()
    assert p["max"] == 20.0
    assert p["p50"] == 3.0               # 3rd of 5 equally weighted values
    # weights follow seen, not kept: a reservoir that SAW 300 but kept 3
    # outweighs one that saw 2, 100:1 per kept value
    heavy = {"seen": 300, "vals": [1.0, 2.0, 3.0]}
    light = {"seen": 2, "vals": [10.0, 20.0]}
    p = merge_states([heavy, light]).percentiles()
    assert p["p50"] == 2.0 and p["p95"] == 3.0
    # units survive scaling; downsample keeps quantiles
    r = Reservoir(cap=4096, seed=3)
    for i in range(4096):
        r.add(float(i))
    st = r.state(scale=2.0, max_vals=64)
    assert len(st["vals"]) == 64 and st["seen"] == 4096
    assert st["vals"][0] == 0.0 and st["vals"][-1] == 2.0 * 4095


def test_fleet_snapshot_equals_manual_merge(booster):
    from lambdagap_tpu.serve import LocalReplica, Router
    b, X = booster
    servers = [b.as_server(max_delay_ms=0.5) for _ in range(2)]
    router = Router([LocalReplica(f"r{i}", s)
                     for i, s in enumerate(servers)], own_replicas=True)
    try:
        # traffic directly per replica so both have distinct counters
        for i in range(3):
            servers[0].predict(X[i], tenant="acme")
        for i in range(5):
            servers[1].predict(X[i], tenant="zed")
        manual = [s.stats_snapshot(reservoirs=True) for s in servers]
        snap = router.fleet_snapshot()
        merged = snap["merged"]
        # counter sums exact
        for key in ("requests", "rows", "errors", "timeouts", "rejected",
                    "swaps", "evictions", "readmissions"):
            assert merged[key] == sum(m[key] for m in manual), key
        assert merged["requests"] == 8
        assert merged["replica_count"] == 2
        # reservoir quantiles consistent: the fleet plane's quantiles ARE
        # the deterministic merge of the per-replica states
        expect = merge_states(
            [m["reservoirs"]["latency_ms"] for m in manual]).percentiles()
        assert merged["latency_ms"] == expect
        # label-preserving tenant rollup
        assert merged["per_tenant"]["acme"]["requests"] == 3
        assert merged["per_tenant"]["zed"]["requests"] == 5
        # registry rollup counts residency per replica
        models = merged["registry"]["models"]
        assert models["default"]["resident_replicas"] == 2
        assert snap["replicas"] == ["r0", "r1"]
    finally:
        router.close()


def test_prometheus_fleet_verb_single_server(booster):
    import io
    from lambdagap_tpu.serve import serve_loop
    b, X = booster
    server = b.as_server()
    try:
        server.predict(X[0])
        out, stats = io.StringIO(), io.StringIO()
        serve_loop(server, ["prometheus fleet"], out, stats_stream=stats)
        text = stats.getvalue()
    finally:
        server.close()
    assert "lambdagap_fleet_replicas 1" in text
    assert "lambdagap_serve_requests_total 1" in text


# -- prometheus fleet exposition: hostile labels ------------------------
_HEADER = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")
_SAMPLE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$")
# exposition-format label values: escaped backslash/quote/newline only
_LABELS = re.compile(
    r'\{([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*")'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*")*\}')


def test_prometheus_fleet_hostile_labels():
    from lambdagap_tpu.serve.stats import ServeStats
    hostile_model = 'mo"del\\v1\nprod'
    hostile_tenant = 'acme "corp"\\'
    stats = [ServeStats(), ServeStats()]
    for i, st in enumerate(stats):
        st.record_request(0.001, 0.002, 0.004 + i * 0.001, rows=2,
                          model=hostile_model, tenant=hostile_tenant)
        st.record_eviction(model=hostile_model)
    snaps = [st.snapshot(reservoirs=True) for st in stats]
    for snap in snaps:
        snap["registry"] = {"registered_models": 1, "resident_models": 1,
                            "hbm_bytes_resident": 128,
                            "hbm_budget_bytes": 0,
                            "models": {hostile_model: {"resident": True,
                                                       "builds": 1,
                                                       "hbm_bytes": 128}}}
    merged = fleet.merge_snapshots(snaps)
    router_snap = {"failovers": 0, "rejected_no_replica": 0,
                   "replicas": {'r"0\n': {"routed": 2, "inflight": 0,
                                          "health": "ok", "dead": False}}}
    text = prom.render_fleet(merged, router=router_snap)
    for ln in [ln for ln in text.splitlines() if ln]:
        if ln.startswith("#"):
            assert _HEADER.match(ln), f"bad header: {ln!r}"
            continue
        m = _SAMPLE.match(ln)
        assert m, f"unparseable sample: {ln!r}"
        float(m.group(3))
        if m.group(2):
            assert _LABELS.fullmatch(m.group(2)), f"bad labels: {ln!r}"
    # the hostile names render escaped, not raw
    assert 'mo\\"del\\\\v1\\nprod' in text
    assert "\nprod" not in text.replace("\\nprod", "")
    assert merged["per_model"][hostile_model]["requests"] == 2


# -- events durability --------------------------------------------------
def test_validate_file_tolerates_torn_final_line(tmp_path):
    p = tmp_path / "torn.jsonl"
    hdr = json.dumps(events.run_header({}))
    span = json.dumps({"type": "span", "trace": "t" * 16, "span": "s" * 16,
                       "parent": None, "name": "dispatch", "t0": 1.0,
                       "dur": 0.5})
    # SIGKILL mid-write: the final line has no trailing newline
    p.write_text(hdr + "\n" + span + "\n" + span[: len(span) // 2])
    assert events.validate_file(str(p)) == []
    recs, truncated = events.read_file(str(p))
    assert truncated
    assert [r["type"] for r in recs] == ["run_header", "span"]
    # a COMPLETE bad line (newline-terminated) is still an error
    p2 = tmp_path / "bad.jsonl"
    p2.write_text(hdr + "\nnot json\n")
    assert any("not JSON" in e for e in events.validate_file(str(p2)))


# -- flight recorder + postmortem ---------------------------------------
def test_flight_recorder_dump_and_postmortem(tmp_path, booster):
    import importlib.util
    b, X = booster
    dump = str(tmp_path / "proc.flight")
    server = b.as_server(max_delay_ms=0.5)
    fr = trace.FlightRecorder(dump, params={"who": "test"})
    try:
        tid = _traced_submit(server, X[0])
        trace.RECORDER.event("test_marker", detail="before-dump")
        fr.dump(reason="test")
    finally:
        server.close()
    assert events.validate_file(dump) == []
    recs, _trunc = events.read_file(dump)
    assert recs[0]["type"] == "run_header"
    assert recs[0]["params"]["reason"] == "test"
    assert any(r.get("type") == "span" and r.get("trace") == tid
               for r in recs)
    assert any(r.get("event") == "test_marker" for r in recs)
    # the postmortem renderer names the process and its last span
    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "postmortem.py"))
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    sources = pm.load([dump])
    merged = pm.merge(sources)
    text = pm.render(sources, merged)
    assert "last span of proc.flight" in text
    assert trace.RECORDER.proc in text


def test_postmortem_tolerates_torn_flight_dump(tmp_path, booster):
    """ISSUE 13 satellite: one SIGKILL-torn dump (truncated final JSON,
    even mid-byte-sequence garbage) must NOT abort the merged timeline —
    the intact sources still render, the torn one reports truncation,
    exactly like obs/events.read_file's torn-final-line contract."""
    import importlib.util
    b, X = booster
    good = str(tmp_path / "r1.flight")
    server = b.as_server(max_delay_ms=0.5)
    fr = trace.FlightRecorder(good, params={"who": "survivor"})
    try:
        _traced_submit(server, X[0])
        fr.dump(reason="test")
    finally:
        server.close()
    # tear a copy of the good dump mid-record, then corrupt the tail
    # with bytes that are not valid UTF-8 (a half-recovered disk)
    raw = open(good, "rb").read()
    torn = str(tmp_path / "r0.flight")
    with open(torn, "wb") as f:
        f.write(raw[: int(len(raw) * 0.6)] + b"\xe2\x82")
    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "postmortem.py"))
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    sources = pm.load([torn, good])      # must not raise
    by_path = {os.path.basename(p): (r, t) for p, r, t in sources}
    assert by_path["r0.flight"][1] is True         # truncation reported
    assert by_path["r1.flight"][1] is False
    merged = pm.merge(sources)
    assert merged                         # the intact source's records
    text = pm.render(sources, merged)
    assert "TRUNCATED" in text
    assert "last span of r1.flight" in text
    # the torn dump's parseable prefix still contributes evidence
    assert any(src == "r0.flight" for _t, src, _r in merged)
    # and main() exits 0 on the same inputs (truncation != failure)
    assert pm.main([torn, good]) == 0


def test_postmortem_skips_structurally_torn_records(tmp_path):
    """Records that parse but lost fields (interior corruption) degrade
    to best-effort rendering, never a KeyError abort."""
    import importlib.util
    import json as _json
    path = str(tmp_path / "weird.flight")
    with open(path, "w") as f:
        f.write(_json.dumps({"type": "run_header", "schema_version": 1,
                             "time_unix": 1.0, "params": "torn"}) + "\n")
        f.write(_json.dumps({"type": "span", "t0": 2.0}) + "\n")
        f.write(_json.dumps({"type": "span", "trace": "t", "span": "s",
                             "name": "dispatch", "t0": "garbage"}) + "\n")
        f.write(_json.dumps({"type": "event", "event": "x",
                             "time_unix": 3.0}) + "\n")
    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "postmortem.py"))
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    sources = pm.load([path])
    merged = pm.merge(sources)
    # the numeric-t0 span and the event survive; the garbage-t0 one is
    # skipped rather than crashing the sort
    assert [r.get("type") for _t, _s, r in merged] == ["span", "event"]
    text = pm.render(sources, merged)
    assert "dispatch" not in text         # the torn span was dropped
    assert "!x" in text


def test_flight_recorder_periodic_dump(tmp_path):
    dump = str(tmp_path / "tick.flight")
    rec = trace.SpanRecorder(ring=64, proc="ticker")
    fr = trace.FlightRecorder(dump, recorder=rec, interval_s=0.05)
    fr.install()
    try:
        rec.event("heartbeat")
        time.sleep(0.25)
        assert os.path.exists(dump)
        assert fr.dumps >= 2
        assert events.validate_file(dump) == []
    finally:
        fr.close()


# -- signal plane -------------------------------------------------------
def _fake_fleet_snap(t, requests, timeouts=0, rejected=0, evictions=0,
                     readmissions=0, health="ok"):
    return {
        "type": "fleet_snapshot", "time_unix": t,
        "replicas": ["r0"],
        "router": {"replicas": {"r0": {"health": health, "dead": False}}},
        "merged": {"requests": requests, "timeouts": timeouts,
                   "rejected": rejected, "errors": 0,
                   "evictions": evictions, "readmissions": readmissions,
                   "registry": {"registered_models": 2,
                                "resident_models": 1,
                                "hbm_bytes_resident": 100,
                                "hbm_budget_bytes": 200,
                                "models": {"m": {"resident_replicas": 1,
                                                 "replicas": 1,
                                                 "builds": 3,
                                                 "hbm_bytes": 100}}}},
    }


def test_signal_plane_schema_and_knee():
    plane = signals.SignalPlane(alpha=0.5, good_ratio=0.9)
    t = 1000.0
    requests = 0
    # ramp at healthy goodput: the knee should track the offered rate up
    for rate in (100, 100, 200, 200, 400, 400):
        t += 1.0
        requests += rate
        tick = plane.update(_fake_fleet_snap(t, requests))
        assert signals.validate_signals(tick) == []
        assert events.validate_record(tick) == []
    good_knee = tick["goodput"]["knee_rps"]
    assert good_knee > 150
    assert -1e-9 <= tick["goodput"]["knee_margin"] <= 1.0
    # saturation: offered rises but half the requests shed -> the knee
    # stops rising and the margin collapses
    timeouts = 0
    for _ in range(4):
        t += 1.0
        requests += 800
        timeouts += 400
        tick = plane.update(_fake_fleet_snap(t, requests,
                                             timeouts=timeouts))
    assert tick["goodput"]["good_fraction"] < 0.9
    assert tick["goodput"]["knee_margin"] < 0.2
    # residency block carries the per-model placement inputs
    res = tick["residency"]
    assert res["resident_models"] == 1
    assert res["per_model"]["m"]["resident_replicas"] == 1
    # health timeline recorded the steady state once (no flapping noise)
    assert tick["health"]["current"] == {"r0": "ok"}
    assert len(tick["health"]["transitions"]) == 1


def test_health_timeline_records_transitions():
    tl = signals.HealthTimeline(ring=8)
    assert tl.note("r0", "ok", t=1.0)
    assert not tl.note("r0", "ok", t=2.0)       # no transition, no entry
    assert tl.note("r0", "degraded", t=3.0)
    assert tl.note("r0", "dead", t=4.0)
    snap = tl.snapshot()
    assert snap["current"] == {"r0": "dead"}
    assert [e["state"] for e in snap["transitions"]] == \
        ["ok", "degraded", "dead"]


def test_router_signals_via_scraper(booster):
    from lambdagap_tpu.serve import (FleetScraper, LocalReplica, Router,
                                     SignalPlane)
    b, X = booster
    servers = [b.as_server(max_delay_ms=0.5) for _ in range(2)]
    router = Router([LocalReplica(f"r{i}", s)
                     for i, s in enumerate(servers)], own_replicas=True)
    try:
        with pytest.raises(ValueError):
            router.signals()             # no plane attached yet
        scraper = FleetScraper(router, signals=SignalPlane())
        router.attach_scraper(scraper)
        for i in range(3):
            router.predict(X[i], timeout=30)
        scraper.scrape()
        tick = router.signals()
        assert signals.validate_signals(tick) == []
        assert tick["health"]["current"]["r0"] == "ok"
        assert router.fleet_snapshot()["merged"]["requests"] == 3
    finally:
        router.close()
