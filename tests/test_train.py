"""End-to-end training tests (reference analog:
tests/python_package_test/test_engine.py — small synthetic datasets, few
iterations, metric-threshold assertions)."""
import numpy as np
import pytest
from sklearn.datasets import make_classification, make_regression

import lambdagap_tpu as lgb


def _reg_data(n=1500, d=10, seed=0):
    X, y = make_regression(n, d, n_informative=6, noise=5.0, random_state=seed)
    return X, y


def _bin_data(n=2000, d=15, seed=0):
    return make_classification(n, d, n_informative=8, random_state=seed)


def test_regression_decreasing_loss():
    X, y = _reg_data()
    ds = lgb.Dataset(X, label=y)
    res = {}
    booster = lgb.train({"objective": "regression", "metric": "l2",
                         "num_leaves": 15, "verbose": -1},
                        ds, num_boost_round=30,
                        valid_sets=[ds], valid_names=["training"],
                        callbacks=[lgb.record_evaluation(res)])
    l2 = res["training"]["l2"]
    assert l2[-1] < l2[0] * 0.2
    assert all(b <= a + 1e-9 for a, b in zip(l2, l2[1:]))


def test_binary_auc():
    X, y = _bin_data()
    ds = lgb.Dataset(X[:1500], label=y[:1500])
    vs = ds.create_valid(X[1500:], label=y[1500:])
    res = {}
    booster = lgb.train({"objective": "binary", "metric": ["auc", "binary_logloss"],
                         "num_leaves": 31, "verbose": -1},
                        ds, num_boost_round=50, valid_sets=[vs],
                        callbacks=[lgb.record_evaluation(res)])
    assert res["valid_0"]["auc"][-1] > 0.93
    preds = booster.predict(X[1500:])
    assert preds.shape == (500,)
    assert np.all((preds >= 0) & (preds <= 1))


def test_multiclass():
    X, y = make_classification(2000, 20, n_informative=10, n_classes=4,
                               random_state=3)
    ds = lgb.Dataset(X[:1500], label=y[:1500])
    vs = ds.create_valid(X[1500:], label=y[1500:])
    res = {}
    booster = lgb.train({"objective": "multiclass", "num_class": 4,
                         "metric": "multi_logloss", "verbose": -1},
                        ds, num_boost_round=30, valid_sets=[vs],
                        callbacks=[lgb.record_evaluation(res)])
    ml = res["valid_0"]["multi_logloss"]
    assert ml[-1] < ml[0]
    preds = booster.predict(X[1500:])
    assert preds.shape == (500, 4)
    np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)
    acc = np.mean(np.argmax(preds, axis=1) == y[1500:])
    assert acc > 0.6


def test_early_stopping():
    X, y = _bin_data(seed=5)
    ds = lgb.Dataset(X[:1000], label=y[:1000])
    vs = ds.create_valid(X[1000:], label=y[1000:])
    booster = lgb.train({"objective": "binary", "metric": "binary_logloss",
                         "verbose": -1, "early_stopping_round": 5,
                         "num_leaves": 63, "learning_rate": 0.3},
                        ds, num_boost_round=500, valid_sets=[vs])
    assert 0 < booster.best_iteration < 500


def test_weights_change_model():
    X, y = _reg_data(seed=2)
    w = np.where(y > np.median(y), 10.0, 0.1)
    p = {"objective": "regression", "verbose": -1, "num_leaves": 7}
    b0 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=10)
    b1 = lgb.train(p, lgb.Dataset(X, label=y, weight=w), num_boost_round=10)
    assert not np.allclose(b0.predict(X), b1.predict(X))


def test_bagging_and_feature_fraction():
    X, y = _bin_data(seed=6)
    booster = lgb.train({"objective": "binary", "verbose": -1,
                         "bagging_fraction": 0.5, "bagging_freq": 1,
                         "feature_fraction": 0.7, "metric": "auc"},
                        lgb.Dataset(X, label=y), num_boost_round=20,
                        valid_sets=[lgb.Dataset(X, label=y, reference=None)])
    # still learns signal
    pred = booster.predict(X)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, pred) > 0.85


def test_goss():
    X, y = _bin_data(seed=7)
    booster = lgb.train({"objective": "binary", "verbose": -1,
                         "data_sample_strategy": "goss",
                         "learning_rate": 0.1},
                        lgb.Dataset(X, label=y), num_boost_round=30)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, booster.predict(X)) > 0.9


def test_boosting_goss_alias():
    X, y = _bin_data(seed=8)
    booster = lgb.train({"objective": "binary", "boosting": "goss",
                         "verbose": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=5)
    assert booster.num_trees() == 5


def test_min_data_in_leaf_respected():
    X, y = _reg_data(n=300)
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "min_data_in_leaf": 50, "num_leaves": 31},
                        lgb.Dataset(X, label=y), num_boost_round=5)
    for tree in booster._booster.models:
        counts = tree.leaf_count[:tree.num_leaves]
        assert counts.min() >= 50


def test_max_depth():
    X, y = _reg_data(n=1000)
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "max_depth": 3, "num_leaves": 31},
                        lgb.Dataset(X, label=y), num_boost_round=5)
    for tree in booster._booster.models:
        assert tree.max_depth <= 3


def test_categorical_feature_training():
    rng = np.random.RandomState(11)
    n = 2000
    cat = rng.randint(0, 5, n)
    num = rng.randn(n)
    y = (cat == 2) * 3.0 + (cat == 4) * -2.0 + 0.5 * num + 0.05 * rng.randn(n)
    X = np.column_stack([cat.astype(float), num])
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "num_leaves": 15, "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y, categorical_feature=[0]),
                        num_boost_round=40)
    pred = booster.predict(X)
    assert np.mean((pred - y) ** 2) < 0.1 * np.var(y)


def test_categorical_high_cardinality_values():
    """Raw category values >= 256 must route correctly at predict time
    (variable-width bitsets; reference sizes them dynamically via
    Common::ConstructBitset)."""
    rng = np.random.RandomState(21)
    n = 2000
    cat = rng.randint(300, 310, n)          # all values above the old 256 cap
    num = rng.randn(n)
    y = (cat == 302) * 3.0 + (cat == 308) * -2.0 + 0.5 * num + 0.05 * rng.randn(n)
    X = np.column_stack([cat.astype(float), num])
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "num_leaves": 15, "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y, categorical_feature=[0]),
                        num_boost_round=40)
    pred = booster.predict(X)
    assert np.mean((pred - y) ** 2) < 0.1 * np.var(y)
    # text round-trip keeps the wide bitsets too
    reloaded = lgb.Booster(model_str=booster.model_to_string())
    pred2 = reloaded.predict(X)
    np.testing.assert_allclose(pred, pred2, rtol=1e-5, atol=1e-5)


def test_missing_values_nan():
    rng = np.random.RandomState(12)
    n = 2000
    x0 = rng.randn(n)
    y = np.where(np.isnan(x0), 5.0, x0 * 2.0)
    x0[rng.rand(n) < 0.3] = np.nan
    y = np.where(np.isnan(x0), 5.0, x0 * 2.0)
    X = np.column_stack([x0, rng.randn(n)])
    booster = lgb.train({"objective": "regression", "verbose": -1,
                         "num_leaves": 31}, lgb.Dataset(X, label=y),
                        num_boost_round=40)
    pred = booster.predict(X)
    assert np.mean((pred - y) ** 2) < 0.05 * np.var(y)


def test_dart_training():
    """DART drops + renormalizes via the batched forest path
    (reference: dart.hpp DroppingTrees/Normalize)."""
    X, y = _reg_data(n=800, seed=31)
    booster = lgb.train({"objective": "regression", "boosting": "dart",
                         "drop_rate": 0.4, "verbose": -1, "num_leaves": 15},
                        lgb.Dataset(X, label=y), num_boost_round=20,
                        valid_sets=[lgb.Dataset(X[:200], label=y[:200],
                                                reference=None)])
    pred = booster.predict(X)
    assert np.mean((pred - y) ** 2) < 0.7 * np.var(y)


def test_init_score():
    X, y = _reg_data(seed=13)
    init = np.full(len(y), 100.0)
    booster = lgb.train({"objective": "regression", "verbose": -1},
                        lgb.Dataset(X, label=y + 100.0, init_score=init),
                        num_boost_round=10)
    # model learns residual around init score; prediction excludes init score
    pred = booster.predict(X)
    assert abs(np.mean(pred) - np.mean(y)) < 5.0


def test_cv_runs():
    X, y = _bin_data(seed=14)
    res = lgb.cv({"objective": "binary", "metric": "auc", "verbose": -1},
                 lgb.Dataset(X, label=y, free_raw_data=False),
                 num_boost_round=10, nfold=3)
    assert "valid auc-mean" in res
    assert len(res["valid auc-mean"]) == 10
    assert res["valid auc-mean"][-1] > 0.85


def test_forest_predict_tree_blocks():
    """The device forest scan dispatches in bounded tree blocks with the
    accumulator carried between kernels (no kernel grows with T — the fix
    for 500-tree forests faulting a tunneled chip worker); results are
    bit-comparable to the single-dispatch scan for plain, early-stop, and
    padding (odd block) configurations."""
    import jax.numpy as jnp
    from lambdagap_tpu.ops.predict import forest_to_arrays, predict_forest
    rng = np.random.RandomState(0)
    X = rng.randn(1200, 8)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "min_data_in_leaf": 5}, lgb.Dataset(X, label=y),
                  num_boost_round=150)
    forest, depth = forest_to_arrays(b._booster.host_models)
    tc = jnp.zeros(150, jnp.int32)
    xd = jnp.asarray(X[:256])
    single = np.asarray(predict_forest(xd, forest, tc, 1, depth, False,
                                       tree_block=10**9))
    for kw in ({"tree_block": 64}, {"tree_block": 37},
               {"tree_block": 64, "early_stop_freq": 10,
                "early_stop_margin": 3.0}):
        want = single
        if "early_stop_freq" in kw:
            want = np.asarray(predict_forest(
                xd, forest, tc, 1, depth, False, tree_block=10**9,
                early_stop_freq=10, early_stop_margin=3.0))
        got = np.asarray(predict_forest(xd, forest, tc, 1, depth, False,
                                        **kw))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # leaf-index prediction blocks the same way (refit/linear replay path)
    from lambdagap_tpu.ops.predict import predict_forest_leaf
    leaf_single = np.asarray(predict_forest_leaf(xd, forest, depth, False,
                                                 tree_block=10**9))
    leaf_blocked = np.asarray(predict_forest_leaf(xd, forest, depth, False,
                                                  tree_block=37))
    np.testing.assert_array_equal(leaf_single, leaf_blocked)
