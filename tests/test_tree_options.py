"""CEGB, interaction constraints, per-node feature sampling, prediction
early stop.

(reference: src/treelearner/cost_effective_gradient_boosting.hpp;
src/treelearner/col_sampler.hpp; src/boosting/prediction_early_stop.cpp;
test models: tests/python_package_test/test_basic.py:407 CEGB cases,
test_engine.py interaction_constraints cases)
"""
import numpy as np
import pytest

import lambdagap_tpu as lgb


def _data(n=1200, d=6, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    w = np.asarray([1.0, 0.9, 0.8, 0.7, 0.6, 0.5])[:d]
    y = X @ w + 0.4 * X[:, 2] * X[:, 3] + 0.1 * rng.randn(n)
    return X, y


BASE = {"objective": "regression", "num_leaves": 15, "min_data_in_leaf": 10,
        "learning_rate": 0.1, "verbose": -1}


def _used_features(b):
    return {f for t in b._booster.host_models
            for f in t.split_feature[:t.num_internal]}


def test_cegb_coupled_penalty_limits_features():
    X, y = _data()
    plain = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=10)
    assert len(_used_features(plain)) >= 4
    # huge coupled penalty on all but features 0/1: model should avoid them
    pen = [0.0, 0.0] + [1e6] * 4
    b = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                   "cegb_penalty_feature_coupled": pen},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    assert _used_features(b) <= {0, 1}


def test_cegb_split_penalty_reduces_splits():
    X, y = _data()
    plain = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=5)
    b = lgb.train({**BASE, "cegb_tradeoff": 1.0, "cegb_penalty_split": 10.0},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    n_plain = sum(t.num_internal for t in plain._booster.host_models)
    n_pen = sum(t.num_internal for t in b._booster.host_models)
    assert n_pen < n_plain


def test_cegb_lazy_penalty_limits_features():
    """cegb_penalty_feature_lazy: per-datum on-demand cost — a candidate
    (leaf, feature) pays lazy[f] per in-leaf row not yet routed through an
    f-split, and applying a split marks the leaf's rows (reference:
    CalculateOndemandCosts + the UpdateLeafBestSplits bitset,
    cost_effective_gradient_boosting.hpp:125-164)."""
    X, y = _data()
    # prohibitive lazy cost on all but features 0/1: first touches are
    # priced per row, so the model should never afford them
    pen = [0.0, 0.0] + [1e6] * 4
    b = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                   "cegb_penalty_feature_lazy": pen},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    assert _used_features(b) <= {0, 1}
    # a small lazy penalty reduces feature spread vs no penalty but keeps
    # the model functional (the marked rows stop paying on reuse, so a
    # feature that earned its first use stays usable)
    small = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                       "cegb_penalty_feature_lazy": [0.001] * 6},
                      lgb.Dataset(X, label=y), num_boost_round=10)
    plain = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=10)
    mse_pen = float(np.mean((small.predict(X) - y) ** 2))
    mse_plain = float(np.mean((plain.predict(X) - y) ** 2))
    assert mse_pen < 2.0 * mse_plain + 0.1, (mse_pen, mse_plain)
    # reuse is cheaper than first use: with a uniform moderate penalty the
    # tree re-splits on already-paid features more than spreading out
    mod = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_lazy": [0.05] * 6},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    assert len(_used_features(mod)) <= len(_used_features(plain))


def test_cegb_lazy_with_bagging_in_bag_only():
    """Lazy CEGB under bagging charges and marks IN-BAG rows only (the
    reference's bagged data_partition_ holds in-bag indices; our partition
    routes out-of-bag rows too, so the lazy path must filter)."""
    X, y = _data()
    pen = [0.0, 0.0] + [1e6] * 4
    b = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                   "cegb_penalty_feature_lazy": pen,
                   "bagging_fraction": 0.6, "bagging_freq": 1},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    assert _used_features(b) <= {0, 1}
    # training still works and beats a constant predictor
    mse = float(np.mean((b.predict(X) - y) ** 2))
    assert mse < float(np.var(y))


def test_interaction_constraints_respected():
    X, y = _data()
    b = lgb.train({**BASE, "interaction_constraints": [[0, 1], [2, 3, 4, 5]]},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    # every root->leaf path must stay within one constraint group
    groups = [frozenset([0, 1]), frozenset([2, 3, 4, 5])]
    for t in b._booster.host_models:
        def walk(node, path):
            if node < 0:
                if path:
                    assert any(path <= g for g in groups), path
                return
            p2 = path | {t.split_feature[node]}
            walk(t.left_child[node], p2)
            walk(t.right_child[node], p2)
        if t.num_internal:
            walk(0, frozenset())


def test_feature_fraction_bynode_trains():
    X, y = _data()
    b = lgb.train({**BASE, "feature_fraction_bynode": 0.5},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    resid = y - b.predict(X)
    assert np.var(resid) < 0.5 * np.var(y)
    # different nodes see different feature subsets -> more diverse features
    assert len(_used_features(b)) >= 3


def test_pred_early_stop_binary():
    rng = np.random.RandomState(0)
    X = rng.randn(800, 5)
    y = (X[:, 0] > 0).astype(float)          # easily separable
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=40)
    full = b.predict(X, raw_score=True)
    b._booster.config.pred_early_stop = True
    b._booster.config.pred_early_stop_freq = 5
    b._booster.config.pred_early_stop_margin = 2.0
    es = b.predict(X, raw_score=True)
    # confident rows froze early: their |score| is capped near the margin
    changed = np.abs(es) < np.abs(full)
    assert changed.any()
    # decisions unchanged for confidently classified rows
    assert ((es > 0) == (full > 0))[np.abs(full) > 2.5].all()
    # with an infinite margin the result is identical
    b._booster.config.pred_early_stop_margin = 1e30
    np.testing.assert_allclose(b.predict(X, raw_score=True), full, rtol=1e-6)


def test_forced_bins(tmp_path):
    import json
    fb = str(tmp_path / "forced.json")
    with open(fb, "w") as f:
        json.dump([{"feature": 0, "bin_upper_bound": [0.3, 0.35, 0.4]}], f)
    rng = np.random.RandomState(1)
    X = rng.rand(1000, 3)
    y = (X[:, 0] > 0.35).astype(float) + 0.01 * rng.randn(1000)
    from lambdagap_tpu.config import Config
    from lambdagap_tpu.data.dataset import BinnedDataset
    cfg = Config.from_params({"max_bin": 16, "forcedbins_filename": fb})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    bounds = ds.mappers[0].bin_upper_bound
    for b in (0.3, 0.35, 0.4):
        assert any(abs(x - b) < 1e-9 for x in bounds), (b, bounds)


def test_linear_tree():
    rng = np.random.RandomState(8)
    X = rng.rand(1500, 4) * 4
    y = 2.0 * X[:, 0] - 1.5 * X[:, 1] + np.where(X[:, 2] > 2, 3.0, 0.0) \
        + 0.05 * rng.randn(1500)
    base = {"objective": "regression", "num_leaves": 4, "learning_rate": 0.5,
            "min_data_in_leaf": 20, "verbose": -1}
    b_const = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=5)
    b_lin = lgb.train({**base, "linear_tree": True, "linear_lambda": 1e-4},
                      lgb.Dataset(X, label=y), num_boost_round=5)
    rmse_c = float(np.sqrt(np.mean((b_const.predict(X) - y) ** 2)))
    rmse_l = float(np.sqrt(np.mean((b_lin.predict(X) - y) ** 2)))
    # piecewise-linear target: linear leaves should crush constant leaves
    assert rmse_l < 0.5 * rmse_c, (rmse_l, rmse_c)
    # text round trip preserves linear payloads
    b2 = lgb.Booster(model_str=b_lin.model_to_string())
    np.testing.assert_allclose(b2.predict(X), b_lin.predict(X),
                               rtol=1e-6, atol=1e-7)
    # NaN rows fall back to the constant leaf value (finite predictions)
    Xn = X.copy()
    Xn[:10, 0] = np.nan
    assert np.isfinite(b_lin.predict(Xn)).all()


def test_linear_tree_with_valid_set():
    rng = np.random.RandomState(9)
    X = rng.rand(800, 3)
    y = 3 * X[:, 0] + X[:, 1]
    dtrain = lgb.Dataset(X[:600], label=y[:600])
    dvalid = lgb.Dataset(X[600:], label=y[600:], reference=dtrain)
    rec = {}
    lgb.train({"objective": "regression", "num_leaves": 4, "verbose": -1,
               "linear_tree": True, "metric": "l2"},
              dtrain, num_boost_round=8, valid_sets=[dvalid],
              callbacks=[lgb.record_evaluation(rec)])
    vals = rec["valid_0"]["l2"]
    assert vals[-1] < vals[0] * 0.5


def test_linear_tree_resume_refit_contrib_guards():
    """ADVICE r2 + ISSUE 11: continued training replays the linear path,
    refit drops linear payloads, pred_contrib attributes linear leaves via
    the coefficient split (rows sum to the raw prediction)."""
    rng = np.random.RandomState(11)
    X = rng.rand(900, 4) * 4
    y = 2.0 * X[:, 0] - 1.5 * X[:, 1] + 0.05 * rng.randn(900)
    params = {"objective": "regression", "num_leaves": 4, "verbose": -1,
              "learning_rate": 0.3, "linear_tree": True,
              "linear_lambda": 1e-4, "min_data_in_leaf": 20}
    b10 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    b5 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    resumed = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5,
                        init_model=b5)
    # a wrong (constant-leaf) replay would leave later gradients computed
    # against wrong scores and visibly diverge from straight training
    np.testing.assert_allclose(resumed.predict(X), b10.predict(X),
                               rtol=1e-3, atol=1e-4)

    # pred_contrib over linear trees: the coefficient-attribution split
    # keeps the TreeSHAP sum invariant (ISSUE 11 tentpole)
    phi = b10.predict(X, pred_contrib=True)
    assert phi.shape == (len(X), X.shape[1] + 1)
    np.testing.assert_allclose(phi.sum(axis=1),
                               b10.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-5)

    # refit drops the linear payload so refitted constants drive predictions
    b_ref = b10.refit(X, y)
    assert np.isfinite(b_ref.predict(X)).all()
    assert not any(getattr(t, "is_linear", False)
                   for t in b_ref._booster.host_models)

    # valid sets added after resume must replay the linear path too
    # (add_valid_set runs AFTER resume_from in engine.py)
    Xv, yv = X[:200] + 0.1, y[:200]
    dtrain = lgb.Dataset(X, label=y)
    dvalid = lgb.Dataset(Xv, label=yv, reference=dtrain)
    rb = lgb.train(params, dtrain, num_boost_round=2, init_model=b5,
                   valid_sets=[dvalid])
    replayed = np.asarray(rb._booster.valid_scores[0][0])
    np.testing.assert_allclose(replayed, rb.predict(Xv, raw_score=True),
                               rtol=1e-4, atol=1e-4)


def test_forced_bins_zero_bounds(tmp_path):
    """Zero rows must never share a bin with nonzero values under forced
    bins (reference: bin.cpp:178-198 FindBinWithPredefinedBin inserts the
    +-kZeroThreshold bounds before any forced bound)."""
    import json
    fb = str(tmp_path / "forced.json")
    with open(fb, "w") as f:
        json.dump([{"feature": 0, "bin_upper_bound": [-0.5, 0.5]}], f)
    rng = np.random.RandomState(2)
    col = rng.randn(2000)
    col[::4] = 0.0                       # 25% exact zeros
    X = np.column_stack([col, rng.rand(2000)])
    y = rng.rand(2000)
    from lambdagap_tpu.config import Config
    from lambdagap_tpu.data.dataset import BinnedDataset
    cfg = Config.from_params({"max_bin": 16, "forcedbins_filename": fb})
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    m = ds.mappers[0]
    zero_bin = m.values_to_bins(np.asarray([0.0]))[0]
    neg = m.values_to_bins(col[np.abs(col) > 1e-6])
    assert zero_bin not in set(neg.tolist())
    for b in (-0.5, 0.5):
        assert any(abs(x - b) < 1e-9 for x in m.bin_upper_bound)


def test_interaction_constraints_fused():
    """The fused program enforces interaction sets in-program via per-leaf
    path bitmasks (no host-learner fallback)."""
    from lambdagap_tpu.models.fused_learner import FusedTreeLearner
    X, y = _data()
    groups = [frozenset([0, 1]), frozenset([2, 3, 4, 5])]
    b = lgb.train({**BASE, "interaction_constraints": [[0, 1], [2, 3, 4, 5]],
                   "tpu_fused_learner": "1"},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    assert isinstance(b._booster.learner, FusedTreeLearner)
    for t in b._booster.host_models:
        def walk(node, path):
            if node < 0:
                if path:
                    assert any(path <= g for g in groups), path
                return
            p2 = path | {t.split_feature[node]}
            walk(t.left_child[node], p2)
            walk(t.right_child[node], p2)
        if t.num_internal:
            walk(0, frozenset())
    # features outside every group are never used
    assert _used_features(b) <= {0, 1, 2, 3, 4, 5}


def test_feature_fraction_bynode_fused():
    from lambdagap_tpu.models.fused_learner import FusedTreeLearner
    X, y = _data()
    b = lgb.train({**BASE, "feature_fraction_bynode": 0.5,
                   "tpu_fused_learner": "1"},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    assert isinstance(b._booster.learner, FusedTreeLearner)
    resid = y - b.predict(X)
    assert np.var(resid) < 0.5 * np.var(y)
    assert len(_used_features(b)) >= 3
    # seeded: reproducible
    b2 = lgb.train({**BASE, "feature_fraction_bynode": 0.5,
                    "tpu_fused_learner": "1"},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    assert b2.model_to_string() == b.model_to_string()
