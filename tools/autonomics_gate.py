#!/usr/bin/env python
"""Autonomics gate (ISSUE 13): the control loop proven under faults.

Run by tools/run_full_suite.sh G0. Three scenarios, one per actuation
behavior the controller ships:

A. **kill-and-revive under open-loop load** — a 2-replica loopback fleet
   of REAL ``task=serve`` subprocesses; replica r0 is SIGKILLed mid-load.
   The controller must respawn it (same fixed port — the
   SO_REUSEADDR/rebind path), re-admit it at probation, and promote it;
   every accepted request resolves (zero stranded futures) and fleet
   goodput re-converges to >= 90% of the pre-kill baseline.
B. **placement under induced eviction pressure** — 3 models on 2
   replicas under an HBM budget that fits ~1 model per replica. The
   placement loop must pin the hot model to a resident replica and route
   its traffic there: during the measured window the cold models churn
   (evictions > 0) while the hot model pays ~zero readmissions.
C. **delta hot-swap during scale-out** — the autoscaler grows the fleet
   (scripted knee signals), then a delta rollout must land atomically on
   EVERY live replica (including the fresh one); with a delta fault
   armed on one replica, the rollout must roll back on all of them — no
   mixed-generation fleet. Delta frames must be smaller than the full
   model text.

Exit 0 on pass; nonzero with a reason on any violation.
"""
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RATE_RPS = 120.0
N_REQUESTS = 240
DEADLINE_MS = 250.0
RECOVERY_FRACTION = 0.90


def fail(msg: str) -> int:
    print(f"AUTONOMICS GATE FAIL: {msg}")
    return 1


def train_model(path: str, seed: int = 0, rounds: int = 10):
    import numpy as np
    import lambdagap_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(1500, 10).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + np.sin(X[:, 2]) > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "tpu_fast_predict_rows": 0},
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    b.save_model(path)
    return X


def spawn_replica(model_path: str, port: int = 0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "lambdagap_tpu", "task=serve",
         f"input_model={model_path}", f"serve_port={port}", "verbose=-1",
         "serve_max_delay_ms=1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO, env=env)


def await_port(proc, timeout_s: float = 120.0) -> int:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("SERVE_PORT="):
            return int(line.split("=", 1)[1])
    raise RuntimeError("replica never printed SERVE_PORT")


# ---------------------------------------------------------------------------
def scenario_a_kill_and_revive(tmp: str) -> int:
    from lambdagap_tpu.obs.fleet import FleetScraper
    from lambdagap_tpu.obs.signals import SignalPlane
    from lambdagap_tpu.serve import (Autonomics, RemoteReplica, Router,
                                     run_open_loop)

    model = os.path.join(tmp, "model_a.txt")
    X = train_model(model)
    print("autonomics gate [A]: spawning 2 task=serve replicas...")
    procs = {}
    procs["r0"] = spawn_replica(model)
    procs["r1"] = spawn_replica(model)
    ports = {name: await_port(p) for name, p in procs.items()}
    print(f"autonomics gate [A]: fleet up on ports {ports}")
    router = Router([RemoteReplica(name, "127.0.0.1", port)
                     for name, port in sorted(ports.items())])
    plane = SignalPlane()
    scraper = FleetScraper(router, interval_s=0.25, signals=plane).start()
    router.attach_scraper(scraper)

    def revive(name, old):
        # respawn the dead subprocess on its OLD fixed port (the
        # SO_REUSEADDR + bind-retry path), then reconnect the client
        proc = procs[name]
        if proc.poll() is None:
            raise ConnectionError(f"{name} process still running")
        fresh = spawn_replica(model, port=old.port)
        procs[name] = fresh
        port = await_port(fresh)
        if port != old.port:
            raise RuntimeError(
                f"respawned replica re-announced port {port}, expected "
                f"to rebind {old.port}")
        return RemoteReplica(name, "127.0.0.1", port)

    auto = Autonomics(router, signals=plane, scraper=scraper,
                      interval_s=0.25, revive=revive,
                      revive_backoff_s=0.25, probe_window=2).start()
    router.attach_autonomics(auto)
    try:
        pre = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                            deadline_ms=DEADLINE_MS, seed=1)
        print(f"autonomics gate [A]: pre-fault goodput ratio "
              f"{pre['goodput_ratio']:.2f}, counts {pre['counts']}")
        if pre["counts"]["error"]:
            return fail("[A] pre-fault round had unexplained errors")
        if pre["goodput_ratio"] < 0.5:
            return fail("[A] fleet cannot carry the gate load; baseline "
                        "meaningless")

        def killer():
            time.sleep(N_REQUESTS / RATE_RPS * 0.4)
            print("autonomics gate [A]: SIGKILL replica r0 mid-load")
            procs["r0"].send_signal(signal.SIGKILL)

        k = threading.Thread(target=killer)
        k.start()
        chaos = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                              deadline_ms=DEADLINE_MS, seed=2)
        k.join()
        c = chaos["counts"]
        resolved = (c["ok"] + c["rejected"] + c["timeout"]
                    + c["transport"] + c["error"])
        print(f"autonomics gate [A]: chaos counts {c}")
        if resolved != N_REQUESTS:
            return fail(f"[A] {N_REQUESTS - resolved} requests never "
                        "resolved — a stranded future")
        if c["error"]:
            return fail(f"[A] {c['error']} unexplained errors in the "
                        "chaos round")

        # the controller must revive r0: same name, same port, probation
        # then promotion — wait for the full cycle, not just the respawn
        deadline = time.time() + 150.0
        while time.time() < deadline:
            snap = router.snapshot()
            info = snap["replicas"]["r0"]
            if not info["dead"] and "probation" not in info \
                    and auto.counters["revivals"] >= 1:
                break
            time.sleep(0.25)
        else:
            return fail(f"[A] r0 never revived+promoted: {snap['replicas']}"
                        f" autonomics={auto.snapshot()}")
        print(f"autonomics gate [A]: r0 revived on port {ports['r0']} "
              f"after {auto.counters['revival_failures']} failed "
              f"attempt(s); promoted from probation")

        post = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                             deadline_ms=DEADLINE_MS, seed=3)
        print(f"autonomics gate [A]: post-revival goodput ratio "
              f"{post['goodput_ratio']:.2f} vs pre "
              f"{pre['goodput_ratio']:.2f}")
        if post["counts"]["error"]:
            return fail("[A] post-revival round had unexplained errors")
        if post["goodput_ratio"] < RECOVERY_FRACTION * pre["goodput_ratio"]:
            return fail(f"[A] goodput did not re-converge: "
                        f"{post['goodput_ratio']:.2f} < "
                        f"{RECOVERY_FRACTION:.0%} of "
                        f"{pre['goodput_ratio']:.2f}")
        # the revived replica must actually be BACK IN ROTATION
        if router.snapshot()["replicas"]["r0"]["routed"] == 0:
            return fail("[A] revived r0 never took a request")
        print("autonomics gate [A]: PASS")
        return 0
    finally:
        router.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()


# ---------------------------------------------------------------------------
def scenario_b_placement(tmp: str) -> int:
    import numpy as np
    import lambdagap_tpu as lgb
    from lambdagap_tpu.obs.fleet import FleetScraper
    from lambdagap_tpu.obs.signals import SignalPlane
    from lambdagap_tpu.serve import (Autonomics, ForestServer,
                                     LocalReplica, Router)

    paths = {}
    for i, name in enumerate(("hot", "cold1", "cold2")):
        paths[name] = os.path.join(tmp, f"model_{name}.txt")
        X = train_model(paths[name], seed=i, rounds=8)

    def make_server(budget):
        s = ForestServer(lgb.Booster(model_file=paths["hot"]),
                         max_delay_ms=1.0, hbm_budget_bytes=budget)
        # the default entry rides along but sees no traffic
        for name in ("hot", "cold1", "cold2"):
            s.add_model(name, paths[name])
        return s

    probe = ForestServer(lgb.Booster(model_file=paths["hot"]),
                         max_delay_ms=1.0)
    one_model = probe.registry.entry("default").bytes
    probe.close()
    budget = int(one_model * 1.5)        # fits ONE model (+ slack), not two
    s0, s1 = make_server(budget), make_server(budget)
    router = Router([LocalReplica("r0", s0), LocalReplica("r1", s1)],
                    own_replicas=True)
    plane = SignalPlane()
    scraper = FleetScraper(router, signals=plane)   # on-demand scrapes
    auto = Autonomics(router, signals=plane, scraper=scraper,
                      placement=True, placement_budget_bytes=budget)
    router.attach_autonomics(auto)
    try:
        rng = np.random.RandomState(3)
        row = X[:1]

        def drive(n, models):
            futs = [router.submit(row, model=models[i % len(models)])
                    for i in range(n)]
            for f in futs:
                f.result(30)

        # traffic history: hot dominates -> the plan pins it
        drive(60, ["hot"])
        drive(12, ["cold1", "cold2"])
        scraper.scrape()
        auto.tick()
        plan = router.snapshot().get("placement")
        if not plan or "hot" not in plan or len(plan["hot"]) != 1:
            return fail(f"[B] no placement plan for the hot model: {plan}")
        hot_home = plan["hot"][0]
        print(f"autonomics gate [B]: plan {plan} (hot -> {hot_home}, "
              f"budget {budget} bytes ~ 1 model/replica)")

        def hot_readmissions():
            stats = router.stats_snapshot()
            return sum((s.get("per_model", {}).get("hot", {})
                        .get("readmissions", 0))
                       for s in stats["replicas"].values()
                       if isinstance(s, dict))

        def total_evictions():
            stats = router.stats_snapshot()
            return sum(s.get("evictions", 0)
                       for s in stats["replicas"].values()
                       if isinstance(s, dict))

        base_readmit = hot_readmissions()
        base_evict = total_evictions()
        # measured window: hot traffic + cold churn (the two cold models
        # alternate on the other replica, evicting each other under the
        # one-model budget — real, measured eviction pressure)
        for _ in range(6):
            drive(20, ["hot"])
            drive(8, ["cold1", "cold2"])
            scraper.scrape()
            auto.tick()
        d_readmit = hot_readmissions() - base_readmit
        d_evict = total_evictions() - base_evict
        print(f"autonomics gate [B]: measured window: hot readmissions "
              f"+{d_readmit}, fleet evictions +{d_evict}")
        if d_evict == 0:
            return fail("[B] no eviction pressure induced — the budget "
                        "did not bind; the scenario proves nothing")
        if d_readmit > 1:
            return fail(f"[B] hot model paid {d_readmit} readmissions "
                        "under placement — requests are not staying on "
                        "the resident replica")
        print("autonomics gate [B]: PASS")
        return 0
    finally:
        router.close()


# ---------------------------------------------------------------------------
def scenario_c_delta_during_scaleout(tmp: str) -> int:
    import lambdagap_tpu as lgb
    from lambdagap_tpu.guard.degrade import SwapFailed
    from lambdagap_tpu.guard.faults import FaultPlan
    from lambdagap_tpu.obs.signals import SignalPlane
    from lambdagap_tpu.serve import (Autonomics, ForestServer,
                                     LocalReplica, Router)
    from lambdagap_tpu.serve.delta import split_model_text

    v1 = os.path.join(tmp, "model_c1.txt")
    X = train_model(v1, seed=9, rounds=8)
    import numpy as np
    y = (X[:, 0] - 0.5 * X[:, 1] + np.sin(X[:, 2]) > 0).astype(np.float32)
    b2 = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                   lgb.Dataset(X, label=y), num_boost_round=4,
                   init_model=v1)
    v2 = os.path.join(tmp, "model_c2.txt")
    b2.save_model(v2)
    b3 = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1},
                   lgb.Dataset(X, label=y), num_boost_round=2,
                   init_model=v2)
    v3 = os.path.join(tmp, "model_c3.txt")
    b3.save_model(v3)

    def mk(name):
        return LocalReplica(name, ForestServer(
            lgb.Booster(model_file=v1), max_delay_ms=1.0))

    router = Router([mk("r0"), mk("r1")], own_replicas=True)
    plane = SignalPlane(alpha=1.0)
    # scripted saturation: offered hugs the knee -> margin ~0
    plane.knee.knee_rps = 100.0
    plane.knee.offered_rps = 99.0
    plane.knee.ticks = 5
    plane.update({"merged": {}, "time_unix": 1.0})
    plane.knee.knee_rps = 100.0
    plane.knee.offered_rps = 99.0
    plane._latest["goodput"] = plane.knee.snapshot()

    auto = Autonomics(router, signals=plane, scale=lambda i: mk(f"s{i}"),
                      scale_out_margin=0.1, scale_in_margin=0.5,
                      max_replicas=3, hysteresis_ticks=1, cooldown_s=0.0)
    router.attach_autonomics(auto)
    try:
        auto.tick()
        live = sorted(router.replica_names())
        if live != ["r0", "r1", "s0"]:
            return fail(f"[C] autoscaler did not scale out: {live}")
        print(f"autonomics gate [C]: scaled out to {live} at "
              "knee_margin ~0.01")

        out = auto.rollout_delta(v2, base_source=v1)
        if out["mode"] != "delta":
            return fail(f"[C] rollout fell back to {out['mode']}")
        if out["delta_bytes"] >= out["full_bytes"]:
            return fail(f"[C] delta frame ({out['delta_bytes']}B) is not "
                        f"smaller than the full text "
                        f"({out['full_bytes']}B)")
        forests = {tuple(split_model_text(
            router.replica(n).server.model_text())[1]) for n in live}
        want = {tuple(split_model_text(open(v2).read())[1])}
        if forests != want:
            return fail("[C] delta rollout did not land the SAME forest "
                        "on every live replica (fresh scale-out replica "
                        "included)")
        print(f"autonomics gate [C]: delta rollout landed on all 3 "
              f"replicas ({out['delta_bytes']}B delta vs "
              f"{out['full_bytes']}B full)")

        # rollout with one replica armed to fail: all-or-nothing
        router.replica("r1").server._faults = FaultPlan("delta_swap_fail=1")
        try:
            auto.rollout_delta(v3)
            return fail("[C] rollout with an armed fault did not raise")
        except SwapFailed as e:
            print(f"autonomics gate [C]: faulted rollout rolled back "
                  f"({e})")
        forests = {tuple(split_model_text(
            router.replica(n).server.model_text())[1])
            for n in sorted(router.replica_names())}
        if len(forests) != 1:
            return fail("[C] MIXED-GENERATION FLEET after failed rollout")
        if forests != want:
            return fail("[C] fleet is uniform but not on the base "
                        "generation after rollback")
        if auto.counters["delta_rollbacks"] != 1:
            return fail("[C] rollback not recorded")
        print("autonomics gate [C]: PASS")
        return 0
    finally:
        router.close()


def main() -> int:
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        for scenario in (scenario_a_kill_and_revive, scenario_b_placement,
                         scenario_c_delta_during_scaleout):
            rc = scenario(tmp)
            if rc:
                return rc
    print("autonomics gate: PASS — revival under load, placement under "
          "eviction pressure, atomic delta rollout during scale-out")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
