#!/usr/bin/env python
"""Batch-scoring gate for tools/run_full_suite.sh (ISSUE 18 CI satellite).

Trains a tiny synthetic booster, shards the scoring matrix into 4 ragged
host windows, and asserts the predict_stream contract end to end:

1. streamed scores are BIT-IDENTICAL (``array_equal``) to the resident
   ``predict_raw`` on the COMPILED engine (the warehouse path the driver
   exists for), ragged tail included;
2. the pumped pass is compile-free inside the window records — pow2
   bucket pre-warm happens before the pump opens, so a compile under a
   window record is a steady-state compile and fails the gate;
3. the ``d2h_scores`` phase (the score ring's async D2H + completion
   residual) actually appears next to ``h2d_prefetch``/``chunk_wait`` in
   the run report — BOTH directions of the overlap are measured, not
   hoped;
4. the co-tenant throttle engages under a scripted serve-goodput knee
   (window issue backs off with growing bounded delays) and recovers
   the moment pressure clears — while the scores stay bit-identical.

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N = 6000
WINDOW = 1700          # 4 ragged windows: 1700 x 3 + 900 tail
ROUNDS = 8


def main() -> int:
    import numpy as np

    import lambdagap_tpu as lgb
    from lambdagap_tpu.guard.backoff import Backoff
    from lambdagap_tpu.infer.stream import CoTenantThrottle

    rng = np.random.RandomState(0)
    X = rng.randn(N, 10).astype(np.float32)
    X[rng.rand(N, 10) < 0.03] = np.nan      # missing values ride along
    y = (np.nan_to_num(X[:, 0]) - 0.4 * np.nan_to_num(X[:, 1])
         + 0.2 * rng.randn(N) > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 20, "tpu_fast_predict_rows": 0,
              "predict_engine": "compiled"}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=ROUNDS)
    gb = bst._booster

    assert N % WINDOW != 0 and -(-N // WINDOW) == 4
    ref = gb.predict_raw(X)
    stats = {}
    got = gb.predict_stream(X, raw_score=True, window_rows=WINDOW,
                            stats_out=stats)
    if not np.array_equal(ref, got):
        print("batch gate: streamed scores are NOT bit-identical to "
              "resident predict_raw on the compiled engine",
              file=sys.stderr)
        return 1

    steady = [(r.get("iter"), r["compiles"]["steady"])
              for r in stats["records"]
              if r.get("type") == "iteration"
              and (r.get("compiles") or {}).get("steady", 0)]
    if steady:
        print(f"batch gate: steady-state compiles inside the pumped "
              f"pass: {steady}", file=sys.stderr)
        return 1

    phases = set(stats["phases"])
    missing = {"h2d_prefetch", "d2h_scores"} - phases
    if missing:
        print(f"batch gate: transfer phases {sorted(missing)} never "
              "appeared in the run report — an overlap direction is "
              "unmeasured", file=sys.stderr)
        return 1

    # scripted serve pressure: 3 checks at the knee, then clear skies
    def _sig(margin):
        return {"goodput": {"knee_rps": 200.0, "knee_margin": margin,
                            "good_fraction": 0.99, "good_ratio": 0.9}}

    sigs = iter([_sig(0.02)] * 3 + [_sig(0.6)] * 100)
    slept = []
    th = CoTenantThrottle(
        lambda: next(sigs),
        backoff=Backoff(base_s=0.01, factor=2.0, max_s=0.1, jitter=0.0,
                        seed=7),
        sleep=slept.append)
    got2 = gb.predict_stream(X, raw_score=True, window_rows=WINDOW,
                             throttle=th)
    if not np.array_equal(ref, got2):
        print("batch gate: throttled scores diverged from resident",
              file=sys.stderr)
        return 1
    if th.waits != 3 or slept != [0.01, 0.02, 0.04]:
        print(f"batch gate: throttle did not back off as scripted "
              f"(waits={th.waits}, delays={slept})", file=sys.stderr)
        return 1
    if th.engaged:
        print("batch gate: throttle failed to recover after the knee "
              "cleared", file=sys.stderr)
        return 1

    print(f"batch gate: OK — {stats['windows']} ragged windows "
          f"(buckets {stats['buckets']}) bit-identical to resident on "
          f"the compiled engine, zero steady compiles, d2h_scores live "
          f"(h2d {stats['phases'].get('h2d_prefetch', 0.0):.4f}s / d2h "
          f"{stats['phases'].get('d2h_scores', 0.0):.4f}s), throttle "
          f"backed off {th.waits}x and recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
