"""Relative per-iter wall: serial fused vs fused data-parallel on the
virtual 8-CPU mesh (VERDICT r2 item 1 done-criterion: within ~1.5x).

Run: python tools/bench_fused_dp.py [rows] [iters]
"""
import os
import sys
import time

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import lambdagap_tpu as lgb  # noqa: E402


def run(tl_params, X, y, iters):
    params = {"objective": "binary", "verbose": -1, "num_leaves": 31,
              "min_data_in_leaf": 20, **tl_params}
    ds = lgb.Dataset(X, label=y)
    # warmup: 2 rounds (compile)
    booster = lgb.Booster(params=params, train_set=ds)
    for _ in range(2):
        booster.update()
    t0 = time.perf_counter()
    for _ in range(iters):
        booster.update()
    # force everything: predictions fold all trees
    float(np.sum(booster.predict(X[:256], raw_score=True)))
    dt = (time.perf_counter() - t0) / iters
    return dt


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    rng = np.random.RandomState(0)
    X = rng.randn(rows, 20).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(rows) > 0)
    t_serial = run({"tpu_fused_learner": "1"}, X, y, iters)
    t_fdp = run({"tree_learner": "data", "tpu_num_devices": 8}, X, y, iters)
    print(f"rows={rows} serial_fused={t_serial*1e3:.1f}ms/iter "
          f"fused_dp8={t_fdp*1e3:.1f}ms/iter ratio={t_fdp/t_serial:.2f}")


if __name__ == "__main__":
    main()
