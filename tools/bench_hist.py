"""Microbenchmark: histogram strategies on TPU.

Races the XLA one-hot contraction (ops.histogram.histogram_from_rows)
against the Pallas VMEM kernel (ops.hist_pallas.hist_pallas) across
(rows, bins) shapes — the TPU analog of TrainingShareStates timing col-wise
vs row-wise on the first iterations (reference: src/io/train_share_states.cpp).

Timing note: on the axon remote-TPU tunnel, block_until_ready does not
reliably force execution of unconsumed results — every timed call's output
is folded into an accumulator that is read back at the end.

Usage: python tools/bench_hist.py [P ...]
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from lambdagap_tpu.ops.histogram import histogram_from_rows  # noqa: E402
from lambdagap_tpu.ops.hist_pallas import hist_pallas, pack_gh8  # noqa: E402

NVAR = 4  # distinct inputs cycled to defeat any cross-call caching


def timeit(fn, variants, reps=12):
    acc = jnp.zeros((), jnp.float32) + jnp.sum(fn(*variants[0]))
    float(acc)  # warmup + compile
    acc = jnp.zeros((), jnp.float32)
    t0 = time.perf_counter()
    for i in range(reps):
        acc = acc + jnp.sum(fn(*variants[i % NVAR]))
    force = float(acc)
    return (time.perf_counter() - t0) / reps, force


def main():
    sizes = [int(a) for a in sys.argv[1:]] or [16384, 65536, 262144]
    F = 28
    rng = np.random.RandomState(0)
    for B in (64, 256):
        for P in sizes:
            vx, vp, vs = [], [], []
            for _ in range(NVAR):
                bins = jnp.asarray(rng.randint(0, B, (P, F), dtype=np.uint8))
                grad = jnp.asarray(rng.randn(P).astype(np.float32))
                hess = jnp.asarray(np.abs(rng.randn(P)).astype(np.float32))
                valid = jnp.ones(P, dtype=bool)
                gh8 = pack_gh8(grad, hess, valid)
                vx.append((bins, grad, hess, valid))
                vp.append((bins, gh8))
                vs.append((bins, gh8))

            t_x, _ = timeit(lambda b, g, h, v: histogram_from_rows(
                b, g, h, v, B, 4096, "split"), vx)
            t_p, _ = timeit(lambda b, g: hist_pallas(b, g, B), vp)
            cnt = jnp.int32(2048)
            t_s, _ = timeit(lambda b, g: hist_pallas(b, g, B, cnt), vs)
            h_x = histogram_from_rows(*vx[0], B, 4096, "split")
            h_p = hist_pallas(*vp[0], B)
            err = float(jnp.max(jnp.abs(h_x - h_p)) /
                        (1e-6 + float(jnp.max(jnp.abs(h_x)))))
            print(f"B={B:3d} P={P:7d}: onehot {t_x*1e3:8.3f} ms  "
                  f"pallas {t_p*1e3:8.3f} ms  speedup {t_x/t_p:5.2f}x  "
                  f"gated@2k {t_s*1e3:7.3f} ms  rel_err {err:.2e}",
                  flush=True)


if __name__ == "__main__":
    main()
