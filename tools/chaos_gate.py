#!/usr/bin/env python
"""Chaos gate for tools/run_full_suite.sh (ISSUE 5 CI satellite).

Two short fault-injection scenarios that must hold before anything ships
(docs/robustness.md):

1. **Training under gradient NaNs** — a short train with
   ``nonfinite_grad`` injected and ``guard_nonfinite=skip_tree`` must
   finish, drop exactly the poisoned iterations, and save a loadable model
   whose predictions are finite.
2. **Serving under dispatch failures** — a ForestServer with the first K
   dispatches failing must shed those requests with errors (no hangs),
   report DEGRADED while failing, then recover to OK and keep serving the
   same bits.

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> int:
    print(f"chaos gate: {msg}", file=sys.stderr)
    return 1


def train_under_nan_gradients() -> int:
    import numpy as np

    import lambdagap_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(1200, 10).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 2] + 0.2 * rng.randn(1200)).astype(np.float32)
    out = os.path.join(tempfile.mkdtemp(prefix="lambdagap_chaos_"),
                       "model.txt")
    rounds = 8
    b = lgb.train({"objective": "regression", "verbose": -1,
                   "guard_nonfinite": "skip_tree",
                   "guard_faults": "nonfinite_grad=2:3",
                   "output_model": out},
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    if b.num_trees() != rounds - 2:
        return fail(f"skip_tree kept {b.num_trees()} trees, expected "
                    f"{rounds - 2} (2 poisoned iterations dropped)")
    b.save_model(out)
    loaded = lgb.Booster(model_file=out)
    preds = loaded.predict(X[:256])
    if not np.all(np.isfinite(preds)):
        return fail("saved model predicts non-finite values")
    print(f"chaos gate: train under NaN gradients OK "
          f"({b.num_trees()}/{rounds} trees kept, model valid)")
    return 0


def serve_under_dispatch_failures() -> int:
    import numpy as np

    import lambdagap_tpu as lgb

    rng = np.random.RandomState(1)
    X = rng.randn(900, 8).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    FAIL_N = 3
    b = lgb.train({"objective": "binary", "verbose": -1,
                   "tpu_fast_predict_rows": 0,
                   "guard_faults": f"serve_dispatch_fail={FAIL_N}"},
                  lgb.Dataset(X, label=y), num_boost_round=6)
    ref = b.predict(X[:600])
    server = b.as_server(buckets=(1, 8), max_delay_ms=0.0, workers=1)
    try:
        shed = 0
        for i in range(FAIL_N):
            fut = server.submit(X[i])
            try:
                fut.result(timeout=30)
            except Exception:
                shed += 1
        if shed != FAIL_N:
            return fail(f"{FAIL_N} injected dispatch failures but only "
                        f"{shed} requests resolved with errors")
        state = server.health.state()
        if state != "degraded":
            return fail(f"health is {state!r} mid-failure, want 'degraded'")
        # faults exhausted: the server must recover, not die
        for i in range(8):
            got = server.submit(X[i]).result(timeout=30)
            if not np.array_equal(got.values, ref[i:i + 1]):
                return fail(f"post-recovery response for row {i} does not "
                            "match the device predict reference")
        state = server.health.state()
        if state != "ok":
            return fail(f"health is {state!r} after recovery, want 'ok'")
        snap = server.stats_snapshot()
        if snap["errors"] < FAIL_N:
            return fail(f"errors counter {snap['errors']} < {FAIL_N}")
    finally:
        server.close()
    if server.health.state() != "draining":
        return fail("health must report 'draining' after close()")
    print(f"chaos gate: serve under dispatch failures OK "
          f"({FAIL_N} shed with errors, DEGRADED -> OK -> DRAINING)")
    return 0


def main() -> int:
    rc = train_under_nan_gradients()
    if rc:
        return rc
    return serve_under_dispatch_failures()


if __name__ == "__main__":
    raise SystemExit(main())
