#!/usr/bin/env python
"""Cost-plane gate for tools/run_full_suite.sh (ISSUE 19 CI satellite).

Runs the cost-plane scenario matrix in one process — the three learners
(serial, fused, fused-2D on an 8-way virtual mesh) and the three predict
engines (scan, tensor, compiled) plus ``predict_stream`` and SHAP — into
one analytic ledger (``lambdagap_tpu.obs.costplane``), then diffs the
ledger's per-program maxima against the checked-in budget
(``tools/cost_budget.json``):

- any ``steady`` budget program missing from the ledger fails (a capture
  site silently unwired is exactly the regression this catches);
- on a matching backend, a ``hot`` program growing its analytic
  bytes-accessed past the budget tolerance (default +10%) or its peak
  HBM at all fails — XLA's analytic counts are deterministic, so any
  growth is a real program change, not noise;
- on a foreign backend the byte/HBM diffs are skipped (the analytic
  counts are backend-shaped) but the presence inventory still gates.

A self-test perturbs a hot program's bytes by +20% in memory and asserts
the check fails, so the gate cannot rot into a tautology.

Modes: default (scenarios -> check -> selftest), ``--emit PATH`` (also
persist the ledger, e.g. the repo COSTS.json artifact), ``--seed-budget``
(rewrite tools/cost_budget.json from this run), ``--selftest`` (skip the
scenario run; needs an existing ledger via ``--ledger``).

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# 8 virtual CPU devices BEFORE jax import: the fused-2D scenario lowers
# on a real 4x2 mesh, so its ledger entry carries the sharded shapes
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

BUDGET_PATH = os.path.join(REPO, "tools", "cost_budget.json")
ROUNDS = 4


def run_scenarios():
    """Train every learner and score through every engine with the plane
    armed; returns the populated module PLANE."""
    import numpy as np

    import lambdagap_tpu as lgb
    from lambdagap_tpu.obs.costplane import PLANE

    PLANE.reset()
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 16).astype(np.float32)
    y = (X[:, 0] - 0.4 * X[:, 1] + 0.2 * rng.randn(2000) > 0
         ).astype(np.float32)
    ds = lambda: lgb.Dataset(X, label=y)  # noqa: E731
    Xp = rng.randn(1536, 16).astype(np.float32)
    base = {"objective": "binary", "num_leaves": 15, "verbose": -1,
            "cost_plane": True, "telemetry": True,
            "tpu_fast_predict_rows": 0}   # rows > threshold: device path

    # one learner + one predict engine per training; each train
    # re-configures the plane with cost_plane on, so the ledger accumulates
    serial = lgb.train({**base, "tpu_fused_learner": "0",
                        "predict_engine": "scan"},
                       ds(), num_boost_round=ROUNDS)
    serial.predict(Xp)
    fused = lgb.train({**base, "tpu_fused_learner": "1",
                       "predict_engine": "tensor"},
                      ds(), num_boost_round=ROUNDS)
    fused.predict(Xp)
    fused2d = lgb.train({**base, "tpu_fused_learner": "1",
                         "tree_learner": "data", "mesh_shape": "4x2",
                         "predict_engine": "compiled"},
                        ds(), num_boost_round=ROUNDS)
    fused2d.predict(Xp)
    fused.predict_stream(Xp, raw_score=True, window_rows=512)
    serial.predict(Xp[:256], pred_contrib=True)
    return PLANE


def _by_program(doc: dict) -> dict:
    """Per-program maxima over padding buckets from a ledger document
    (mirror of CostPlane.by_program, but over the persisted JSON)."""
    out: dict = {}
    for e in doc.get("entries", {}).values():
        agg = out.setdefault(e["program"], {"bytes_accessed": 0.0,
                                            "peak_hbm_bytes": 0.0})
        agg["bytes_accessed"] = max(agg["bytes_accessed"],
                                    float(e["bytes_accessed"]))
        agg["peak_hbm_bytes"] = max(agg["peak_hbm_bytes"],
                                    float(e["peak_hbm_bytes"]))
    return out


def check(doc: dict, budget: dict) -> list:
    """Diff a ledger document against the budget; returns failure strings
    (empty = pass)."""
    errs = []
    got = _by_program(doc)
    same_backend = doc.get("backend") == budget.get("backend")
    tol = budget.get("tolerance", {})
    tol_bytes = float(tol.get("bytes_accessed_frac", 0.10))
    tol_hbm = float(tol.get("peak_hbm_frac", 0.0))
    for name, b in sorted(budget.get("programs", {}).items()):
        if name not in got:
            if b.get("steady"):
                errs.append(f"steady program {name} missing from the "
                            "ledger (capture site unwired?)")
            continue
        if not (b.get("hot") and same_backend):
            continue
        g = got[name]
        lim = b["bytes_accessed"] * (1.0 + tol_bytes)
        if g["bytes_accessed"] > lim + 1e-9:
            errs.append(
                f"{name}: bytes_accessed {g['bytes_accessed']:.3e} exceeds "
                f"budget {b['bytes_accessed']:.3e} by more than "
                f"{tol_bytes:.0%}")
        lim = b["peak_hbm_bytes"] * (1.0 + tol_hbm)
        if g["peak_hbm_bytes"] > lim + 1e-9:
            errs.append(
                f"{name}: peak HBM {g['peak_hbm_bytes']:.3e} regressed past "
                f"budget {b['peak_hbm_bytes']:.3e}")
    if not same_backend:
        errs = errs or []
        print(f"cost gate: note: ledger backend {doc.get('backend')!r} != "
              f"budget backend {budget.get('backend')!r}; byte/HBM diffs "
              "skipped, presence inventory still gated")
    return errs


def seed_budget(doc: dict, path: str = BUDGET_PATH) -> dict:
    """Budget from a ledger's per-program maxima: device programs are hot
    (byte/HBM gated), everything captured is steady (presence gated)."""
    programs = {}
    for name, agg in sorted(_by_program(doc).items()):
        host = any(e["program"] == name
                   and e.get("memory_source") == "host_analytic"
                   for e in doc["entries"].values())
        programs[name] = {
            "bytes_accessed": agg["bytes_accessed"],
            "peak_hbm_bytes": agg["peak_hbm_bytes"],
            "hot": not host,
            "steady": True,
        }
    budget = {
        "schema_version": doc.get("schema_version", 1),
        "backend": doc.get("backend", "unknown"),
        "tolerance": {"bytes_accessed_frac": 0.10, "peak_hbm_frac": 0.0},
        "programs": programs,
    }
    with open(path, "w") as f:
        json.dump(budget, f, indent=1, sort_keys=True)
        f.write("\n")
    return budget


def selftest(doc: dict, budget: dict) -> list:
    """The gate must pass on its own ledger and fail on an injected +20%
    bytes regression of a hot program."""
    errs = check(doc, budget)
    if errs:
        return [f"selftest: unperturbed ledger failed: {e}" for e in errs]
    hot = [n for n, b in budget["programs"].items()
           if b.get("hot") and n in _by_program(doc)]
    if not hot:
        return ["selftest: no hot budget program present in the ledger"]
    bad = copy.deepcopy(doc)
    victim = sorted(hot)[0]
    for e in bad["entries"].values():
        if e["program"] == victim:
            e["bytes_accessed"] = float(e["bytes_accessed"]) * 1.2
    if not check(bad, budget):
        return [f"selftest: +20% bytes on {victim} was NOT caught — the "
                "gate is a tautology"]
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--emit", metavar="PATH",
                    help="also persist the ledger document to PATH")
    ap.add_argument("--ledger", metavar="PATH",
                    help="check an existing ledger instead of running the "
                         "scenario matrix")
    ap.add_argument("--budget", metavar="PATH", default=BUDGET_PATH,
                    help=f"budget file (default {BUDGET_PATH})")
    ap.add_argument("--seed-budget", action="store_true",
                    help="rewrite the budget from this run's ledger")
    ap.add_argument("--selftest", action="store_true",
                    help="only run the perturbation self-test")
    args = ap.parse_args(argv)

    if args.ledger:
        doc = json.load(open(args.ledger))
    else:
        plane = run_scenarios()
        doc = plane.to_json()
    if args.emit:
        with open(args.emit, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"cost gate: ledger written to {args.emit} "
              f"({len(doc['entries'])} entries)")
    if args.seed_budget:
        budget = seed_budget(doc, args.budget)
        print(f"cost gate: budget seeded at {args.budget} "
              f"({len(budget['programs'])} programs)")
        if not args.selftest:
            return 0
    if not os.path.exists(args.budget):
        print(f"cost gate: no budget at {args.budget}; run --seed-budget "
              "first", file=sys.stderr)
        return 1
    budget = json.load(open(args.budget))

    if not args.selftest:
        errs = check(doc, budget)
        if errs:
            print("cost gate: FAIL\n  " + "\n  ".join(errs),
                  file=sys.stderr)
            return 1
    st = selftest(doc, budget)
    if st:
        print("cost gate: FAIL\n  " + "\n  ".join(st), file=sys.stderr)
        return 1
    byp = _by_program(doc)
    walls = doc.get("walls", {})
    print(f"cost gate: OK ({len(doc['entries'])} ledger entries over "
          f"{len(byp)} programs, {len(walls)} measured wall phases, "
          "selftest caught the injected +20% bytes regression)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
