#!/usr/bin/env python
"""Generate (or --check) docs/capability-matrix.md from the graftlint
semantic index — the gen_params_doc pattern applied to COMPOSITION.

The matrix is the statically extracted capability lattice of the feature
axes (residency x layout x learner x parallelism x linear x quantized x
boosting): every axis pair with an explicit config-validation **error**
cell or loud-demotion **demote** cell, each naming its source of truth
(graftlint rule R12, lambdagap_tpu/analysis/rules/r12_composition.py).
Pairs not listed compose freely — and R12 makes sure a NEW non-composing
pair cannot land without either a cell (which regenerates this doc) or a
finding (silent demotion / half-named demotion).

Usage: python tools/gen_capability_matrix.py [--check]

--check exits 1 when docs/capability-matrix.md differs from what the
current tree generates; tools/run_full_suite.sh G0 runs it right after
gen_params_doc --check, so the documented lattice can never drift from
the code.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DOC = os.path.join(REPO, "docs", "capability-matrix.md")


def generate() -> str:
    from lambdagap_tpu.analysis import build_index
    from lambdagap_tpu.analysis.rules.r12_composition import (
        extract_matrix, render_matrix)
    contexts, index, _failures = build_index(
        [os.path.join(REPO, "lambdagap_tpu")])
    return render_matrix(extract_matrix(contexts, index))


def main() -> int:
    text = generate()
    if "--check" in sys.argv:
        try:
            with open(DOC, "r", encoding="utf-8") as f:
                current = f.read()
        except OSError:
            print("capability-matrix check FAILED: docs/capability-"
                  "matrix.md is missing; run python "
                  "tools/gen_capability_matrix.py", file=sys.stderr)
            return 1
        if current != text:
            print("capability-matrix check FAILED: docs/capability-"
                  "matrix.md is stale (the extracted lattice changed); "
                  "run python tools/gen_capability_matrix.py",
                  file=sys.stderr)
            return 1
        print("capability-matrix check OK")
        return 0
    with open(DOC, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {DOC}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
