#!/usr/bin/env python
"""graftir G0 gate: IR contract verification + mutation selftest + the
merged static-analysis SARIF artifact.

Three steps, each failing loudly:

1. ``python -m lambdagap_tpu.analysis --ir`` under ``--max-seconds``
   (default 570): every registered contract verified over the full
   scenario inventory (five learners x four virtual grids, stream
   kernels, three predict engines, linear leaves). The per-program
   verdict cache makes an unchanged-tree re-run a hash walk; the budget
   is enforced on whatever the run actually was, so a broken cache or an
   outgrown inventory fails the gate instead of silently slowing it.
2. ``--ir --selftest``: the seeded-violation mutation suite (extra psum,
   host callback, f64 literal, pre-psum gradient scale, float-fed int
   reduction, unbucketed retrace) must be CAUGHT by the real checkers —
   the suite's teeth are proven on every gate run, not assumed.
3. ``--sarif-out``: render graftlint (warm cache) + graftir (warm cache)
   as SARIF and merge their runs into one artifact for code-scanning
   upload.

Exit 0 only when all requested steps pass.
"""
import argparse
import contextlib
import io
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the gate process itself is lint-side (stdlib-only); the graftir worker
# subprocesses it spawns override this via LAMBDAGAP_IR_CAPTURE
os.environ.setdefault("LAMBDAGAP_LINT_ONLY", "1")

from lambdagap_tpu.analysis import cli  # noqa: E402


def _capture(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftir_gate")
    ap.add_argument("--max-seconds", type=float, default=570.0,
                    help="wall budget for the IR pass (default 570)")
    ap.add_argument("--sarif-out", default=None, metavar="PATH",
                    help="write the merged graftlint+graftir SARIF here")
    ap.add_argument("--skip-selftest", action="store_true")
    args = ap.parse_args(argv)
    os.chdir(REPO)

    rc = cli.main(["--ir", "--max-seconds", str(args.max_seconds)])
    if rc != 0:
        print("graftir_gate: IR contract verification FAILED (exit "
              f"{rc}) — a lowered program drifted from its declared "
              "contract, or the pass blew its budget", file=sys.stderr)
        return 1

    if not args.skip_selftest:
        rc = cli.main(["--ir", "--selftest"])
        if rc != 0:
            print("graftir_gate: mutation selftest FAILED — a planted "
                  "violation went uncaught; the checkers have lost "
                  "their teeth", file=sys.stderr)
            return 1

    if args.sarif_out:
        rc_l, lint = _capture(["--format", "sarif", "lambdagap_tpu",
                               "bench.py", "bench_serve.py", "tools"])
        rc_i, ir = _capture(["--ir", "--format", "sarif"])
        merged = cli.merge_sarif([lint, ir])
        out_dir = os.path.dirname(args.sarif_out)
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(args.sarif_out, "w", encoding="utf-8") as f:
            f.write(merged + "\n")
        print(f"graftir_gate: merged SARIF (graftlint + graftir) -> "
              f"{args.sarif_out}")
        if rc_l != 0 or rc_i != 0:
            # the artifact is still written (it carries the findings),
            # but non-baselined findings keep the gate red
            print(f"graftir_gate: SARIF render saw findings "
                  f"(graftlint rc={rc_l}, graftir rc={rc_i})",
                  file=sys.stderr)
            return 1

    print("graftir_gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
