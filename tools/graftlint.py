#!/usr/bin/env python
"""graftlint wrapper: ``python tools/graftlint.py [paths...]``.

Thin shim over ``python -m lambdagap_tpu.analysis`` so the linter is
runnable from the tools/ directory without an installed package. See
docs/static-analysis.md for the rule catalog, suppression syntax, and
baseline workflow.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the linter never touches jax; skip the framework half of the package
# import (must be set before lambdagap_tpu's __init__ runs)
os.environ.setdefault("LAMBDAGAP_LINT_ONLY", "1")

from lambdagap_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
