#!/usr/bin/env python
"""Compiled-forest gate (ISSUE 16): one compile per fleet, one dispatch
per mixed batch.

Run by tools/run_full_suite.sh G0. The multi-tenant serving contract the
compiled subsystem exists to keep:

1. a ``predict_engine=compiled`` server comes up, and steady-state
   traffic at warmed bucket shapes triggers ZERO further bucket
   compiles — recompilation in the request path is the outage mode the
   padding buckets exist to prevent;
2. with ``serve_pack_models=true``, one mixed 3-tenant batcher window
   resolves through exactly ONE packed dispatch — many small forests,
   one executable — and every tenant's rows match its solo cache
   bit-for-bit;
3. replica B admits A's serialized artifact BY CONTENT HASH over the
   socket frontend, then places the same model: fleet-wide the shipped
   model is compiled exactly ONCE (A's local compile; B's build is a
   shared admission). A corrupt payload must be rejected loudly
   (ArtifactMismatch) and leave B serving correctly via local compile —
   never a wrong-model serve.

Exit 0 on pass; nonzero with a reason on any violation.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fail(msg: str) -> int:
    print(f"INFER GATE FAIL: {msg}")
    return 1


def train(params, rounds=10, seed=0, feats=10):
    import numpy as np
    import lambdagap_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(1500, feats).astype(np.float32)
    X[::13, 2] = np.nan
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "tpu_fast_predict_rows": 0,
                   "predict_engine": "compiled", **params},
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    return b, X


def main() -> int:
    import numpy as np
    from lambdagap_tpu.infer import ArtifactMismatch
    from lambdagap_tpu.serve import ForestServer, FrontendClient, \
        ServeFrontend

    # -- 1: zero steady-state recompiles ---------------------------------
    b1, X = train({})
    srv = ForestServer(b1, buckets=(8, 64, 512), warmup=True)
    try:
        warm = srv.stats.snapshot()["cache"]["bucket_compiles"]
        for i in range(20):
            srv.predict(X[: 1 + (i * 7) % 500])
        steady = srv.stats.snapshot()["cache"]["bucket_compiles"]
        print(f"infer gate: bucket compiles warmup={warm} "
              f"after 20 mixed-shape rounds={steady}")
        if steady != warm:
            return fail(f"{steady - warm} steady-state bucket recompiles "
                        "after warmup — compilation leaked into the "
                        "request path")
    finally:
        srv.close()

    # -- 2: one packed dispatch for a mixed 3-tenant window --------------
    b_pk, _ = train({"serve_pack_models": True}, seed=1)
    b2, _ = train({}, rounds=6, seed=2)
    b3, _ = train({"num_leaves": 7}, rounds=4, seed=3)
    # a long window so all three tenants land in ONE batcher round
    pk = ForestServer(b_pk, warmup=False, max_delay_ms=200.0, workers=1)
    try:
        pk.add_model("t2", b2._booster)
        pk.add_model("t3", b3._booster)
        futs = [pk.submit(X[:13]), pk.submit(X[13:20], model="t2"),
                pk.submit(X[20:31], model="t3")]
        outs = [f.result(60.0) for f in futs]
        packed = pk.stats.snapshot()["cache"]["packed_dispatches"]
        print(f"infer gate: mixed 3-tenant window -> "
              f"packed_dispatches={packed}")
        if packed != 1:
            return fail(f"mixed 3-tenant window cost {packed} packed "
                        "dispatches (want exactly 1 executable for the "
                        "whole window)")
        refs = [pk.registry.get("default").predict(X[:13]),
                pk.registry.get("t2").predict(X[13:20]),
                pk.registry.get("t3").predict(X[20:31])]
        for i, (out, ref) in enumerate(zip(outs, refs)):
            if not np.array_equal(out.values, ref):
                return fail(f"packed output for tenant {i} is not "
                            "bit-identical to its solo cache")
    finally:
        pk.close()

    # -- 3: fleet one-compile via hash admission over the wire -----------
    bA, _ = train({}, rounds=8, seed=4)     # the model the fleet shares
    boot, _ = train({"num_leaves": 7}, rounds=2, seed=5)
    A = ForestServer(bA, warmup=False)
    B = ForestServer(boot, warmup=False)
    try:
        with ServeFrontend(A) as feA, ServeFrontend(B) as feB:
            cliA = FrontendClient("127.0.0.1", feA.port)
            cliB = FrontendClient("127.0.0.1", feB.port)
            with cliA, cliB:
                payload = cliA.fetch_artifact()
                h = A.registry.get("default").artifact_hash
                try:
                    cliB.push_artifact(payload[:-6], expect_hash=h)
                    return fail("corrupt artifact payload was admitted")
                except ArtifactMismatch as e:
                    print(f"infer gate: corrupt admission rejected "
                          f"loudly ({e})")
                got = cliB.push_artifact(payload, expect_hash=h)
                if got != h:
                    return fail(f"admitted hash {got[:12]} != published "
                                f"{h[:12]}")
        B.add_model("shared", bA._booster)
        sA = A.stats.snapshot()["cache"]
        sB = B.stats.snapshot()["cache"]
        fleet_local = sA["compiles_local"] + sB["compiles_local"]
        print(f"infer gate: fleet compiles local A={sA['compiles_local']} "
              f"B={sB['compiles_local']} shared B={sB['compiles_shared']}")
        # each server compiles its own boot model at construction — A's
        # boot IS the publisher compile — so the shipped model must add
        # ZERO further local compiles fleet-wide
        if fleet_local != 2 or sB["compiles_shared"] != 1:
            return fail("shipped model was compiled more than once "
                        f"fleet-wide (local={fleet_local}, want 2 = "
                        "one boot per replica; "
                        f"shared={sB['compiles_shared']}, want 1)")
        if B.registry.get("shared").artifact_hash != h:
            return fail("replica B's shared model does not carry the "
                        "admitted artifact hash")
        if not np.array_equal(B.predict(X[:64], model="shared"),
                              A.predict(X[:64])):
            return fail("replica B's admitted forest is not bit-identical "
                        "to the publisher's")
    finally:
        A.close()
        B.close()

    print("infer gate: PASS — zero steady recompiles, mixed batch in one "
          "packed dispatch, one compile fleet-wide by artifact hash")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
