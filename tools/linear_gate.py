#!/usr/bin/env python
"""Linear-leaf gate for tools/run_full_suite.sh (ISSUE 11 CI satellite).

Runs a short ``linear_tree=true`` training on the FUSED learner with
telemetry, then checks the whole model-class contract end to end:

* zero steady-state recompiles — the batched moment accumulation compiles
  at ONE fixed shape per config (ops/linear.py leaf_feature_width), so a
  steady compile means the shape pinning regressed;
* the trained model really carries linear leaves (is_linear=1 payload);
* tensor-engine predictions ``array_equal`` to the scan oracle on the
  result (the engine parity contract over the linear payload);
* a serve dispatch of the linear model succeeds and is bit-identical to
  device predict (the serve/cache.py rejection must stay gone).

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = 6


def main() -> int:
    import numpy as np

    import lambdagap_tpu as lgb

    out = os.path.join(tempfile.mkdtemp(prefix="lambdagap_gate_"),
                       "run.jsonl")
    rng = np.random.RandomState(0)
    X = (rng.rand(2000, 8) * 4).astype(np.float32)
    y = (2.0 * X[:, 0] - 1.5 * X[:, 1] + np.where(X[:, 2] > 2, 3.0, 0.0)
         + 0.05 * rng.randn(2000)).astype(np.float32)
    booster = lgb.train({"objective": "regression", "num_leaves": 15,
                         "linear_tree": True, "linear_lambda": 1e-3,
                         "verbose": -1, "telemetry": True,
                         "telemetry_out": out, "tpu_fused_learner": "1"},
                        lgb.Dataset(X, label=y), num_boost_round=ROUNDS)

    text = booster.model_to_string()
    if "is_linear=1" not in text:
        print("linear gate: trained model carries no linear leaves",
              file=sys.stderr)
        return 1

    records = [json.loads(ln) for ln in open(out) if ln.strip()]
    iters = [r for r in records if r.get("type") == "iteration"]
    steady = sum(r["compiles"]["steady"] for r in iters)
    if steady:
        print(f"linear gate: {steady} steady-state recompile(s) — the "
              f"fixed-shape moment accumulation (ops/linear.py) regressed",
              file=sys.stderr)
        return 1

    outs = {}
    for eng in ("tensor", "scan"):
        bb = lgb.Booster(model_str=text, params={"predict_engine": eng,
                                                 "verbose": -1})
        outs[eng] = bb.predict(X[:777], raw_score=True)
    if not np.array_equal(outs["tensor"], outs["scan"]):
        print("linear gate: tensor engine diverged from the scan oracle "
              "on a linear forest", file=sys.stderr)
        return 1

    ref = booster.predict(X[:128])
    with booster.as_server(buckets=(64,), warmup=True) as server:
        got = server.predict(X[:128])
    if not np.array_equal(got, ref):
        print("linear gate: serve dispatch of the linear model is not "
              "bit-identical to device predict", file=sys.stderr)
        return 1

    rmse = float(np.sqrt(np.mean((booster.predict(X) - y) ** 2)))
    print(f"linear gate OK: {ROUNDS} fused linear iterations, zero steady "
          f"recompiles, tensor==scan on 777 rows, serve bit-identical "
          f"(train rmse {rmse:.4f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
