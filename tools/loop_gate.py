#!/usr/bin/env python
"""Continuous-learning loop gate (ISSUE 20): every seam SIGKILLed.

Run by tools/run_full_suite.sh G0. Four scenarios, one per seam of the
train -> shadow -> promote loop (docs/continuous-learning.md):

A. **trainer killed mid-candidate-write** — a REAL ``task=loop_train``
   subprocess folds tailed batches; the ``candidate_torn`` fault tears
   its second candidate write (the SIGKILL-mid-write window,
   materialized) and the process is then SIGKILLed. The torn candidate
   must be rejected by checksum, resume must pick the last VALID epoch,
   and the restarted trainer's next candidate must extend that epoch's
   trees byte-identically.
B. **shadow replica killed mid-evaluation** — the live fleet serves
   while a subprocess shadow replica mirrors 100% of traffic; the
   shadow is SIGKILLed mid-load. Live goodput must stay >= 95% of the
   pre-kill baseline (shadow is strictly off the reply path), the
   sheds must be counted, and the controller must restart the shadow
   window on a fresh replica.
C. **serving replica killed mid-promote** — a 3-replica subprocess
   fleet; one replica is SIGKILLed between the shadow decision and the
   rollout. The fleet-atomic rollout must roll back — survivors
   converge all-base, tree-hash identical, never mixed — and the NEXT
   candidate epoch must then promote onto the survivors.
D. **delta_swap_fail injected mid-rollout** — one in-process replica
   arms the delta fault; the promotion's rollout must observe the
   fleet-atomic rollback (``loop_rollback`` JSONL event, every replica
   back on base trees), and ``loop_status`` must answer the state
   machine's position over the wire.

Exit 0 on pass; nonzero with a reason on any violation.
"""
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SHADOW_GOODPUT_FRACTION = 0.95


def fail(msg: str) -> int:
    print(f"LOOP GATE FAIL: {msg}")
    return 1


def write_batches(dirpath: str, n: int, start: int = 0, rows: int = 300,
                  cols: int = 5, seed: int = 0) -> None:
    import numpy as np
    from lambdagap_tpu.data.tail import write_batch
    rng = np.random.RandomState(seed + start)
    for i in range(start, start + n):
        X = rng.randn(rows, cols)
        y = X[:, 0] * 2.0 + 0.1 * rng.randn(rows)
        write_batch(dirpath, f"batch_{i:04d}", X, y)


def spawn_trainer(batches: str, model: str, *, faults: str = "",
                  max_epochs: int = 0, trace_out: str = ""):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "lambdagap_tpu", "task=loop_train",
           f"data={batches}", f"output_model={model}", "verbose=-1",
           "min_data_in_leaf=5", "num_leaves=7", "loop_iters_per_fold=3",
           "loop_interval_s=0.2", "guard_snapshot_keep=4",
           f"loop_max_epochs={max_epochs}"]
    if faults:
        cmd.append(f"guard_faults={faults}")
    if trace_out:
        cmd.append(f"serve_trace_out={trace_out}")
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, cwd=REPO, env=env)


def spawn_replica(model_path: str, port: int = 0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "lambdagap_tpu", "task=serve",
         f"input_model={model_path}", f"serve_port={port}", "verbose=-1",
         "serve_max_delay_ms=1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO, env=env)


def await_port(proc, timeout_s: float = 120.0) -> int:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("SERVE_PORT="):
            return int(line.split("=", 1)[1])
    raise RuntimeError("replica never printed SERVE_PORT")


def await_file(path: str, timeout_s: float = 180.0) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        if os.path.exists(path):
            return
        time.sleep(0.1)
    raise RuntimeError(f"timed out waiting for {path}")


def reap(*procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()


def trees_of(text: str):
    from lambdagap_tpu.serve.delta import split_model_text
    return split_model_text(text)[1]


def write_candidate(booster, family: str, epoch: int) -> str:
    from lambdagap_tpu.guard.snapshot import write_training_snapshot
    return write_training_snapshot(
        booster._booster, family, candidate=True,
        extra_state={"candidate_epoch": epoch})


def train_base(path: str, seed: int = 0, rounds: int = 8):
    import numpy as np
    import lambdagap_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(1200, 8).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "tpu_fast_predict_rows": 0},
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    b.save_model(path)
    return X, y, b


# ---------------------------------------------------------------------------
def scenario_a_trainer_torn(tmp: str) -> int:
    from lambdagap_tpu.guard.snapshot import (latest_snapshot,
                                              list_snapshots, read_snapshot)
    from lambdagap_tpu.obs import events as obs_events

    batches = os.path.join(tmp, "batches_a")
    os.makedirs(batches)
    model = os.path.join(tmp, "cand_a.txt")
    write_batches(batches, 1, start=0)
    print("loop gate [A]: spawning task=loop_train with candidate_torn=2")
    trainer = spawn_trainer(batches, model, faults="candidate_torn=2")
    try:
        # epoch 1 lands valid (iters_per_fold=3 -> snapshot_iter_3)
        await_file(f"{model}.snapshot_iter_3")
        found = latest_snapshot(model)
        if found is None:
            return fail("[A] epoch-1 candidate unreadable")
        # feed one more batch: the fold after it is the TORN write
        write_batches(batches, 1, start=1)
        await_file(f"{model}.snapshot_iter_6")
        time.sleep(0.2)                  # let the torn bytes settle
        print("loop gate [A]: SIGKILL trainer after the torn epoch-2 write")
        trainer.send_signal(signal.SIGKILL)
        trainer.wait(timeout=20)

        torn_path = f"{model}.snapshot_iter_6"
        try:
            read_snapshot(torn_path)
            return fail("[A] the torn candidate validated — the fault "
                        "point did not tear it")
        # graftlint: disable=R8 — the raise IS the pass condition: a
        # torn candidate must be rejected by checksum, and the assertion
        # above already fails the gate when it validates
        except Exception:
            pass
        found = latest_snapshot(model)
        if found is None:
            return fail("[A] no valid snapshot survived the torn write")
        path1, text1, state1 = found
        if int(state1.get("candidate_epoch", -1)) != 1:
            return fail(f"[A] resume picked {path1} (epoch "
                        f"{state1.get('candidate_epoch')}), not the last "
                        "VALID epoch 1 — torn candidate not rejected")
        print(f"loop gate [A]: torn epoch-2 rejected; latest valid is "
              f"epoch 1 at {os.path.basename(path1)}")

        # restart: the trainer must resume from epoch 1 and extend it
        trace_out = os.path.join(tmp, "trainer_events.jsonl")
        t2 = spawn_trainer(batches, model, max_epochs=1,
                           trace_out=trace_out)
        if t2.wait(timeout=300) != 0:
            return fail("[A] restarted trainer exited nonzero")
        path2, text2, state2 = latest_snapshot(model)
        if int(state2.get("candidate_epoch", -1)) != 2:
            return fail(f"[A] restarted trainer wrote epoch "
                        f"{state2.get('candidate_epoch')}, wanted 2")
        old, new = trees_of(text1), trees_of(text2)
        if len(new) <= len(old) or new[:len(old)] != old:
            return fail("[A] resumed candidate does not extend the last "
                        "valid candidate's trees byte-identically")
        print(f"loop gate [A]: resumed epoch 2 extends epoch 1 "
              f"byte-identically ({len(old)} -> {len(new)} trees); "
              f"{len(list_snapshots(model))} snapshots retained")
        records, _torn = obs_events.read_file(trace_out)
        if not any(r.get("event") == "loop_candidate_written"
                   for r in records):
            return fail("[A] trainer emitted no loop_candidate_written "
                        "event")
        errs = obs_events.validate_file(trace_out)
        if errs:
            return fail(f"[A] trainer event log is not schema-valid: "
                        f"{errs[:3]}")
        print("loop gate [A]: PASS")
        return 0
    finally:
        reap(trainer)


# ---------------------------------------------------------------------------
def scenario_b_shadow_killed(tmp: str) -> int:
    import lambdagap_tpu as lgb
    from lambdagap_tpu.serve import (Autonomics, LocalReplica,
                                     RemoteReplica, Router, run_open_loop)
    from lambdagap_tpu.loop import PromotionController

    base_path = os.path.join(tmp, "base_b.txt")
    X, y, base = train_base(base_path, seed=1)
    cand = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbose": -1}, lgb.Dataset(X, label=y),
                     num_boost_round=3, init_model=base_path)
    family = os.path.join(tmp, "cand_b.txt")
    write_candidate(cand, family, epoch=1)

    router = Router(
        [LocalReplica(f"r{i}",
                      lgb.Booster(model_file=base_path).as_server(
                          max_delay_ms=1.0))
         for i in range(2)], own_replicas=True)
    auto = Autonomics(router, interval_s=999.0)
    shadow_procs = []

    def make_shadow(text):
        p = os.path.join(tmp, f"shadow_b_{len(shadow_procs)}.txt")
        with open(p, "w") as f:
            f.write(text)
        proc = spawn_replica(p)
        shadow_procs.append(proc)
        return RemoteReplica("shadow", "127.0.0.1", await_port(proc))

    ctl = PromotionController(router, auto, family, sample=1.0,
                              min_requests=10 ** 9,  # hold the window open
                              make_shadow=make_shadow)
    try:
        ctl.tick()                       # idle -> shadowing
        if router.loop_status()["state"] != "shadowing":
            return fail("[B] controller never armed the shadow")
        print("loop gate [B]: shadow replica up; measuring baseline")
        pre = run_open_loop(router.submit, X, 120.0, 180,
                            deadline_ms=250.0, seed=1)
        if pre["goodput_ratio"] < 0.5 or pre["counts"]["error"]:
            return fail(f"[B] baseline round unusable: {pre['counts']}")

        def killer():
            time.sleep(180 / 120.0 * 0.4)
            print("loop gate [B]: SIGKILL shadow replica mid-evaluation")
            shadow_procs[-1].send_signal(signal.SIGKILL)

        k = threading.Thread(target=killer)
        k.start()
        chaos = run_open_loop(router.submit, X, 120.0, 180,
                              deadline_ms=250.0, seed=2)
        k.join()
        if chaos["counts"]["error"]:
            return fail(f"[B] live path saw errors with the shadow dying: "
                        f"{chaos['counts']}")
        if chaos["goodput_ratio"] < SHADOW_GOODPUT_FRACTION \
                * pre["goodput_ratio"]:
            return fail(f"[B] live goodput collapsed with the shadow: "
                        f"{chaos['goodput_ratio']:.2f} < "
                        f"{SHADOW_GOODPUT_FRACTION:.0%} of "
                        f"{pre['goodput_ratio']:.2f}")
        deadline = time.time() + 30.0
        while time.time() < deadline:
            snap = router.shadow_snapshot()
            if snap and snap["dead"]:
                break
            time.sleep(0.2)
        else:
            return fail(f"[B] mirror never marked the dead shadow: {snap}")
        if snap["shed"] == 0:
            return fail("[B] shadow death shed nothing — mirrors were not "
                        "reaching the replica")
        print(f"loop gate [B]: live goodput {chaos['goodput_ratio']:.2f} "
              f"vs baseline {pre['goodput_ratio']:.2f}; "
              f"{snap['shed']} mirror(s) shed silently")

        ctl.tick()                       # dead shadow -> window restart
        st = ctl.status()
        if st["counters"]["shadow_restarts"] != 1:
            return fail(f"[B] controller did not restart the window: "
                        f"{st['counters']}")
        futs = [router.submit(X[:1]) for _ in range(30)]
        for f in futs:
            f.result(30)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            snap2 = router.shadow_snapshot()
            if snap2 and not snap2["dead"] and snap2["compared"] > 0:
                break
            time.sleep(0.2)
        else:
            return fail(f"[B] restarted window never compared a "
                        f"request: {snap2}")
        print(f"loop gate [B]: fresh window live on the respawned shadow "
              f"({snap2['compared']} compared)")
        print("loop gate [B]: PASS")
        return 0
    finally:
        router.close()
        reap(*shadow_procs)


# ---------------------------------------------------------------------------
def scenario_c_replica_killed_mid_promote(tmp: str) -> int:
    import lambdagap_tpu as lgb
    from lambdagap_tpu.serve import (Autonomics, RemoteReplica, Router)
    from lambdagap_tpu.loop import PromotionController

    base_path = os.path.join(tmp, "base_c.txt")
    X, y, base = train_base(base_path, seed=2)
    base_text = open(base_path).read()
    family = os.path.join(tmp, "cand_c.txt")
    cand1 = lgb.train({"objective": "binary", "num_leaves": 15,
                       "verbose": -1}, lgb.Dataset(X, label=y),
                      num_boost_round=3, init_model=base_path)
    write_candidate(cand1, family, epoch=1)

    print("loop gate [C]: spawning 3 task=serve replicas")
    procs = {f"r{i}": spawn_replica(base_path) for i in range(3)}
    ports = {n: await_port(p) for n, p in procs.items()}
    router = Router([RemoteReplica(n, "127.0.0.1", port)
                     for n, port in sorted(ports.items())])
    auto = Autonomics(router, interval_s=999.0)
    ctl = PromotionController(router, auto, family, sample=1.0,
                              min_requests=15, threshold=1e9,
                              base_source=base_text,
                              watch_min_requests=10)

    def drive(n):
        futs = [router.submit(X[:1]) for _ in range(n)]
        for f in futs:
            f.result(30)

    try:
        ctl.tick()                       # idle -> shadowing
        drive(30)
        deadline = time.time() + 60.0
        while time.time() < deadline:
            snap = router.shadow_snapshot()
            if snap and snap["compared"] >= 15:
                break
            time.sleep(0.2)
        else:
            return fail(f"[C] shadow window never filled: {snap}")
        ctl.tick()                       # shadowing -> promoting
        if ctl.status()["state"] != "promoting":
            return fail(f"[C] window full but state is "
                        f"{ctl.status()['state']}")
        print("loop gate [C]: SIGKILL replica r2 mid-promote")
        procs["r2"].send_signal(signal.SIGKILL)
        procs["r2"].wait(timeout=20)
        ctl.tick()                       # rollout hits the corpse
        st = ctl.status()
        if st["state"] != "idle" or st["counters"]["rollbacks"] != 1:
            return fail(f"[C] promote over a dead replica did not roll "
                        f"back: {st}")

        # survivors must agree — all-base: probe each one directly and
        # require exact prediction agreement with each other and the base
        survivors = ["r0", "r1"]
        import numpy as np
        probe = X[:16]
        want = base.predict(probe)
        got = {}
        for n in survivors:
            got[n] = np.asarray(
                router.replica(n).submit(probe).result(30).values)
        agree = all(np.array_equal(got[survivors[0]], v)
                    for v in got.values())
        if not agree:
            return fail("[C] MIXED fleet: survivors answer differently "
                        "after the failed promote")
        if not np.allclose(got[survivors[0]].ravel(), want.ravel(),
                           rtol=0, atol=1e-6):
            return fail("[C] survivors are uniform but NOT on base after "
                        "the rollback")
        print("loop gate [C]: survivors converged all-base "
              f"(rollback after dead r2; epoch 1 rejected)")

        # the corpse must leave rotation, then the NEXT epoch promotes
        drive(30)                        # failovers mark r2 dead
        if "r2" in router.replica_names(live_only=True):
            return fail("[C] dead r2 still in live rotation")
        cand2 = lgb.train({"objective": "binary", "num_leaves": 15,
                           "verbose": -1}, lgb.Dataset(X, label=y),
                          num_boost_round=5, init_model=base_path)
        write_candidate(cand2, family, epoch=2)
        ctl.tick()                       # idle -> shadowing (epoch 2)
        drive(30)
        deadline = time.time() + 60.0
        while time.time() < deadline:
            snap = router.shadow_snapshot()
            if snap and snap["compared"] >= 15:
                break
            time.sleep(0.2)
        else:
            return fail(f"[C] epoch-2 window never filled: {snap}")
        ctl.tick()                       # -> promoting
        ctl.tick()                       # -> watching (rollout on survivors)
        st = ctl.status()
        if st["state"] != "watching" or st["promoted_epoch"] != 2:
            return fail(f"[C] epoch 2 did not promote on survivors: {st}")
        drive(20)
        ctl.tick()                       # watching -> idle (clean)
        st = ctl.status()
        if st["state"] != "idle" or st["counters"]["promotions"] != 1:
            return fail(f"[C] watch window did not clear: {st}")
        cand2_vals = cand2.predict(probe)
        for n in survivors:
            v = np.asarray(router.replica(n).submit(probe).result(30).values)
            if not np.allclose(v.ravel(), cand2_vals.ravel(), rtol=0,
                               atol=1e-6):
                return fail(f"[C] survivor {n} is not serving the "
                            "promoted epoch-2 candidate")
        print("loop gate [C]: epoch 2 promoted onto the 2 survivors; "
              "watch window clean")
        print("loop gate [C]: PASS")
        return 0
    finally:
        router.close()
        reap(*procs.values())


# ---------------------------------------------------------------------------
def scenario_d_delta_fault_mid_rollout(tmp: str, events_path: str) -> int:
    import numpy as np
    import lambdagap_tpu as lgb
    from lambdagap_tpu.guard.faults import FaultPlan
    from lambdagap_tpu.loop import PromotionController
    from lambdagap_tpu.obs import events as obs_events
    from lambdagap_tpu.serve import (Autonomics, FrontendClient,
                                     LocalReplica, Router, ServeFrontend)

    base_path = os.path.join(tmp, "base_d.txt")
    X, y, base = train_base(base_path, seed=3)
    base_text = open(base_path).read()
    cand = lgb.train({"objective": "binary", "num_leaves": 15,
                      "verbose": -1}, lgb.Dataset(X, label=y),
                     num_boost_round=3, init_model=base_path)
    family = os.path.join(tmp, "cand_d.txt")
    write_candidate(cand, family, epoch=1)

    router = Router(
        [LocalReplica(f"r{i}",
                      lgb.Booster(model_file=base_path).as_server(
                          max_delay_ms=1.0))
         for i in range(2)], own_replicas=True)
    auto = Autonomics(router, interval_s=999.0)
    ctl = PromotionController(router, auto, family, sample=1.0,
                              min_requests=10, threshold=1e9,
                              base_source=base_text)
    fe = ServeFrontend(router).start()
    client = FrontendClient("127.0.0.1", fe.port)
    try:
        # arm the delta fault on ONE replica: rollout must be all-or-none
        router.replica("r1").server._faults = FaultPlan("delta_swap_fail=1")
        ctl.tick()                       # idle -> shadowing
        futs = [router.submit(X[:1]) for _ in range(20)]
        for f in futs:
            f.result(30)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            snap = router.shadow_snapshot()
            if snap and snap["compared"] >= 10:
                break
            time.sleep(0.2)
        else:
            return fail(f"[D] shadow window never filled: {snap}")
        ctl.tick()                       # -> promoting
        ctl.tick()                       # rollout: r0 lands, r1 faults
        st = client.loop_status()
        if st["state"] != "idle" or st["counters"]["rollbacks"] != 1:
            return fail(f"[D] faulted rollout did not roll back: {st}")
        if auto.counters["delta_rollbacks"] != 1:
            return fail("[D] autonomics did not record the delta rollback")
        base_trees = tuple(trees_of(base_text))
        forests = {tuple(trees_of(router.replica(n).server.model_text()))
                   for n in router.replica_names()}
        if forests != {base_trees}:
            return fail("[D] fleet not uniformly on base after the "
                        "mid-rollout fault — rollback was not atomic")
        print(f"loop gate [D]: delta fault mid-rollout rolled the fleet "
              f"back atomically; wire loop_status={st['state']}")

        # with the fault disarmed, the NEXT epoch lands delta-mode
        cand2 = lgb.train({"objective": "binary", "num_leaves": 15,
                           "verbose": -1}, lgb.Dataset(X, label=y),
                          num_boost_round=5, init_model=base_path)
        write_candidate(cand2, family, epoch=2)
        ctl.tick()
        futs = [router.submit(X[:1]) for _ in range(20)]
        for f in futs:
            f.result(30)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            snap = router.shadow_snapshot()
            if snap and snap["compared"] >= 10:
                break
            time.sleep(0.2)
        ctl.tick()
        ctl.tick()
        st = client.loop_status()
        if st["state"] != "watching" or st["promoted_epoch"] != 2:
            return fail(f"[D] epoch 2 did not promote after the fault "
                        f"cleared: {st}")
        # wire op bijection partner: shadow_on arms/disarms over the wire
        sh = client.shadow_on(base_path, sample=1.0)
        if not sh.get("armed"):
            return fail(f"[D] shadow_on did not arm over the wire: {sh}")
        sh = client.shadow_on(None, sample=0.0)
        if sh.get("armed"):
            return fail(f"[D] shadow_on sample=0 did not disarm: {sh}")

        # every stage of the loop must have emitted a schema-valid event
        from lambdagap_tpu.obs import trace as obs_trace
        obs_trace.RECORDER.close()
        errs = obs_events.validate_file(events_path)
        if errs:
            return fail(f"[D] loop event log is not schema-valid: "
                        f"{errs[:3]}")
        records, _torn = obs_events.read_file(events_path)
        seen = {r.get("event") for r in records}
        need = {"loop_candidate", "loop_shadow_start", "loop_shadow_window",
                "loop_rollback", "loop_rollout", "loop_promote",
                "loop_shadow_restart"}
        missing = need - seen
        if missing:
            return fail(f"[D] loop events missing from the JSONL stream: "
                        f"{sorted(missing)}")
        print(f"loop gate [D]: {len(need)} loop_* event kinds "
              "schema-valid in the JSONL stream")
        print("loop gate [D]: PASS")
        return 0
    finally:
        client.close()
        fe.close()
        router.close()


def main() -> int:
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        events_path = os.path.join(tmp, "loop_events.jsonl")
        from lambdagap_tpu.obs import trace as obs_trace
        obs_trace.configure(out=events_path)
        rc = scenario_a_trainer_torn(tmp)
        if rc:
            return rc
        rc = scenario_b_shadow_killed(tmp)
        if rc:
            return rc
        rc = scenario_c_replica_killed_mid_promote(tmp)
        if rc:
            return rc
        rc = scenario_d_delta_fault_mid_rollout(tmp, events_path)
        if rc:
            return rc
    print("loop gate: PASS — torn candidate resume, shadow isolation "
          "under death, fleet convergence through a mid-promote kill, "
          "atomic rollback on an injected delta fault")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
