#!/usr/bin/env python
"""Multi-chip gate for tools/run_full_suite.sh (ISSUE 8 CI satellite).

Self-provisions an 8-virtual-device CPU mesh (subprocess, same recipe as
``__graft_entry__.dryrun_multichip``) and asserts the distributed training
contract the unified sharding registry is supposed to guarantee:

1. the fused data-parallel learner on 8 devices builds trees
   BYTE-IDENTICAL to the 1-device fused serial learner at a small shape
   (rows not divisible by 8, so pad rows are live). The gate runs the
   QUANTIZED path (use_quantized_grad, deterministic rounding): integer
   gradient levels accumulate exactly (order-independent sums below the
   f32-exact range), so the histogram reduction is width-invariant BY
   CONSTRUCTION — the invariant elastic resume at a different device
   count rests on. (The f32 path is correct but only
   reduction-order-equal: near-tied split gains may legitimately resolve
   differently across widths, so bit-identity is a quant-mode contract.)
2. ZERO steady-state recompiles in the 8-device arm (a per-width program
   that keeps retracing would silently serialize the mesh);
3. the guard snapshot sidecar carries the mesh + row-shard geometry
   fields (``mesh.axes/shape/n_devices/n_pad/n_loc``) that
   ``resume=auto`` reads back for elastic resume.

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")

import json
import numpy as np

import lambdagap_tpu as lgb
from lambdagap_tpu.guard.snapshot import read_snapshot, snapshot_path, \
    write_training_snapshot

ROUNDS = 6
WARMUP = 2
N = 6001          # deliberately not divisible by 8: pad rows are live

rng = np.random.RandomState(0)
X = rng.randn(N, 10).astype(np.float32)
y = (X[:, 0] - 0.4 * X[:, 1] + 0.2 * rng.randn(N) > 0).astype(np.float32)

def train(n_dev, tree_learner):
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": tree_learner, "tpu_fused_learner": "1",
              "min_data_in_leaf": 20, "tpu_num_devices": n_dev,
              "use_quantized_grad": True, "stochastic_rounding": False,
              "telemetry": True, "telemetry_warmup": WARMUP}
    return lgb.train(params, lgb.Dataset(X, label=y, params=params),
                     num_boost_round=ROUNDS)

b1 = train(1, "serial")
b8 = train(8, "data")
from lambdagap_tpu.parallel.fused_parallel import \
    FusedDataParallelTreeLearner
assert isinstance(b8._booster.learner, FusedDataParallelTreeLearner)

t1 = b1.model_to_string().split("end of trees")[0]
t8 = b8.model_to_string().split("end of trees")[0]
if t1.split("Tree=0")[1] != t8.split("Tree=0")[1]:
    print("MCGATE_FAIL trees: 8-device fused data-parallel diverged from "
          "the 1-device fused serial learner")
    sys.exit(1)

tel = b8._booster.telemetry
bad = [(r["iter"], r["compiles"]["total"]) for r in tel.records
       if r.get("iter", 0) >= WARMUP
       and (r.get("compiles") or {}).get("total", 0)]
if bad:
    print("MCGATE_FAIL steady-state recompiles on the 8-device mesh: "
          + json.dumps(bad))
    sys.exit(1)

import tempfile
with tempfile.TemporaryDirectory() as td:
    out = f"{td}/m.txt"
    write_training_snapshot(b8._booster, out)
    _, state = read_snapshot(snapshot_path(out, b8._booster.iter_))
mesh = state.get("mesh") or {}
want = {"axes": ["data", "feature"], "shape": [8, 1], "n_devices": 8}
for k, v in want.items():
    if mesh.get(k) != v:
        print(f"MCGATE_FAIL sidecar mesh field {k}={mesh.get(k)!r} "
              f"(want {v!r}); full sidecar mesh: {json.dumps(mesh)}")
        sys.exit(1)
if mesh.get("n_loc", 0) * 8 != mesh.get("n_pad", -1):
    print("MCGATE_FAIL sidecar shard geometry inconsistent: "
          + json.dumps(mesh))
    sys.exit(1)

print("MCGATE_" + "OK 8-device fused data-parallel bit-identical to "
      "1-device serial, zero steady compiles, sidecar mesh fields "
      + json.dumps(mesh))
"""


def main() -> int:
    env = dict(os.environ)
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if not f.startswith("--xla_force_host_platform"))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD % {"repo": REPO}],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900)
    sys.stderr.write(proc.stderr[-2000:] if proc.stderr else "")
    tail = (proc.stdout or "").strip().splitlines()
    for line in tail[-5:]:
        print(line)
    if proc.returncode != 0 or not any("MCGATE_OK" in ln for ln in tail):
        print("multichip gate: FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
