#!/usr/bin/env python
"""Multi-chip gate for tools/run_full_suite.sh (ISSUE 8 CI satellite).

Self-provisions an 8-virtual-device CPU mesh (subprocess, same recipe as
``__graft_entry__.dryrun_multichip``) and asserts the distributed training
contract the unified sharding registry is supposed to guarantee:

1. the fused data-parallel learner on 8 devices builds trees
   BYTE-IDENTICAL to the 1-device fused serial learner at a small shape
   (rows not divisible by 8, so pad rows are live). The gate runs the
   QUANTIZED path (use_quantized_grad, deterministic rounding): integer
   gradient levels accumulate exactly (order-independent sums below the
   f32-exact range), so the histogram reduction is width-invariant BY
   CONSTRUCTION — the invariant elastic resume at a different device
   count rests on. (The f32 path is correct but only
   reduction-order-equal: near-tied split gains may legitimately resolve
   differently across widths, so bit-identity is a quant-mode contract.)
2. ZERO steady-state recompiles in the 8-device arm (a per-width program
   that keeps retracing would silently serialize the mesh);
3. the guard snapshot sidecar carries the mesh + row-shard geometry
   fields (``mesh.axes/shape/n_devices/n_pad/n_loc``) that
   ``resume=auto`` reads back for elastic resume;
4. (ISSUE 15) the fused 2-D data x feature program on a genuine 2x4
   grid builds quantized trees bit-identical to the 1-device serial
   run with zero steady recompiles — G0 guards the dd>1 && ff>1
   composition, not just the pure axes;
5. (ISSUE 15) one stream x distributed parity check: the composed
   out-of-core path on 2 virtual devices over 2 ragged host shards is
   bit-identical to the resident run on the same grid (the same-grid
   mirror contract — f32 cross-width identity is shape-lucky per the
   ISSUE-8 finding, so the cross-width legs stay quantized), with the
   h2d_prefetch/chunk_wait ring phases live and zero steady compiles.

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")

import json
import numpy as np

import lambdagap_tpu as lgb
from lambdagap_tpu.guard.snapshot import read_snapshot, snapshot_path, \
    write_training_snapshot

ROUNDS = 6
WARMUP = 2
N = 6001          # deliberately not divisible by 8: pad rows are live

rng = np.random.RandomState(0)
X = rng.randn(N, 10).astype(np.float32)
y = (X[:, 0] - 0.4 * X[:, 1] + 0.2 * rng.randn(N) > 0).astype(np.float32)

def train(n_dev, tree_learner):
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": tree_learner, "tpu_fused_learner": "1",
              "min_data_in_leaf": 20, "tpu_num_devices": n_dev,
              "use_quantized_grad": True, "stochastic_rounding": False,
              "telemetry": True, "telemetry_warmup": WARMUP}
    return lgb.train(params, lgb.Dataset(X, label=y, params=params),
                     num_boost_round=ROUNDS)

b1 = train(1, "serial")
b8 = train(8, "data")
from lambdagap_tpu.parallel.fused_parallel import \
    FusedDataParallelTreeLearner
assert isinstance(b8._booster.learner, FusedDataParallelTreeLearner)

t1 = b1.model_to_string().split("end of trees")[0]
t8 = b8.model_to_string().split("end of trees")[0]
if t1.split("Tree=0")[1] != t8.split("Tree=0")[1]:
    print("MCGATE_FAIL trees: 8-device fused data-parallel diverged from "
          "the 1-device fused serial learner")
    sys.exit(1)

tel = b8._booster.telemetry
bad = [(r["iter"], r["compiles"]["total"]) for r in tel.records
       if r.get("iter", 0) >= WARMUP
       and (r.get("compiles") or {}).get("total", 0)]
if bad:
    print("MCGATE_FAIL steady-state recompiles on the 8-device mesh: "
          + json.dumps(bad))
    sys.exit(1)

import tempfile
with tempfile.TemporaryDirectory() as td:
    out = f"{td}/m.txt"
    write_training_snapshot(b8._booster, out)
    _, state = read_snapshot(snapshot_path(out, b8._booster.iter_))
mesh = state.get("mesh") or {}
want = {"axes": ["data", "feature"], "shape": [8, 1], "n_devices": 8}
for k, v in want.items():
    if mesh.get(k) != v:
        print(f"MCGATE_FAIL sidecar mesh field {k}={mesh.get(k)!r} "
              f"(want {v!r}); full sidecar mesh: {json.dumps(mesh)}")
        sys.exit(1)
if mesh.get("n_loc", 0) * 8 != mesh.get("n_pad", -1):
    print("MCGATE_FAIL sidecar shard geometry inconsistent: "
          + json.dumps(mesh))
    sys.exit(1)

# -- ISSUE 15: the genuine 2-D data x feature program ------------------
# quantized trees must be bit-identical on a real dd>1 && ff>1 grid too
# (integer psum over data is grid-invariant; the feature all_gather
# argmax picks the same global first-max for any column blocking)
def train_grid(grid, residency="hbm", extra=None):
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": "data", "tpu_fused_learner": "1",
              "min_data_in_leaf": 20, "mesh_shape": grid,
              "use_quantized_grad": True, "stochastic_rounding": False,
              "data_residency": residency,
              "telemetry": True, "telemetry_warmup": WARMUP}
    params.update(extra or {})
    return lgb.train(params, lgb.Dataset(X, label=y, params=params),
                     num_boost_round=ROUNDS)

from lambdagap_tpu.parallel.fused_parallel import Fused2DTreeLearner
b24 = train_grid("2x4")
assert isinstance(b24._booster.learner, Fused2DTreeLearner)
t24 = b24.model_to_string().split("end of trees")[0]
if t24.split("Tree=0")[1] != t1.split("Tree=0")[1]:
    print("MCGATE_FAIL 2-D grid: 2x4 fused 2-D trees diverged from the "
          "1-device fused serial learner on the quantized path")
    sys.exit(1)
tel24 = b24._booster.telemetry
bad24 = [(r["iter"], r["compiles"]["total"]) for r in tel24.records
         if r.get("iter", 0) >= WARMUP
         and (r.get("compiles") or {}).get("total", 0)]
if bad24:
    print("MCGATE_FAIL steady-state recompiles on the 2x4 grid: "
          + json.dumps(bad24))
    sys.exit(1)

# -- ISSUE 15: stream x distributed composition ------------------------
# 2 devices, 2 ragged host shards: the composed out-of-core path must be
# bit-identical to the RESIDENT run on the same grid (the same-grid
# mirror contract; stream excludes quantization, and f32 cross-WIDTH
# identity is shape-lucky per the ISSUE-8 finding, so the cross-width
# leg above stays quantized while this leg pins stream==hbm)
stream_extra = {"use_quantized_grad": False, "enable_bundle": False,
                "stream_shard_rows": 3100}   # 6001 rows -> 2 ragged shards
bs = train_grid("2x1", "stream", stream_extra)
lr = bs._booster.learner
assert isinstance(lr, Fused2DTreeLearner) and lr.residency == "stream", (
    type(lr).__name__, getattr(lr, "residency", None))
assert lr.sdata.num_shards == 2 and lr.sdata.shards[-1].shape[0] == 2901
bh = train_grid("2x1", "hbm", stream_extra)
if bs.model_to_string().split("end of trees")[0] \
        != bh.model_to_string().split("end of trees")[0]:
    print("MCGATE_FAIL stream x distributed: composed 2-device stream "
          "trees diverged from the resident run on the same grid")
    sys.exit(1)
tels = bs._booster.telemetry
bads = [(r["iter"], r["compiles"]["total"]) for r in tels.records
        if r.get("iter", 0) >= WARMUP
        and (r.get("compiles") or {}).get("total", 0)]
if bads:
    print("MCGATE_FAIL steady-state recompiles in the composed stream x "
          "distributed arm: " + json.dumps(bads))
    sys.exit(1)
phases = set()
for r in tels.records:
    phases.update((r.get("phases") or {}).keys())
if {"h2d_prefetch", "chunk_wait"} - phases:
    print("MCGATE_FAIL ring phases missing from the composed stream arm: "
          + json.dumps(sorted({"h2d_prefetch", "chunk_wait"} - phases)))
    sys.exit(1)

print("MCGATE_" + "OK 8-device fused data-parallel AND 2x4 fused 2-D "
      "bit-identical to 1-device serial (quantized), composed stream x "
      "distributed bit-identical to resident on 2 ragged shards, zero "
      "steady compiles, sidecar mesh fields " + json.dumps(mesh))
"""


def main() -> int:
    env = dict(os.environ)
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if not f.startswith("--xla_force_host_platform"))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD % {"repo": REPO}],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900)
    sys.stderr.write(proc.stderr[-2000:] if proc.stderr else "")
    tail = (proc.stdout or "").strip().splitlines()
    for line in tail[-5:]:
        print(line)
    if proc.returncode != 0 or not any("MCGATE_OK" in ln for ln in tail):
        print("multichip gate: FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
