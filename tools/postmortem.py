#!/usr/bin/env python
"""Postmortem: one merged cross-process timeline from N observability
files.

A fleet incident leaves evidence scattered across processes: each
replica's flight-recorder dump (``serve_flight_dump``, written atomically
on fault/SIGTERM/interval — a SIGKILLed replica leaves its last periodic
dump), each process's span JSONL (``serve_trace_out``, per-record flushed,
torn final line tolerated), and the training run logs. This tool reads any
number of them through the lenient ``obs.events.read_file`` reader and
renders ONE wall-clock-ordered timeline — spans and events from every
process interleaved on the shared epoch clock — plus, per source process,
its LAST recorded span: the thing a dead replica was doing when it died.

Usage::

    python tools/postmortem.py r0.flight r1.flight trace.jsonl
    python tools/postmortem.py --trace <trace_id> dumps/*.flight
    python tools/postmortem.py --json merged.json r*.flight

Exit 0 when every input parsed (truncation is reported, not fatal);
exit 2 when an input was unreadable.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load(paths):
    """[(path, records, truncated)] via the lenient reader. A MISSING or
    unreadable file aborts (a postmortem with silently absent evidence
    is worse than none); a file that exists but is SIGKILL-torn —
    truncated final JSON, garbage bytes, even an empty dump — is
    evidence of the crash itself: its parseable prefix joins the
    timeline and the truncation is reported, never fatal (the
    obs.events.read_file contract, applied to every input)."""
    from lambdagap_tpu.obs.events import read_file
    out = []
    for path in paths:
        try:
            records, truncated = read_file(path)
        except OSError as e:
            print(f"postmortem: cannot read {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
        except ValueError as e:
            # defensively non-fatal: whatever mangling the reader could
            # not absorb still must not take down the merged timeline
            print(f"postmortem: {path} is corrupt beyond recovery ({e}); "
                  "keeping it as an empty, truncated source",
                  file=sys.stderr)
            records, truncated = [], True
        out.append((path, records, truncated))
    return out


def merge(sources, trace_id=None):
    """One time-ordered record list; each record annotated with its
    source file (``_src``) and the recording process when the record
    carries one."""
    merged = []
    for path, records, _trunc in sources:
        src = os.path.basename(path)
        for rec in records:
            if not isinstance(rec, dict):
                continue
            rtype = rec.get("type")
            if rtype == "span":
                if trace_id and rec.get("trace") != trace_id:
                    continue
                t = rec.get("t0", 0.0)
            elif rtype in ("event", "signals"):
                if trace_id:
                    continue
                t = rec.get("time_unix", 0.0)
            else:
                continue                 # run_header/iteration: context only
            if not isinstance(t, (int, float)):
                continue                 # structurally torn record: skip it
            merged.append((float(t), src, rec))
    merged.sort(key=lambda item: item[0])
    return merged


def last_spans(sources):
    """source file -> (proc, last span record) — the dead replica's last
    recorded act."""
    out = {}

    def _num(v):
        return float(v) if isinstance(v, (int, float)) else 0.0

    for path, records, _trunc in sources:
        spans = [r for r in records
                 if isinstance(r, dict) and r.get("type") == "span"]
        if spans:
            last = max(spans, key=lambda s: _num(s.get("t0"))
                       + _num(s.get("dur")))
            out[os.path.basename(path)] = (last.get("proc", "?"), last)
    return out


def render(sources, merged, width=72):
    lines = []
    lines.append("postmortem: merged timeline over "
                 f"{len(sources)} file(s), {len(merged)} record(s)")
    for path, records, trunc in sources:
        n_spans = sum(1 for r in records if r.get("type") == "span")
        n_events = sum(1 for r in records if r.get("type") == "event")
        header = next((r for r in records
                       if r.get("type") == "run_header"), {})
        params = header.get("params")
        reason = params.get("reason", "") if isinstance(params, dict) else ""
        lines.append(
            f"  {os.path.basename(path)}: {n_spans} spans, "
            f"{n_events} events"
            + (f", dump reason={reason}" if reason else "")
            + (" [TRUNCATED final line — writer was killed mid-record]"
               if trunc else ""))
    if not merged:
        lines.append("  (no timeline records)")
        return "\n".join(lines)
    t_base = merged[0][0]
    lines.append(f"  t=0 at epoch {t_base:.6f}")
    lines.append("")
    lines.append(f"{'t (ms)':>10}  {'dur (ms)':>9}  "
                 f"{'proc':<16} {'src':<18} record")
    for t, src, rec in merged:
        off = (t - t_base) * 1e3
        proc = str(rec.get("proc", ""))[:16]
        if rec["type"] == "span":
            # .get defaults throughout: a span that parsed but lost
            # fields to a torn write still renders instead of KeyError-
            # aborting every OTHER process's evidence
            what = str(rec.get("name", "?"))
            attrs = rec.get("attrs") or {}
            if isinstance(attrs, dict) and attrs:
                short = ",".join(f"{k}={v}" for k, v in
                                 sorted(attrs.items()))[:width - len(what)]
                what = f"{what}({short})"
            dur = rec.get("dur", 0.0)
            dur = float(dur) if isinstance(dur, (int, float)) else 0.0
            tid = str(rec.get("trace", ""))[:8]
            lines.append(f"{off:10.2f}  {dur * 1e3:9.2f}  "
                         f"{proc:<16} {src:<18} {what} "
                         f"[trace {tid}]")
        else:
            what = rec.get("event", rec["type"])
            lines.append(f"{off:10.2f}  {'-':>9}  {proc:<16} {src:<18} "
                         f"!{what}")
    lines.append("")
    for src, (proc, span) in sorted(last_spans(sources).items()):
        t0 = span.get("t0", t_base)
        t0 = float(t0) if isinstance(t0, (int, float)) else t_base
        dur = span.get("dur", 0.0)
        dur = float(dur) if isinstance(dur, (int, float)) else 0.0
        lines.append(f"last span of {src} (proc {proc}): "
                     f"{span.get('name', '?')} at t={(t0 - t_base) * 1e3:.2f}ms "
                     f"dur={dur * 1e3:.2f}ms "
                     f"[trace {str(span.get('trace', ''))[:8]}]")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("files", nargs="+",
                    help="flight dumps / span JSONLs / run logs")
    ap.add_argument("--trace", default=None,
                    help="restrict the timeline to one trace id")
    ap.add_argument("--json", default=None,
                    help="also write the merged records as JSON here")
    args = ap.parse_args(argv)
    sources = load(args.files)
    merged = merge(sources, trace_id=args.trace)
    print(render(sources, merged))
    if args.json:
        with open(args.json, "w") as f:
            json.dump([{"t": t, "src": src, **rec}
                       for t, src, rec in merged], f, indent=2,
                      default=str)
        print(f"\nmerged records written to {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
