#!/usr/bin/env python
"""Postmortem: one merged cross-process timeline from N observability
files.

A fleet incident leaves evidence scattered across processes: each
replica's flight-recorder dump (``serve_flight_dump``, written atomically
on fault/SIGTERM/interval — a SIGKILLed replica leaves its last periodic
dump), each process's span JSONL (``serve_trace_out``, per-record flushed,
torn final line tolerated), and the training run logs. This tool reads any
number of them through the lenient ``obs.events.read_file`` reader and
renders ONE wall-clock-ordered timeline — spans and events from every
process interleaved on the shared epoch clock — plus, per source process,
its LAST recorded span: the thing a dead replica was doing when it died.

Usage::

    python tools/postmortem.py r0.flight r1.flight trace.jsonl
    python tools/postmortem.py --trace <trace_id> dumps/*.flight
    python tools/postmortem.py --json merged.json r*.flight

Exit 0 when every input parsed (truncation is reported, not fatal);
exit 2 when an input was unreadable.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load(paths):
    """[(path, records, truncated)] via the lenient reader; unreadable
    files abort (a postmortem with silently missing evidence is worse
    than none)."""
    from lambdagap_tpu.obs.events import read_file
    out = []
    for path in paths:
        try:
            records, truncated = read_file(path)
        except OSError as e:
            print(f"postmortem: cannot read {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
        out.append((path, records, truncated))
    return out


def merge(sources, trace_id=None):
    """One time-ordered record list; each record annotated with its
    source file (``_src``) and the recording process when the record
    carries one."""
    merged = []
    for path, records, _trunc in sources:
        src = os.path.basename(path)
        for rec in records:
            if not isinstance(rec, dict):
                continue
            rtype = rec.get("type")
            if rtype == "span":
                if trace_id and rec.get("trace") != trace_id:
                    continue
                t = rec.get("t0", 0.0)
            elif rtype in ("event", "signals"):
                if trace_id:
                    continue
                t = rec.get("time_unix", 0.0)
            else:
                continue                 # run_header/iteration: context only
            merged.append((float(t), src, rec))
    merged.sort(key=lambda item: item[0])
    return merged


def last_spans(sources):
    """source file -> (proc, last span record) — the dead replica's last
    recorded act."""
    out = {}
    for path, records, _trunc in sources:
        spans = [r for r in records
                 if isinstance(r, dict) and r.get("type") == "span"]
        if spans:
            last = max(spans, key=lambda s: s.get("t0", 0.0)
                       + s.get("dur", 0.0))
            out[os.path.basename(path)] = (last.get("proc", "?"), last)
    return out


def render(sources, merged, width=72):
    lines = []
    lines.append("postmortem: merged timeline over "
                 f"{len(sources)} file(s), {len(merged)} record(s)")
    for path, records, trunc in sources:
        n_spans = sum(1 for r in records if r.get("type") == "span")
        n_events = sum(1 for r in records if r.get("type") == "event")
        header = next((r for r in records
                       if r.get("type") == "run_header"), {})
        reason = header.get("params", {}).get("reason", "")
        lines.append(
            f"  {os.path.basename(path)}: {n_spans} spans, "
            f"{n_events} events"
            + (f", dump reason={reason}" if reason else "")
            + (" [TRUNCATED final line — writer was killed mid-record]"
               if trunc else ""))
    if not merged:
        lines.append("  (no timeline records)")
        return "\n".join(lines)
    t_base = merged[0][0]
    lines.append(f"  t=0 at epoch {t_base:.6f}")
    lines.append("")
    lines.append(f"{'t (ms)':>10}  {'dur (ms)':>9}  "
                 f"{'proc':<16} {'src':<18} record")
    for t, src, rec in merged:
        off = (t - t_base) * 1e3
        proc = str(rec.get("proc", ""))[:16]
        if rec["type"] == "span":
            what = rec["name"]
            attrs = rec.get("attrs") or {}
            if attrs:
                short = ",".join(f"{k}={v}" for k, v in
                                 sorted(attrs.items()))[:width - len(what)]
                what = f"{what}({short})"
            tid = rec.get("trace", "")[:8]
            lines.append(f"{off:10.2f}  {rec['dur'] * 1e3:9.2f}  "
                         f"{proc:<16} {src:<18} {what} "
                         f"[trace {tid}]")
        else:
            what = rec.get("event", rec["type"])
            lines.append(f"{off:10.2f}  {'-':>9}  {proc:<16} {src:<18} "
                         f"!{what}")
    lines.append("")
    for src, (proc, span) in sorted(last_spans(sources).items()):
        off = (span.get("t0", t_base) - t_base) * 1e3
        lines.append(f"last span of {src} (proc {proc}): "
                     f"{span['name']} at t={off:.2f}ms "
                     f"dur={span.get('dur', 0.0) * 1e3:.2f}ms "
                     f"[trace {span.get('trace', '')[:8]}]")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("files", nargs="+",
                    help="flight dumps / span JSONLs / run logs")
    ap.add_argument("--trace", default=None,
                    help="restrict the timeline to one trace id")
    ap.add_argument("--json", default=None,
                    help="also write the merged records as JSON here")
    args = ap.parse_args(argv)
    sources = load(args.files)
    merged = merge(sources, trace_id=args.trace)
    print(render(sources, merged))
    if args.json:
        with open(args.json, "w") as f:
            json.dump([{"t": t, "src": src, **rec}
                       for t, src, rec in merged], f, indent=2,
                      default=str)
        print(f"\nmerged records written to {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
