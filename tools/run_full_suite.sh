#!/bin/bash
cd /root/repo
{
echo "=== G0 pre-test gates: graftlint + docs drift + telemetry $(date)"
# fail-fast: a hazard finding or stale generated doc aborts before any
# test group burns wall-clock (graftlint exits nonzero on non-baselined
# findings; see docs/static-analysis.md). The scan covers the package AND
# the timing surfaces R7 guards (bench*.py, tools/bench_*).
# --max-seconds 2 enforces the ISSUE-10 budget for the whole THREE-pass
# run (semantic index build + transitive effect inference + all rules):
# the gate FAILS if the scan slows past it, so the budget is measured on
# every run, not hoped. The cache is deleted first so the budget measures
# a COLD scan — the warm-cache assertion below covers the cached path.
rm -f .graftlint_cache.json
if ! env LAMBDAGAP_LINT_ONLY=1 \
        python -m lambdagap_tpu.analysis --max-seconds 2 --format json \
        lambdagap_tpu bench.py bench_serve.py tools \
        > /tmp/graftlint_cold.json; then
    cat /tmp/graftlint_cold.json
    echo "FAIL-FAST: graftlint found non-baselined hazards or blew the 2s"
    echo "scan budget (fix findings / suppress with a justification /"
    echo "regenerate the baseline; a slow scan means the index build"
    echo "regressed — profile analysis/core.py)"
    exit 1
fi
# warm-cache re-scan (ISSUE 14): the content-hash cache must replay
# byte-identical findings AND actually hit (cold==warm identity is the
# cache's correctness contract; see docs/static-analysis.md)
if ! env LAMBDAGAP_LINT_ONLY=1 \
        python -m lambdagap_tpu.analysis --format json \
        lambdagap_tpu bench.py bench_serve.py tools \
        > /tmp/graftlint_warm.json; then
    echo "FAIL-FAST: graftlint warm-cache re-scan found findings the cold"
    echo "scan did not (cache corruption or nondeterminism)"
    exit 1
fi
if ! python - <<'PYEOF'
import json, sys
cold = json.load(open("/tmp/graftlint_cold.json"))
warm = json.load(open("/tmp/graftlint_warm.json"))
if not warm.get("cache_hit"):
    sys.exit("warm scan did not hit the cache")
for key in ("findings", "baselined", "stale_baseline_entries"):
    if cold[key] != warm[key]:
        sys.exit(f"cold/warm scan results differ in {key!r}")
print("graftlint warm-cache identity OK")
PYEOF
then
    echo "FAIL-FAST: warm-cache scan is not byte-identical to the cold"
    echo "scan (see docs/static-analysis.md 'Incremental scan cache')"
    exit 1
fi
# graftir gate (ISSUE 17): IR-level contract verification of the lowered
# programs — collective schedules across four virtual grids, transfer-
# freedom, precision discipline, retrace-freedom — plus the seeded-
# violation mutation selftest (proves the checkers still have teeth) and
# the single merged graftlint+graftir SARIF artifact. The per-program
# verdict cache is NOT deleted: an unchanged tree replays warm in
# milliseconds, and the --max-seconds 570 budget fails the gate loudly
# if the cache broke or the scenario inventory outgrew it.
if ! python tools/graftir_gate.py --max-seconds 570 \
        --sarif-out /tmp/static_analysis.sarif; then
    echo "FAIL-FAST: graftir gate failed (a lowered program drifted from"
    echo "its declared IR contract, the mutation suite lost its teeth,"
    echo "or the pass blew its 570s budget; see docs/static-analysis.md"
    echo "'IR contracts')"
    exit 1
fi
# composition-matrix drift (ISSUE 14): docs/capability-matrix.md must
# match the lattice R12 extracts from the current tree
if ! python tools/gen_capability_matrix.py --check; then
    echo "FAIL-FAST: docs/capability-matrix.md is stale; run python"
    echo "tools/gen_capability_matrix.py"
    exit 1
fi
# docs drift, BOTH directions: config.py knobs missing from Parameters.md
# AND Parameters.md rows whose knob config.py no longer declares (the
# doc-side counterpart of graftlint R11)
if ! python tools/gen_params_doc.py --check; then
    echo "FAIL-FAST: docs/Parameters.md is stale; run python tools/gen_params_doc.py"
    exit 1
fi
# telemetry gate (ISSUE 4): short telemetry=true training, JSONL validated
# against the documented schema, zero steady-state recompiles
if ! env JAX_PLATFORMS=cpu python tools/telemetry_gate.py; then
    echo "FAIL-FAST: telemetry gate failed (obs/ run log invalid or a"
    echo "steady-state recompile appeared; see docs/observability.md)"
    exit 1
fi
# stream gate (ISSUE 7): tiny synthetic dataset forced onto 4 host
# shards (ragged tail) must train bit-identical to the resident path
# with zero steady-state recompiles and live h2d_prefetch/chunk_wait
# ring telemetry
if ! env JAX_PLATFORMS=cpu python tools/stream_gate.py; then
    echo "FAIL-FAST: stream gate failed (out-of-core training diverged"
    echo "from the resident path or recompiles/ring telemetry regressed;"
    echo "see docs/performance.md)"
    exit 1
fi
# multichip gate (ISSUE 8): 8 virtual CPU devices — fused data-parallel
# trees must be bit-identical to the 1-device serial learner (quantized
# path: width-invariant integer histogram reduction), zero steady-state
# recompiles, and the snapshot sidecar must carry the mesh/shard fields
# elastic resume reads back
if ! env JAX_PLATFORMS=cpu python tools/multichip_gate.py; then
    echo "FAIL-FAST: multichip gate failed (distributed training diverged"
    echo "from 1-device, recompiled in steady state, or the snapshot"
    echo "sidecar lost its mesh fields; see docs/performance.md)"
    exit 1
fi
# linear gate (ISSUE 11): short fused linear_tree training — zero
# steady-state recompiles (fixed-shape moment accumulation), model text
# carries linear leaves, tensor/scan engine parity on the result, and a
# serve dispatch of the linear model succeeds bit-identically
if ! env JAX_PLATFORMS=cpu python tools/linear_gate.py; then
    echo "FAIL-FAST: linear gate failed (linear-leaf training/predict/serve"
    echo "contract regressed; see docs/linear-trees.md)"
    exit 1
fi
# chaos gate (ISSUE 5): short train under injected gradient NaNs must
# finish with a valid model (guard_nonfinite=skip_tree), and a serve loop
# under injected dispatch failures must shed, degrade, and recover
if ! env JAX_PLATFORMS=cpu python tools/chaos_gate.py; then
    echo "FAIL-FAST: chaos gate failed (guard layer let a fault hang,"
    echo "corrupt, or kill the pipeline; see docs/robustness.md)"
    exit 1
fi
# serve fleet gate (ISSUE 9): a 2-replica loopback fleet under open-loop
# load survives a SIGKILLed replica with zero stranded futures and
# goodput recovering to >= 90% of the pre-fault baseline
if ! env JAX_PLATFORMS=cpu python tools/serve_gate.py; then
    echo "FAIL-FAST: serve gate failed (a replica death stranded a future"
    echo "or goodput never recovered; see docs/serving.md)"
    exit 1
fi
# trace gate (ISSUE 12): one traced request through a 2-replica loopback
# fleet must yield a schema-valid parent-linked span tree tiling the
# client-observed wall, and a SIGKILLed replica must leave a valid
# flight-recorder dump that tools/postmortem.py renders naming its last
# span — with zero stranded futures under tracing
if ! env JAX_PLATFORMS=cpu python tools/trace_gate.py; then
    echo "FAIL-FAST: trace gate failed (the distributed span tree broke,"
    echo "the flight recorder lost the dead replica's history, or tracing"
    echo "stranded a future; see docs/observability.md)"
    exit 1
fi
# autonomics gate (ISSUE 13): the fleet control loop under faults — a
# SIGKILLed replica is respawned on its old port and goodput re-converges
# with zero stranded futures; placement pins the hot model (readmissions
# ~0 under induced eviction pressure); a delta rollout during scale-out
# lands atomically on every live replica or rolls back on all of them
if ! env JAX_PLATFORMS=cpu python tools/autonomics_gate.py; then
    echo "FAIL-FAST: autonomics gate failed (revival, placement, or the"
    echo "atomic delta rollout contract regressed; see docs/robustness.md"
    echo "'Fleet autonomics')"
    exit 1
fi

# compiled-forest gate (ISSUE 16): zero steady-state recompiles at warmed
# bucket shapes; a mixed 3-tenant window through ONE packed executable,
# bit-identical per tenant; replica B admits A's artifact by content hash
# over the wire and the shipped model compiles exactly once fleet-wide
# (corrupt payloads rejected loudly, local-compile fallback)
if ! env JAX_PLATFORMS=cpu python tools/infer_gate.py; then
    echo "FAIL-FAST: infer gate failed (steady-state recompiles, a split"
    echo "packed window, or the fleet one-compile artifact contract"
    echo "regressed; see docs/serving.md 'Compiled forest artifacts')"
    exit 1
fi
# batch-scoring gate: 4 ragged predict_stream windows bit-identical to
# resident predict_raw on the compiled engine, zero steady compiles in
# the pumped pass, the d2h_scores phase live next to h2d_prefetch, and
# the co-tenant throttle backing off under a scripted goodput knee and
# recovering when it clears (docs/performance.md "Batch scoring")
if ! env JAX_PLATFORMS=cpu python tools/batch_gate.py; then
    echo "FAIL-FAST: batch gate failed (out-of-core scoring diverged from"
    echo "resident predict, a window compiled in steady state, an overlap"
    echo "direction went unmeasured, or the co-tenant throttle broke; see"
    echo "docs/performance.md 'Batch scoring')"
    exit 1
fi
# cost-plane gate (ISSUE 19): every learner and predict engine must land
# an analytic ledger entry (a silently unwired capture site fails the
# presence inventory), no hot program may grow its bytes-accessed >10% or
# its peak HBM at all vs tools/cost_budget.json, and the perturbation
# self-test proves the diff still bites
if ! env JAX_PLATFORMS=cpu python tools/cost_gate.py; then
    echo "FAIL-FAST: cost gate failed (a capture site went missing or a"
    echo "hot program's analytic bytes/peak-HBM regressed past the budget;"
    echo "see docs/observability.md 'Cost plane')"
    exit 1
fi
# loop gate (ISSUE 20): every seam of the continuous-learning loop
# SIGKILLed — a torn mid-write candidate must be rejected and resume
# byte-identical; a shadow replica death must not cost live goodput
# (>= 95% of baseline) and the window must restart; a replica killed
# mid-promote must leave the fleet all-base or all-candidate, never
# mixed; an injected delta_swap_fail must roll back fleet-atomically
# with the loop_rollback event and a wire-queryable loop_status
if ! env JAX_PLATFORMS=cpu python tools/loop_gate.py; then
    echo "FAIL-FAST: loop gate failed (the continuous-learning loop tore a"
    echo "candidate into resume, let a shadow touch the live path, or left"
    echo "the fleet mixed-generation; see docs/continuous-learning.md)"
    exit 1
fi
echo "=== G1 $(date)"
python -m pytest tests/test_binning.py tests/test_split_math.py tests/test_efb.py tests/test_capi.py tests/test_fast_predict.py tests/test_predict_tensor.py tests/test_misc_api.py tests/test_graftlint.py tests/test_graftir.py tests/test_costplane.py tests/test_profile.py -q 2>&1 | tail -1
echo "=== G2 $(date)"
python -m pytest tests/test_train.py tests/test_rank.py tests/test_cli_io.py -q 2>&1 | tail -1
echo "=== G3 $(date)"
python -m pytest tests/test_monotone.py tests/test_tree_options.py tests/test_extra_contri.py tests/test_forced_splits.py -q 2>&1 | tail -1
echo "=== G4 $(date)"
python -m pytest tests/test_fused.py tests/test_layout.py tests/test_stream.py tests/test_distributed.py tests/test_quantized.py tests/test_continued.py tests/test_model_io.py tests/test_shap_json.py -q 2>&1 | tail -1
echo "=== G5 $(date)"
python -m pytest tests/test_multiprocess.py tests/test_arrow.py tests/test_sparse_ingest.py tests/test_differential.py tests/test_serve.py tests/test_serve_fleet.py tests/test_serve_stress.py tests/test_infer.py tests/test_predict_stream.py tests/test_shadow.py tests/test_loop.py -q 2>&1 | tail -1
echo "=== G6 full-length consistency $(date)"
LAMBDAGAP_CONSISTENCY_FULL=1 python -m pytest tests/test_consistency.py -q 2>&1 | tail -1
echo "=== DONE $(date)"
} > /tmp/full_suite_result.txt 2>&1
