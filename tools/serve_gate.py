#!/usr/bin/env python
"""Serve-fleet gate (ISSUE 9): kill a replica mid-load, strand nothing.

Run by tools/run_full_suite.sh G0. The scenario a million-user deployment
actually meets:

1. a 2-replica fleet comes up on loopback — two REAL ``task=serve``
   subprocesses behind their socket frontends, driven through the
   health-aware router exactly as a production caller would;
2. an open-loop load round establishes the pre-fault goodput baseline;
3. a second round runs while one replica is SIGKILLed mid-load — the
   hard-death case: no drain, no goodbye, a torn socket with requests in
   flight. EVERY accepted request must still resolve (failover or an
   explicit error; a single hung future fails the gate — R8 at fleet
   scope);
4. a third round on the surviving replica must recover goodput to >= 90%
   of the pre-fault baseline.

Exit 0 on pass; nonzero with a reason on any violation.
"""
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RATE_RPS = 120.0
N_REQUESTS = 240                  # ~2 s per round at RATE_RPS
DEADLINE_MS = 250.0
RECOVERY_FRACTION = 0.90


def fail(msg: str) -> int:
    print(f"SERVE GATE FAIL: {msg}")
    return 1


def train_model(path: str):
    import numpy as np
    import lambdagap_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(1500, 10).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + np.sin(X[:, 2]) > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "tpu_fast_predict_rows": 0},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    b.save_model(path)
    return X


def spawn_replica(model_path: str):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lambdagap_tpu", "task=serve",
         f"input_model={model_path}", "serve_port=0", "verbose=-1",
         "serve_max_delay_ms=1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO, env=env)
    return proc


def await_port(proc, timeout_s: float = 120.0) -> int:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("SERVE_PORT="):
            return int(line.split("=", 1)[1])
    raise RuntimeError("replica never printed SERVE_PORT")


def main() -> int:
    import tempfile
    from lambdagap_tpu.serve import RemoteReplica, Router, run_open_loop

    with tempfile.TemporaryDirectory() as tmp:
        model = os.path.join(tmp, "model.txt")
        X = train_model(model)
        print("serve gate: spawning 2 task=serve replicas on loopback...")
        procs = [spawn_replica(model), spawn_replica(model)]
        try:
            ports = [await_port(p) for p in procs]
            print(f"serve gate: fleet up on ports {ports}")
            router = Router([RemoteReplica(f"r{i}", "127.0.0.1", port)
                             for i, port in enumerate(ports)])

            # round 1: pre-fault baseline
            pre = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                                deadline_ms=DEADLINE_MS, seed=1)
            print(f"serve gate: pre-fault goodput "
                  f"{pre['goodput_rps']:.0f}/{RATE_RPS:.0f} rps offered "
                  f"(ratio {pre['goodput_ratio']:.2f}), counts "
                  f"{pre['counts']}")
            if pre["counts"]["error"]:
                return fail("pre-fault round had unexplained errors")
            if pre["goodput_ratio"] < 0.5:
                return fail("pre-fault goodput below 50% of offered — the "
                            "fleet cannot carry the gate's load; baseline "
                            "meaningless")

            # round 2: SIGKILL replica 0 mid-load
            def killer():
                time.sleep(N_REQUESTS / RATE_RPS * 0.4)
                print("serve gate: SIGKILL replica r0 mid-load")
                procs[0].send_signal(signal.SIGKILL)

            k = threading.Thread(target=killer)
            k.start()
            chaos = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                                  deadline_ms=DEADLINE_MS, seed=2)
            k.join()
            print(f"serve gate: chaos round counts {chaos['counts']}, "
                  f"goodput ratio {chaos['goodput_ratio']:.2f}")
            c = chaos["counts"]
            # good/late partition ok; the disjoint outcomes are these five
            resolved = (c["ok"] + c["rejected"] + c["timeout"]
                        + c["transport"] + c["error"])
            if resolved != N_REQUESTS:
                return fail(f"{N_REQUESTS - resolved} of {N_REQUESTS} "
                            "requests never resolved — a stranded future")
            if c["error"]:
                return fail(f"{c['error']} requests resolved with "
                            "unexplained errors (expected failover or "
                            "explicit shed)")
            snap = router.snapshot()
            if not snap["replicas"]["r0"]["dead"]:
                return fail("router never marked the killed replica dead")
            if snap["replicas"]["r0"]["inflight"]:
                return fail("killed replica still shows in-flight requests")

            # round 3: goodput must recover on the survivor
            post = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                                 deadline_ms=DEADLINE_MS, seed=3)
            print(f"serve gate: post-fault goodput "
                  f"{post['goodput_rps']:.0f} rps "
                  f"(ratio {post['goodput_ratio']:.2f}) vs pre-fault "
                  f"{pre['goodput_rps']:.0f}")
            if post["counts"]["error"]:
                return fail("post-fault round had unexplained errors")
            # compare offered-normalized goodput: the rounds offer the
            # same rate, but Poisson schedule length varies by seed, so
            # raw rps carries schedule noise the ratio does not
            if post["goodput_ratio"] \
                    < RECOVERY_FRACTION * pre["goodput_ratio"]:
                return fail(
                    f"goodput did not recover: ratio "
                    f"{post['goodput_ratio']:.2f} < "
                    f"{RECOVERY_FRACTION:.0%} of pre-fault "
                    f"{pre['goodput_ratio']:.2f}")
            router.close()
            print("serve gate: PASS — zero stranded futures, replica "
                  "death detected, goodput recovered "
                  f"({post['goodput_rps']:.0f}/{pre['goodput_rps']:.0f} "
                  "rps)")
            return 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
