#!/usr/bin/env python
"""Out-of-core stream gate for tools/run_full_suite.sh (ISSUE 7 CI
satellite).

Trains a tiny synthetic dataset twice — ``data_residency=hbm`` and
``data_residency=stream`` forced onto 4 host shards (ragged tail
included) — on the fused learner, and asserts:

1. the streamed trees are byte-identical to the resident trees (the
   stream mode's core contract: same windows, same accumulation order);
2. the stream arm's telemetry shows ZERO steady-state recompiles — the
   pow2 window/bucket shapes must stabilize during warmup, or every
   boosting iteration would pay a fresh XLA compile (the R2-at-runtime
   regression, caught here the same way the telemetry gate catches it for
   the resident program);
3. the ``h2d_prefetch``/``chunk_wait`` ring phases actually appear in the
   stream arm's phase spans (the overlap instrumentation is live, not
   silently skipped).

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = 8
WARMUP = 4
N = 6000
SHARDS = 4


def main() -> int:
    import numpy as np

    import lambdagap_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(N, 10).astype(np.float32)
    y = (X[:, 0] - 0.4 * X[:, 1] + 0.2 * rng.randn(N) > 0
         ).astype(np.float32)
    # deliberately NOT a divisor of N: the last shard must be ragged so
    # the gate exercises the tail-window path
    shard_rows = 1700
    assert N % shard_rows != 0 and -(-N // shard_rows) == SHARDS
    base = {"objective": "binary", "num_leaves": 15, "verbose": -1,
            "tpu_fused_learner": "1", "enable_bundle": False,
            "min_data_in_leaf": 20, "stream_shard_rows": shard_rows,
            # pow2 buckets stabilize within the first trees; anything
            # compiling after WARMUP iterations is a steady-state compile
            "telemetry": True, "telemetry_warmup": WARMUP}

    boosters = {}
    for res in ("hbm", "stream"):
        boosters[res] = lgb.train(
            {**base, "data_residency": res},
            lgb.Dataset(X, label=y, params={**base,
                                            "data_residency": res}),
            num_boost_round=ROUNDS)

    trees = {k: b.model_to_string().split("end of trees")[0]
             for k, b in boosters.items()}
    if trees["stream"] != trees["hbm"]:
        print("stream gate: streamed trees are NOT bit-identical to the "
              "resident trees", file=sys.stderr)
        return 1

    tel = boosters["stream"]._booster.telemetry
    records = list(tel.records)
    steady = [r for r in records
              if r.get("iter", 0) >= WARMUP
              and (r.get("compiles") or {}).get("total", 0)]
    if steady:
        print("stream gate: steady-state recompiles in stream mode: "
              f"{[(r['iter'], r['compiles']['total']) for r in steady]}",
              file=sys.stderr)
        return 1
    phases = set()
    for r in records:
        phases.update((r.get("phases") or {}).keys())
    missing = {"h2d_prefetch", "chunk_wait"} - phases
    if missing:
        print(f"stream gate: ring phases {sorted(missing)} never appeared "
              "in the stream arm's telemetry", file=sys.stderr)
        return 1
    lr = boosters["stream"]._booster.learner
    print(f"stream gate: OK — {ROUNDS} rounds bit-identical across "
          f"{lr.sdata.num_shards} shards (shard_rows={lr.sdata.shard_rows},"
          f" ragged tail {lr.sdata.shards[-1].shape[0]}), zero steady "
          "compiles, ring phases live")
    return 0


if __name__ == "__main__":
    sys.exit(main())
