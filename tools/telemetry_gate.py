#!/usr/bin/env python
"""Telemetry gate for tools/run_full_suite.sh (ISSUE 4 CI satellite).

Runs a short ``telemetry=true, telemetry_out=...`` training, validates the
emitted JSONL run log against the documented schema
(``lambdagap_tpu.obs.events.validate_file``), and checks the record
inventory: one run_header, one iteration record per boosting round, every
iteration carrying phase spans that tile its wall, zero steady-state
recompiles (a steady compile in this shape-stable config is exactly the
R2-at-runtime regression the watchdog exists to catch).

Exit 0 on success, 1 with a diagnostic on any violation.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = 6


def main() -> int:
    import numpy as np

    import lambdagap_tpu as lgb
    from lambdagap_tpu.obs import events

    out = os.path.join(tempfile.mkdtemp(prefix="lambdagap_gate_"),
                       "run.jsonl")
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 16).astype(np.float32)
    y = (X[:, 0] - 0.4 * X[:, 1] + 0.2 * rng.randn(2000) > 0
         ).astype(np.float32)
    # the fused whole-tree learner is the shape-stable program (one
    # executable per tree shape); the host-orchestrated serial learner
    # legitimately compiles new power-of-2 pad buckets as leaves shrink,
    # which would make a zero-steady-compile assertion flaky
    booster = lgb.train({"objective": "binary", "num_leaves": 31,
                         "verbose": -1, "telemetry": True,
                         "telemetry_out": out, "tpu_fused_learner": "1"},
                        lgb.Dataset(X, label=y), num_boost_round=ROUNDS)

    errs = events.validate_file(out)
    if errs:
        print("telemetry gate: JSONL schema violations:\n  "
              + "\n  ".join(errs[:20]), file=sys.stderr)
        return 1

    records = [json.loads(ln) for ln in open(out) if ln.strip()]
    iters = [r for r in records if r["type"] == "iteration"]
    if [r["iter"] for r in iters] != list(range(ROUNDS)):
        print(f"telemetry gate: expected iterations 0..{ROUNDS - 1}, got "
              f"{[r['iter'] for r in iters]}", file=sys.stderr)
        return 1
    for r in iters[1:]:
        span = sum(v for k, v in r["phases"].items() if k != "eval")
        if not (0.9 * r["wall_s"] - 1e-3 <= span <= 1.05 * r["wall_s"]
                + 1e-3):
            print(f"telemetry gate: iteration {r['iter']} phase spans "
                  f"({span:.4f}s) do not tile wall ({r['wall_s']:.4f}s)",
                  file=sys.stderr)
            return 1
    steady = sum(r["compiles"]["steady"] for r in iters)
    if steady:
        print(f"telemetry gate: {steady} steady-state recompile(s) in a "
              "shape-stable training config — the R2-at-runtime regression",
              file=sys.stderr)
        return 1
    tel = booster._booster.telemetry
    if tel.iterations != ROUNDS or len(tel.records) != ROUNDS:
        print("telemetry gate: ring buffer lost records", file=sys.stderr)
        return 1
    print(f"telemetry gate: OK ({ROUNDS} iterations, "
          f"{len(records)} JSONL records, 0 steady compiles; {out})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
