#!/usr/bin/env python
"""Trace gate (ISSUE 12): one request, one connected cross-process record.

Run by tools/run_full_suite.sh G0. Two invariants of graftscope v2, on a
REAL 2-replica loopback fleet (``task=serve`` subprocesses behind their
socket frontends, routed exactly as a production caller would):

1. **The span tree tiles the wall.** One traced request through
   router -> frontend -> replica -> batcher -> dispatch must yield a
   schema-valid (``obs.events.validate_file``) parent-linked span tree
   whose spans tile the client-observed latency within tolerance
   (``obs.trace.validate_tree`` — the PR 4 span-sum≈wall discipline,
   across processes).

2. **The dead replica leaves evidence.** SIGKILL a replica mid-open-loop
   load: its periodic flight-recorder dump must be a valid JSONL ring on
   disk (atomic writes mean the last completed dump survives a kill at
   ANY point), ``tools/postmortem.py`` must render the merged timeline
   naming the dead replica's last span — and zero futures may strand
   (the serve_gate invariant preserved under tracing).

Exit 0 on pass; nonzero with a reason on any violation.
"""
import os
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RATE_RPS = 100.0
N_REQUESTS = 200                  # ~2 s of chaos-round load
DEADLINE_MS = 250.0
FLIGHT_INTERVAL_S = 0.3
TREE_TOLERANCE = 0.35             # CPU-container scheduling jitter headroom
TREE_MIN_COVER = 0.5


def fail(msg: str) -> int:
    print(f"TRACE GATE FAIL: {msg}")
    return 1


def train_model(path: str):
    import numpy as np
    import lambdagap_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(1200, 10).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float32)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbose": -1,
                   "tpu_fast_predict_rows": 0},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    b.save_model(path)
    return X


def spawn_replica(model_path: str, tmp: str, i: int):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lambdagap_tpu", "task=serve",
         f"input_model={model_path}", "serve_port=0", "verbose=-1",
         "serve_max_delay_ms=1",
         f"serve_trace_out={tmp}/r{i}.trace.jsonl",
         f"serve_flight_dump={tmp}/r{i}.flight",
         f"serve_flight_interval_s={FLIGHT_INTERVAL_S}"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO, env=env)
    return proc


def await_port(proc, timeout_s: float = 120.0) -> int:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("SERVE_PORT="):
            return int(line.split("=", 1)[1])
    raise RuntimeError("replica never printed SERVE_PORT")


def traced_request(router, X) -> str:
    """One traced request through the fleet; records the client root span
    and returns the trace id."""
    from lambdagap_tpu.obs import trace
    ctx = trace.start_trace()
    t0_wall, t0 = time.time(), time.perf_counter()
    fut = router.submit(X[0][None, :], trace=ctx)
    fut.result(30)
    trace.RECORDER.record("client_request", ctx, t0_wall,
                          time.perf_counter() - t0,
                          span_id=ctx.span_id, parent="")
    return ctx.trace_id


def main() -> int:
    import tempfile
    from lambdagap_tpu.obs import trace
    from lambdagap_tpu.obs.events import read_file, validate_file
    from lambdagap_tpu.serve import RemoteReplica, Router, run_open_loop

    with tempfile.TemporaryDirectory() as tmp:
        model = os.path.join(tmp, "model.txt")
        X = train_model(model)
        print("trace gate: spawning 2 traced task=serve replicas...")
        procs = [spawn_replica(model, tmp, i) for i in range(2)]
        trace.RECORDER.configure(ring=65536, proc="gate-client")
        try:
            ports = [await_port(p) for p in procs]
            print(f"trace gate: fleet up on ports {ports}")
            replicas = [RemoteReplica(f"r{i}", "127.0.0.1", port)
                        for i, port in enumerate(ports)]
            router = Router(replicas)

            # warm both replicas' serve paths (and seed both flight
            # rings with spans) with one traced request each
            for r in replicas:
                ctx = trace.start_trace()
                t0w, t0 = time.time(), time.perf_counter()
                r.submit(X[1][None, :], trace=ctx).result(60)
                trace.RECORDER.record("client_request", ctx, t0w,
                                      time.perf_counter() - t0,
                                      span_id=ctx.span_id, parent="")

            # -- invariant 1: the span tree tiles the client wall -------
            tid = traced_request(router, X)
            time.sleep(0.3)              # replicas flush per record; settle
            spans = trace.RECORDER.spans()
            for i in range(2):
                path = os.path.join(tmp, f"r{i}.trace.jsonl")
                errs = validate_file(path)
                if errs:
                    return fail(f"replica {i} span JSONL invalid: "
                                f"{errs[:3]}")
                recs, _trunc = read_file(path)
                spans += [r for r in recs if r.get("type") == "span"]
            mine = [s for s in spans if s.get("trace") == tid]
            names = sorted({s["name"] for s in mine})
            print(f"trace gate: {len(mine)} spans for trace {tid[:8]}: "
                  f"{names}")
            for need in ("client_request", "route", "frontend",
                         "serve_request", "queue_wait", "dispatch"):
                if need not in names:
                    return fail(f"span {need!r} missing from the trace "
                                f"(got {names})")
            errs = trace.validate_tree(mine, tid,
                                       tolerance=TREE_TOLERANCE,
                                       min_cover=TREE_MIN_COVER)
            if errs:
                return fail("span tree does not tile the client wall: "
                            + "; ".join(errs))
            print("trace gate: span tree parent-linked + tiles the "
                  "client-observed wall")

            # -- invariant 2: SIGKILL leaves a valid flight dump --------
            time.sleep(2 * FLIGHT_INTERVAL_S)   # ensure a periodic dump
            dead_pid = procs[0].pid

            def killer():
                time.sleep(N_REQUESTS / RATE_RPS * 0.4)
                print("trace gate: SIGKILL replica r0 mid-load")
                procs[0].send_signal(signal.SIGKILL)

            k = threading.Thread(target=killer)
            k.start()
            chaos = run_open_loop(router.submit, X, RATE_RPS, N_REQUESTS,
                                  deadline_ms=DEADLINE_MS, seed=2)
            k.join()
            c = chaos["counts"]
            resolved = (c["ok"] + c["rejected"] + c["timeout"]
                        + c["transport"] + c["error"])
            if resolved != N_REQUESTS:
                return fail(f"{N_REQUESTS - resolved} of {N_REQUESTS} "
                            "requests never resolved under tracing — a "
                            "stranded future")
            if c["error"]:
                return fail(f"{c['error']} unexplained request errors")
            print(f"trace gate: chaos round resolved {resolved}/"
                  f"{N_REQUESTS} (counts {c})")

            dump0 = os.path.join(tmp, "r0.flight")
            if not os.path.exists(dump0):
                return fail("killed replica left no flight-recorder dump "
                            f"({dump0}); periodic dumps did not run")
            errs = validate_file(dump0)
            if errs:
                return fail(f"flight dump of the killed replica is not "
                            f"schema-valid: {errs[:3]}")
            recs, _trunc = read_file(dump0)
            if not any(r.get("type") == "span" for r in recs):
                return fail("killed replica's flight dump holds no spans")

            # postmortem renders the merged timeline naming the dead
            # replica's last span
            pm = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools",
                                              "postmortem.py"),
                 dump0, os.path.join(tmp, "r1.flight"),
                 os.path.join(tmp, "r0.trace.jsonl")],
                capture_output=True, text=True, cwd=REPO,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            if pm.returncode != 0:
                return fail(f"postmortem exited {pm.returncode}: "
                            f"{pm.stderr[-300:]}")
            out = pm.stdout
            if f"serve:{dead_pid}" not in out:
                return fail("postmortem timeline never names the dead "
                            f"replica's process serve:{dead_pid}")
            if "last span of r0.flight" not in out:
                return fail("postmortem did not render the dead "
                            "replica's last span")
            last_line = next(ln for ln in out.splitlines()
                             if ln.startswith("last span of r0.flight"))
            print(f"trace gate: postmortem renders the merged timeline — "
                  f"{last_line}")
            router.close()
            print("trace gate: PASS — connected trace tiles the wall, "
                  "SIGKILLed replica left a valid flight dump, zero "
                  "stranded futures")
            return 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()


if __name__ == "__main__":
    raise SystemExit(main())
